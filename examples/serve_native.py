"""Native C++ serving recipe — export a trained model and run it through
the embedded predictor (no JAX at serving time).

The pipeline (reference: ``save_inference_model`` + ``inference/api``):
  1. fold BN into conv weights (``transpiler.inference.fuse_batch_norm``) —
     export-time identity elimination then removes all BN arithmetic;
  2. ``save_native_model`` traces eval-mode apply, bakes weights in as
     constants, and runs the program through the generic pass pipeline
     (copy-prop, CSE, conv-epilogue fusion, DCE — ``native/passes.py``);
  3. ``NativePredictor`` loads program.txt + weights.bin and interprets
     them with the register-blocked GEMM microkernel (runtime AVX2/AVX-512
     dispatch), cached packed weights, and fused conv epilogues.

Measured on one core of this container: ResNet-50 bs16 = 5.5 img/s
kernel-only and 7.1 img/s with this BN-fold recipe — 102% / 132% of the
reference's MKL-DNN per-core anchor (IntelOptimizedPaddle.md).

    python examples/serve_native.py
"""
import functools
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.models.resnet import resnet_imagenet  # noqa: E402
from paddle_tpu.native import NativePredictor  # noqa: E402
from paddle_tpu.native.export import save_native_model  # noqa: E402


def main():
    net = pt.build(functools.partial(resnet_imagenet, class_dim=102, depth=18))
    x = np.random.RandomState(0).rand(4, 224, 224, 3).astype(np.float32)
    variables = net.init(0, x)

    # 1. the serving transform: BN -> conv weights
    variables = pt.transpiler.inference.fuse_batch_norm(variables)

    with tempfile.TemporaryDirectory() as td:
        # 2. export (program.txt + weights.bin after the pass pipeline)
        save_native_model(net, variables, [x], td)

        # 3. serve
        pred = NativePredictor(td)
        logits = pred.run(x)[0]  # first call packs const weights
        t0 = time.perf_counter()
        logits = pred.run(x)[0]
        dt = time.perf_counter() - t0
        print(f"resnet18 bs{x.shape[0]}: {x.shape[0] / dt:.2f} img/s "
              f"(native, {os.cpu_count()} cores)")
        print("top-1:", logits.argmax(axis=-1))

        # parity vs the jax eval path
        ref, _ = net.apply(variables, x, is_train=False)
        np.testing.assert_allclose(logits, np.asarray(ref), rtol=2e-3, atol=2e-4)
        print("matches jax eval forward")


if __name__ == "__main__":
    main()

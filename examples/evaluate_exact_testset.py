"""Exact test-set evaluation with uneven final batches — every sample
counts exactly once on a device mesh.

Static TPU shapes forbid ragged shards, so `DataParallel.pad_batch` pads
the final partial batch to the shard multiple (repeating the last real
row) and `Trainer.evaluate` threads the validity mask into a per-sample
metric: the reported accuracy is over EXACTLY N test samples, matching the
reference's data_balance guarantee (data_balance_op_handle.cc:154).

Data: REAL bundled UCI handwritten digits (dataset/digits.py — zero
egress), 359 test samples: with the default 8 virtual devices that is
2 x 128 + a ragged 103-row final batch (the mesh size follows
len(jax.devices()) — a preset XLA_FLAGS overrides the 8-device default).

Run: python examples/evaluate_exact_testset.py          # default backend
     python examples/evaluate_exact_testset.py --cpu    # force CPU (~10s)

Pass --cpu on hosts whose TPU platform is registered but unreachable —
backend init would otherwise block indefinitely (JAX_PLATFORMS env can't
override a sitecustomize that already configured jax; the config update
below can, because backends initialize lazily).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nets, reader
from paddle_tpu.dataset import digits
from paddle_tpu.parallel import make_mesh
from paddle_tpu.trainer import Trainer


def net(img, label):
    img = img.reshape(img.shape[0], 28, 28, 1)
    conv = nets.simple_img_conv_pool(
        img, num_filters=16, filter_size=5, pool_size=2, pool_stride=2, act="relu")
    logits = pt.layers.fc(conv.reshape(img.shape[0], -1), size=10, name="clf")
    loss = pt.layers.softmax_with_cross_entropy(logits, label).mean()
    return loss, logits


def batches(split_reader, bs, drop_last):
    r = reader.stack_batch(
        lambda: ((im, np.int64(lb)) for im, lb in split_reader()), bs,
        drop_last=drop_last,
    )
    return lambda: ((x.astype(np.float32), y.reshape(-1, 1)) for x, y in r())


def main():
    n_dev = len(jax.devices())
    tr = Trainer(
        lambda: pt.build(net, name="digits_net"),
        lambda: pt.optimizer.Adam(learning_rate=1e-3),
        parallel=True,
        parallel_kwargs=dict(mesh=make_mesh(data=n_dev)),
    )
    # train batches must divide the mesh; eval batches may be ragged
    tr.train(num_epochs=4, reader=batches(digits.train_as_mnist(), 64, True))

    test_n = sum(1 for _ in digits.test_as_mnist()())
    acc = tr.evaluate(
        batches(digits.test_as_mnist(), 128, False),  # final batch is ragged
        lambda out, x, y: (np.asarray(jax.numpy.argmax(out[1], -1))
                           == np.asarray(y)[:, 0]),
    )
    print(f"test accuracy over exactly {test_n} samples "
          f"({n_dev}-device mesh, ragged final batch): {acc:.4f}")


if __name__ == "__main__":
    main()

"""Continuous-batching autoregressive serving: mixed-length generation
requests share a paged KV cache, with iteration-level admission — a
finished request's slot refills on the very next decode step instead of
idling until the slowest member of a static batch drains. A draft model
speculates `spec_tokens` tokens per iteration (verified token-exactly in
one target pass), and the radix prefix cache lets requests sharing a
system prompt skip its prefill entirely.

Run: python examples/serve_decode.py [--cpu]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    # hosts whose TPU platform is registered but unreachable hang at
    # backend init; lazy backends make this config update effective
    jax.config.update("jax_platforms", "cpu")
import numpy as np

from paddle_tpu import models
from paddle_tpu.serving import DecodeConfig, DecodeEngine

# a tiny LM stands in for a trained checkpoint; the draft would normally
# be a distilled/smaller checkpoint sharing the target's tokenizer —
# here the target drafts for itself (acceptance stays high, and the
# output is token-exact no matter how good or bad the draft is)
spec = models.get_model("transformer_lm", seq_len=128, vocab=256,
                        d_model=64, d_inner=128, num_heads=4, n_layers=2)
cfg = spec.extra["cfg"]
rng = np.random.RandomState(0)
variables = spec.model.init(0, *spec.synth_batch(2, rng))

engine = DecodeEngine(
    variables, cfg,
    decode=DecodeConfig(
        max_slots=4,         # concurrent sequences per decode step
        page_size=16,        # tokens per KV page (HBM granularity)
        max_context=128,     # prompt + generation budget per sequence
        prefill_chunk=16,    # prompts absorbed in fixed-shape chunks
        spec_tokens=4,       # drafted tokens per verify iteration
        prefix_cache=True,   # radix tree over already-prefilled pages
    ),
    draft_variables=variables,  # swap in a smaller LM (same vocab)
    draft_cfg=cfg,
)

# submit a mixed-length burst sharing a 32-token "system prompt": after
# the first request prefills it, every later request adopts those KV
# pages from the radix tree instead of recomputing them
system_prompt = rng.randint(1, 256, size=(32,))
handles = []
for i in range(8):
    tail = rng.randint(1, 256, size=(int(rng.randint(4, 24)),))
    prompt = np.concatenate([system_prompt, tail])
    max_new = int(rng.randint(8, 48))
    handles.append((i, max_new, engine.submit(prompt, max_new)))

for i, max_new, h in handles:
    out = h.result(timeout=300)
    print(f"req {i}: asked {max_new:2d} tokens -> got {len(out.tokens):2d} "
          f"({out.finish_reason}, {out.n_preemptions} preemptions)")

snap = engine.metrics.snapshot()
print(f"steps={snap['steps_total']} tokens={snap['tokens_total']} "
      f"mean tokens/step={snap['mean_step_occupancy']:.2f} "
      f"(of {4} slots)")
print(f"speculation: {snap['verify_steps_total']} verify steps, "
      f"accept rate {snap['spec_accept_rate']:.2f}, "
      f"{engine.metrics.accepted_tokens_per_verify_step():.2f} "
      "accepted tokens/verify step")
print(f"prefix cache: {snap['prefix_hit_tokens_total']} prompt tokens "
      f"served from the tree "
      f"({engine.metrics.prefix_saved_frac():.0%} of all prompt tokens), "
      f"{snap['cow_copies_total']} copy-on-write page copies")
print(f"decode step executables: {engine.decode_step_cache_size()} "
      f"verify: {engine.verify_step_cache_size()} "
      "(compiled once; admission never recompiles)")
engine.close()

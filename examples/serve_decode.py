"""Continuous-batching autoregressive serving: mixed-length generation
requests share a paged KV cache, with iteration-level admission — a
finished request's slot refills on the very next decode step instead of
idling until the slowest member of a static batch drains.

Run: python examples/serve_decode.py [--cpu]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    # hosts whose TPU platform is registered but unreachable hang at
    # backend init; lazy backends make this config update effective
    jax.config.update("jax_platforms", "cpu")
import numpy as np

from paddle_tpu import models
from paddle_tpu.serving import DecodeConfig, DecodeEngine

# a tiny LM stands in for a trained checkpoint
spec = models.get_model("transformer_lm", seq_len=128, vocab=256,
                        d_model=64, d_inner=128, num_heads=4, n_layers=2)
cfg = spec.extra["cfg"]
rng = np.random.RandomState(0)
variables = spec.model.init(0, *spec.synth_batch(2, rng))

engine = DecodeEngine(
    variables, cfg,
    decode=DecodeConfig(
        max_slots=4,         # concurrent sequences per decode step
        page_size=16,        # tokens per KV page (HBM granularity)
        max_context=128,     # prompt + generation budget per sequence
        prefill_chunk=16,    # prompts absorbed in fixed-shape chunks
    ),
)

# submit a mixed-length burst: short and long requests coexist in the
# same decode iterations, no padding to a common shape anywhere
handles = []
for i in range(8):
    prompt = rng.randint(1, 256, size=(int(rng.randint(4, 24)),))
    max_new = int(rng.randint(8, 48))
    handles.append((i, max_new, engine.submit(prompt, max_new)))

for i, max_new, h in handles:
    out = h.result(timeout=300)
    print(f"req {i}: asked {max_new:2d} tokens -> got {len(out.tokens):2d} "
          f"({out.finish_reason}, {out.n_preemptions} preemptions)")

snap = engine.metrics.snapshot()
print(f"steps={snap['steps_total']} tokens={snap['tokens_total']} "
      f"mean tokens/step={snap['mean_step_occupancy']:.2f} "
      f"(of {4} slots)")
print(f"decode step executables: {engine.decode_step_cache_size()} "
      "(compiled once; admission never recompiles)")
engine.close()

"""Serve a trained model from pure C++ via the native predictor, with
int8 weight-only quantization (~4x smaller artifact).

This path fits fixed-shape (single forward pass) inference. For
autoregressive generation, use the continuous-batching decode engine
instead — see examples/serve_decode.py (paged KV cache, iteration-level
admission, no per-shape recompiles).

Run: python examples/serve_quantized.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if "--cpu" in __import__("sys").argv:
    # hosts whose TPU platform is registered but unreachable hang at
    # backend init; lazy backends make this config update effective
    jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as pt
from paddle_tpu.native import NativePredictor
from paddle_tpu.native.export import save_native_model

def train_net(x, y):
    h = pt.layers.fc(x, size=64, act="relu")
    logits = pt.layers.fc(h, size=4)
    return pt.layers.softmax_with_cross_entropy(logits, y).mean()

def serve_net(x):  # same layer order => same parameter names
    h = pt.layers.fc(x, size=64, act="relu")
    return pt.layers.fc(h, size=4)

model = pt.build(train_net)
rng = np.random.RandomState(0)
x = rng.randn(128, 16).astype(np.float32)
y = rng.randint(0, 4, (128, 1))
variables = model.init(0, x, y)
opt = pt.optimizer.Adam(learning_rate=1e-2)
opt_state = opt.create_state(variables.params)
step = jax.jit(opt.minimize(model))
for _ in range(50):
    out = step(variables, opt_state, x, y)
    variables, opt_state = out.variables, out.opt_state

serve_model = pt.build(serve_net)
save_native_model(serve_model, variables, [x], "/tmp/quant_model", quantize_int8=True)
pred = NativePredictor("/tmp/quant_model")   # pure C++ from here on
(logits,) = pred.run(x)
print("C++ int8 predictions:", logits.argmax(1)[:16].tolist())
pred.close()

"""Train the decoder-only LM with the TPU-native fast path: bf16 MXU
compute + Pallas flash attention (fused backward, causal block skipping),
gradient accumulation, AdamW with warmup-cosine schedule, remat — then
decode with the cached generate().

Run: python examples/train_lm_flash.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if "--cpu" in __import__("sys").argv:
    # hosts whose TPU platform is registered but unreachable hang at
    # backend init; lazy backends make this config update effective
    jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu.models import transformer_lm

pt.core.config.set_flags(use_bf16_compute=True, use_flash_attention=True)

spec = models.get_model(
    "transformer_lm", seq_len=256, vocab=8000, d_model=256, d_inner=1024,
    num_heads=8, n_layers=4, remat=True,
)
rng = np.random.RandomState(0)
batch = spec.synth_batch(16, rng)
variables = spec.model.init(0, *batch)
sched = pt.lr_scheduler.LinearWarmup(
    pt.lr_scheduler.CosineDecay(3e-4, decay_steps=1000), warmup_steps=50)
opt = pt.optimizer.AdamW(learning_rate=sched, weight_decay=0.01)
opt_state = opt.create_state(variables.params)
step = jax.jit(opt.minimize(spec.model, accum_steps=4), donate_argnums=(0, 1))

for i in range(20):
    out = step(variables, opt_state, *batch, rng=jax.random.PRNGKey(i))
    variables, opt_state = out.variables, out.opt_state
    if i % 5 == 0:
        print(f"step {i}: loss={float(out.loss):.4f}")

prompt = np.random.RandomState(1).randint(1, 8000, (2, 16)).astype(np.int32)
tokens = transformer_lm.generate(
    variables, jax.numpy.asarray(prompt), max_new_tokens=32, cfg=spec.extra["cfg"])
print("generated:", np.asarray(tokens)[0].tolist())

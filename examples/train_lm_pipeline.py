"""Train the LM pipeline-parallel: layer groups as pipe stages.

``transformer_lm(pipe_mesh=mesh)`` splits the stack into contiguous layer
groups, one per device along the ``pipe`` mesh axis; microbatch
activations flow stage-to-stage through the GPipe ppermute schedule
(``parallel/pipeline.py``), composing with data parallelism on a joint
pipe x data mesh. ``remat=True`` gives the 1F1B memory profile.

Run on the 8-device virtual CPU mesh:

    python examples/train_lm_pipeline.py
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if not os.environ.get("PT_EXAMPLE_TPU"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if not os.environ.get("PT_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from paddle_tpu import models  # noqa: E402
from paddle_tpu.parallel import DataParallel  # noqa: E402
from paddle_tpu.parallel.mesh import make_mesh  # noqa: E402


def main():
    mesh = make_mesh(pipe=2, data=4)
    spec = models.get_model(
        "transformer_lm", seq_len=64, vocab=512, d_model=64, d_inner=128,
        num_heads=4, n_layers=4, max_len=64,
        pipe_mesh=mesh, pipe_n_micro=4,
        attn_dropout=0.0, relu_dropout=0.0, residual_dropout=0.0,
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 512, size=(16, 64)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)  # memorize next-token on a fixed batch

    trainer = DataParallel(
        spec.model, spec.optimizer(), mesh=mesh,
        batch_specs=[P("data"), P("data")], donate=False,
    )
    v, o = trainer.init(0, ids, labels)
    print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}: "
          f"{spec.extra['cfg']['n_layers']} layers -> 2 stages, 4 microbatches")
    for step in range(1, 151):
        out = trainer.step(v, o, *trainer.put_batch(ids, labels))
        v, o = out.variables, out.opt_state
        if step % 30 == 0 or step == 1:
            print(f"step {step}: loss {float(out.loss):.4f}")
    assert float(out.loss) < 3.0, float(out.loss)
    print("pipeline-parallel memorization OK")


if __name__ == "__main__":
    main()

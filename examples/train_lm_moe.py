"""Train a mixture-of-experts LM with expert parallelism.

The FFN in every block is an expert-parallel MoE (Switch router by
default): expert weights shard over the ``expert`` mesh axis, tokens
all-to-all to their experts and back, and the router's load-balance aux
loss joins the training loss. Composes with data parallelism (and, on a
joint mesh, with ring-attention sequence parallelism — see
tests/test_lm_moe.py).

Run on the 8-device virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_lm_moe.py
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if not os.environ.get("PT_EXAMPLE_TPU"):
    # APPEND to any existing XLA_FLAGS — setdefault would silently skip the
    # device-count flag and make_mesh would then fail on 1 CPU device
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if not os.environ.get("PT_EXAMPLE_TPU"):
    # default to the virtual CPU mesh (the tunnel is usually down);
    # PT_EXAMPLE_TPU=1 runs on the real backend instead
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from paddle_tpu import models  # noqa: E402
from paddle_tpu.parallel import DataParallel  # noqa: E402
from paddle_tpu.parallel.mesh import make_mesh  # noqa: E402


def main():
    mesh = make_mesh(expert=4, data=2)
    spec = models.get_model(
        "transformer_lm", seq_len=64, vocab=512, d_model=64, d_inner=128,
        num_heads=4, n_layers=2, max_len=64,
        moe_experts=4, moe_router="top1", moe_aux_weight=0.01,
        scan_layers=True,
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 512, size=(8, 64)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)  # memorize next-token on a fixed batch

    trainer = DataParallel(
        spec.model, spec.optimizer(), mesh=mesh,
        batch_specs=[P("data"), P("data")], donate=False,
    )
    v, o = trainer.init(0, ids, labels)
    n_expert_params = sum(
        np.prod(p.shape) for k, p in v.params.items() if "moe_ffn" in k
    )
    print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"{n_expert_params:,} expert params")
    for step in range(1, 201):
        out = trainer.step(v, o, *trainer.put_batch(ids, labels))
        v, o = out.variables, out.opt_state
        if step % 40 == 0 or step == 1:
            print(f"step {step}: loss {float(out.loss):.4f}")
    assert float(out.loss) < 2.0, float(out.loss)
    print("memorization OK (loss includes the router aux term)")


if __name__ == "__main__":
    main()

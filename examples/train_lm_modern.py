"""Train the modern decoder stack: RoPE + grouped-query attention +
SwiGLU FFN + sliding-window attention, with ZeRO-1 optimizer-state
sharding and prefetched input on a data-parallel mesh.

Run: python examples/train_lm_modern.py            (single chip / CPU)
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         JAX_PLATFORMS=cpu python examples/train_lm_modern.py   (8-dev mesh)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if "--cpu" in __import__("sys").argv:
    # hosts whose TPU platform is registered but unreachable hang at
    # backend init; lazy backends make this config update effective
    jax.config.update("jax_platforms", "cpu")
import numpy as np

from paddle_tpu import models
from paddle_tpu.parallel import DataParallel, make_mesh

spec = models.get_model(
    "transformer_lm",
    seq_len=256,
    vocab=2048,
    d_model=256,
    d_inner=512,
    num_heads=8,
    num_kv_heads=2,          # GQA: 4 query heads share each kv head
    pos_encoding="rope",     # rotary embeddings at the attention rotation
    ffn_activation="swiglu",
    attention_window=128,    # sliding window: O(T*W) attention
    n_layers=2,
)

dp = DataParallel(
    spec.model, spec.optimizer(),
    mesh=make_mesh(data=-1),
    zero_shard_optimizer=True,  # Adam moments sharded over the data axis
)
rng = np.random.RandomState(0)
batch = spec.synth_batch(8 * dp.num_devices, rng)
variables, opt_state = dp.init(0, *batch)

for step in range(10):
    out = dp.step(variables, opt_state, *batch, rng=jax.random.PRNGKey(step))
    variables, opt_state = out.variables, out.opt_state
    print(f"step {step}: loss {float(out.loss):.4f}")

"""Ragged long-context LM training: seq_lens + sliding window through the
flash ring, sequence-parallel over a seq mesh axis.

The reference's variable-length story was LoD tensors threaded through every
op (``paddle/fluid/framework/lod_tensor.h:60-110``); here ragged batches
travel as a [B] ``seq_lens`` vector — attention masks padded keys
STRUCTURALLY inside the fused flash kernels (global-position kv_len bounds,
so fully-padded tail blocks are skipped, not computed-and-masked), and the
loss averages real targets only. This composes with ring sequence
parallelism and sliding-window attention; run it on the 8-device CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_lm_ragged.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

# default to the virtual CPU mesh: probing the TPU backend first would hang
# whenever the tunnel is down. Set PT_EXAMPLE_TPU=1 to run on the chip.
if not os.environ.get("PT_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu.parallel.mesh import make_mesh


def main():
    # the fused kernels only pay off on real hardware; the CPU mesh runs the
    # (numerically identical) composed ring so the demo stays quick
    pt.core.config.set_flags(
        use_flash_attention=jax.devices()[0].platform == "tpu"
    )
    mesh = make_mesh(seq=4, data=2)
    spec = models.get_model(
        "transformer_lm", ring_mesh=mesh, seq_len=256, vocab=512,
        d_model=64, d_inner=128, num_heads=4, n_layers=2,
        attention_window=64,
    )
    rng = np.random.RandomState(0)
    bs, T = 8, 256
    ids = rng.randint(1, 512, size=(bs, T)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    seq_lens = rng.randint(T // 4, T + 1, size=(bs,)).astype(np.int32)
    for b in range(bs):  # zero the pad tail like a real tokenizer batch
        ids[b, seq_lens[b]:] = 0
        labels[b, seq_lens[b]:] = 0

    variables = spec.model.init(0, ids, labels, seq_lens)
    opt = spec.optimizer()
    opt_state = opt.create_state(variables.params)
    step = jax.jit(opt.minimize(spec.model))
    for s in range(20):
        out = step(variables, opt_state, ids, labels, seq_lens,
                   rng=jax.random.PRNGKey(s))
        variables, opt_state = out.variables, out.opt_state
        if s % 5 == 0 or s == 19:
            print(f"step {s:3d}  masked loss {float(out.loss):.4f}")


if __name__ == "__main__":
    main()

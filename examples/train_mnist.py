"""Train an MNIST classifier end-to-end — the minimal paddle_tpu workflow:
build -> init -> minimize -> Executor-style loop -> save for serving.

Run: python examples/train_mnist.py  (CPU or TPU; ~30s on CPU)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if "--cpu" in __import__("sys").argv:
    # hosts whose TPU platform is registered but unreachable hang at
    # backend init; lazy backends make this config update effective
    jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as pt
from paddle_tpu import dataset, nets, reader


def net(img, label):
    img = img.reshape(img.shape[0], 28, 28, 1)
    conv = nets.simple_img_conv_pool(
        img, num_filters=16, filter_size=3, pool_size=2, pool_stride=2, act="relu")
    logits = pt.layers.fc(conv.reshape(img.shape[0], -1), size=10)
    loss = pt.layers.softmax_with_cross_entropy(logits, label).mean()
    acc = pt.layers.accuracy(logits, label)
    return loss, acc


def main():
    model = pt.build(net)
    batches = reader.stack_batch(dataset.mnist.train(), 64)
    first = next(iter(batches()))
    variables = model.init(0, *first)
    opt = pt.optimizer.Adam(learning_rate=1e-3)
    opt_state = opt.create_state(variables.params)
    step = jax.jit(opt.minimize(model), donate_argnums=(0, 1))

    for epoch in range(2):
        for i, batch in enumerate(batches()):
            out = step(variables, opt_state, *[np.asarray(b) for b in batch])
            variables, opt_state = out.variables, out.opt_state
            if i % 20 == 0:
                print(f"epoch {epoch} step {i}: loss={float(out.loss):.4f}")

    # export for serving (StableHLO; native=True adds the C++ predictor artifact)
    def infer(img):
        img = img.reshape(img.shape[0], 28, 28, 1)
        conv = nets.simple_img_conv_pool(
            img, num_filters=16, filter_size=3, pool_size=2, pool_stride=2, act="relu")
        return pt.layers.fc(conv.reshape(img.shape[0], -1), size=10)

    infer_model = pt.build(infer)
    pt.io.save_inference_model("/tmp/mnist_model", infer_model, variables, [first[0]], native=True)
    print("saved inference model to /tmp/mnist_model")


if __name__ == "__main__":
    main()

"""Quickshot harvest: the FIRST thing a chip window produces.

VERDICT r4 #1: four rounds of flaky tunnel produced zero TPU numbers, so the
two numbers the north star actually needs — ResNet-50 train img/s and the
MFU-representative LM's MFU — must land within the first ~120 seconds of
backend availability, before the longer smoke/bench/tune chain gets a chance
to be interrupted. This script does exactly two measurements, writes
``BENCH_QUICK_TPU.json`` incrementally after each, and stamps every phase
(spec build / init / compile / measure) with elapsed-since-start so the
committed artifact doubles as a time-to-first-number log.

Cost levers (why <2 min is plausible on a warm window):
- persistent compile cache (``.jax_cache``): recompiles from a dropped
  window are cache hits on the next one;
- ``scan_layers=True`` on the LM: one traced layer body, one Mosaic flash
  compile instead of 12;
- warmup=1, iters=3: a throughput estimate, not the final number — the full
  ``bench.py`` sweep refines it later in the chain.

Reference metric discipline: examples/sec as in
``benchmark/fluid/fluid_benchmark.py:295-301``.

Dry-run mode (no chip): ``PT_QUICK_FORCE_CPU=1`` runs the same chain on the
CPU backend with the same configs (override batch with
``PT_QUICK_RESNET_BS``) and writes ``.harvest/quickshot_dryrun.json`` —
committed as the proof-of-ordering log when no window opens.
"""
from __future__ import annotations

import json
import os
import sys
import time

_T0 = time.monotonic()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

import _stall_watchdog  # noqa: E402  (before the first jax import)

# 600s default: the stall budget must cover the LONGEST silent stretch —
# a cold tunnel compile of the scanned flash body gives no progress signal
# (only _mark() refreshes the stamp). The probe already passed seconds
# before this script starts, so a longer budget costs nothing unless the
# tunnel dies mid-run, and the watcher re-probes right after.
_PROGRESS = _stall_watchdog.install("QUICKSHOT", "PT_QUICK_STALL_S", 600)

_FORCE_CPU = bool(os.environ.get("PT_QUICK_FORCE_CPU"))
_OUT = (
    os.path.join(_REPO, ".harvest", "quickshot_dryrun.json")
    if _FORCE_CPU
    else os.path.join(_REPO, "BENCH_QUICK_TPU.json")
)

result = {"metric": "quickshot_first_numbers", "complete": False, "phases": {}}


def _mark(phase: str) -> None:
    result["phases"][phase] = round(time.monotonic() - _T0, 1)
    _PROGRESS[0] = time.monotonic()
    _write()
    print(f"[{result['phases'][phase]:7.1f}s] {phase}", flush=True)


def _write() -> None:
    os.makedirs(os.path.dirname(_OUT), exist_ok=True)
    with open(_OUT + ".tmp", "w") as f:
        json.dump(result, f, indent=1)
    os.replace(_OUT + ".tmp", _OUT)


def main() -> None:
    import jax

    if _FORCE_CPU:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache")
    )

    import bench  # repo-root bench.py: _bench_step / _peak_flops

    from paddle_tpu import models
    from paddle_tpu.core.config import set_flags

    dev = jax.devices()[0]
    result["platform"] = dev.platform
    result["device_kind"] = dev.device_kind
    if dev.platform != "cpu":
        set_flags(use_bf16_compute=True, use_flash_attention=True)
    peak = bench._peak_flops(dev.device_kind)
    _mark("backend_up")

    # --- number 1: ResNet-50 train img/s, single batch point ---
    bs = int(os.environ.get("PT_QUICK_RESNET_BS", "128"))
    iters = int(os.environ.get("PT_QUICK_ITERS", "3"))  # dry-run: 1
    try:
        spec = models.get_model(
            "resnet", dataset="flowers", depth=50, class_dim=1000
        )
        _mark("resnet_spec")
        dt, flops, mem = bench._bench_step(spec, bs, warmup=1, iters=iters)
        result["resnet_imgs_per_sec"] = round(bs / dt, 2)
        if mem:
            result["resnet_peak_hbm_bytes"] = mem["peak_hbm_bytes"]
            result["resnet_donated_alias_bytes"] = mem["donated_alias_bytes"]
        result["resnet_batch_size"] = bs
        result["vs_baseline"] = round(bs / dt / bench.BASELINE_IMG_PER_SEC, 3)
        result["vs_v100_target"] = round(
            bs / dt / bench.V100_TARGET_IMG_PER_SEC, 3
        )
        if peak and flops:
            result["resnet_mfu"] = round(flops / dt / peak, 4)
        _mark("resnet_done")
    except Exception as e:  # keep going — the LM number is independent
        result["resnet_error"] = f"{type(e).__name__}: {e}"[:300]
        _mark("resnet_failed")

    # --- number 2: lm_large MFU (the MXU-filling config, scanned layers) ---
    try:
        lm_bs = int(os.environ.get("PT_QUICK_LM_BS", "4"))
        lspec = models.get_model("transformer_lm", **bench.LM_LARGE_KWARGS)
        _mark("lm_large_spec")
        dt, flops, mem = bench._bench_step(lspec, lm_bs, warmup=1, iters=iters)
        seq = bench.LM_LARGE_KWARGS["seq_len"]
        result["lm_large_tokens_per_sec"] = round(lm_bs * seq / dt, 1)
        if mem:
            result["lm_large_peak_hbm_bytes"] = mem["peak_hbm_bytes"]
            result["lm_large_donated_alias_bytes"] = mem["donated_alias_bytes"]
        if peak and flops:
            result["lm_large_mfu"] = round(flops / dt / peak, 4)
        _mark("lm_large_done")
    except Exception as e:
        result["lm_large_error"] = f"{type(e).__name__}: {e}"[:300]
        _mark("lm_large_failed")

    # tokens/sec, not MFU: MFU needs device_kind in bench._PEAK_BF16's
    # table, and an unlisted chip must not wedge the whole harvest chain
    got_number = (
        "resnet_imgs_per_sec" in result or "lm_large_tokens_per_sec" in result
    )
    # a CPU result only "completes" the dry-run artifact, never the chip one
    result["complete"] = got_number and (
        result["platform"] != "cpu" if not _FORCE_CPU else True
    )
    result["total_elapsed_s"] = round(time.monotonic() - _T0, 1)
    _write()
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Chaos smoke gate: training + serving under a seeded fault schedule.

Runs a short end-to-end workload with ``paddle_tpu.resilience.faults``
injecting a deterministic fault schedule — a checkpoint-save IO error, NaN
gradient steps, a reader stall, a corrupted latest checkpoint serial, and a
persistently failing serving replica — and checks that every recovery path
actually recovered:

- the save retried and published (``core.retry`` backoff);
- the NaN steps were skipped (``nan_policy="skip_step"``) and training
  still finished with a finite loss;
- auto-resume fell back past the corrupt serial (quarantined ``*.corrupt``)
  to the previous good one;
- a device lost mid-training shrank the mesh to the survivors and resumed
  from the freshest async-save snapshot within one checkpoint interval
  (``ResilienceConfig(elastic=True)``), and a preemption notice drained a
  final save and auto-resumed in a fresh trainer;
- serving ejected the sick replica (circuit breaker), redispatched its
  batches, kept answering every request, and re-admitted the replica after
  the faults stopped;
- continuous-batching decode came through a transient decode-step storm
  with ZERO failed requests and token-exact outputs (quarantine +
  re-admission through the preempt/resume path), migrated every live
  request off a permanently sick engine — even with a fault injected
  inside the recovery path itself — still token-exact on the original
  handles, and replayed a simulated process crash from the durable token
  journal on a fresh engine with already-delivered tokens deduped; plus
  the PR 9 invariants (preempt/resume under page starvation, cancel
  mid-generation, compile-once decode step, zero leaked pages);
- a tensor-parallel replica group (two tp=2 groups over the virtual
  mesh) lost ONE member to a canary fault — the WHOLE group's breaker
  tripped and every live request finished token-exactly on the other
  group; a stalled member was localized by the per-shard skew watch
  without ejecting anybody;
- under mixed-tenant overload at ~10x capacity (plus a transiently
  failing replica), admission control held the interactive p99 SLO, shed
  batch traffic via typed ``AdmissionRejected`` while batch kept its
  guaranteed drain share, and no request was silently dropped — verified
  from ``/metrics``, ``/tenants``, and the runlog.

Every phase routes its schedule through :func:`_inject`, so the gate can
prove coverage as well as recovery: a fault point registered in
``paddle_tpu.resilience.faults`` that no leg exercises FAILS the run —
new fault points must arrive with their chaos leg.

Exit code 0 = every fault fired AND every recovery held AND every
registered fault point was exercised; 1 = anything less. CI-registered
next to ``tools/lint_program.py --verify`` (see README "Resilience").

Usage:
    python tools/chaos_smoke.py [--seed N] [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import glob
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the serving phase ejects one replica and survives on the other, and the
# shardgroup phase needs two tp=2 replica groups — that takes four
# devices, so virtualize them on a CPU-only host
if os.environ.get("JAX_PLATFORMS") == "cpu" and \
        "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()

# deadlock canary: run every phase with the core.locks order detector on
# (respects an explicit PADDLE_TPU_LOCK_CHECK=0) and fail the run on any
# recorded order violation or a lock held past the watchdog threshold
os.environ.setdefault("PADDLE_TPU_LOCK_CHECK", "1")

import numpy as np  # noqa: E402


class ChaosFailure(AssertionError):
    """One of the recovery contracts did not hold."""


def check(cond, msg: str) -> None:
    if not cond:
        raise ChaosFailure(msg)


# a lock held this long under chaos load is a wedge, not a critical
# section (matches the watchdog timeout scale used by the decode phases)
_LOCK_HOLD_BUDGET_S = 30.0


def _deadlock_canary(phase: str) -> None:
    """Fail the run if the lock-order detector recorded a potential
    deadlock during ``phase``, or any instrumented lock is still held past
    the watchdog threshold (a wedged thread the phase leaked)."""
    from paddle_tpu.core import locks
    vs = locks.violations()
    check(not vs,
          f"{phase}: {len(vs)} lock-order violation(s): "
          + "; ".join(" -> ".join(v["cycle"]) for v in vs))
    hold = locks.max_hold_seconds()
    check(hold < _LOCK_HOLD_BUDGET_S,
          f"{phase}: a lock has been held {hold:.1f}s "
          f"(budget {_LOCK_HOLD_BUDGET_S}s):\n" + locks.render_held_table())


_EXERCISED_POINTS = set()


def _inject(*specs, **kw):
    """``faults.injected`` plus coverage bookkeeping: main() fails the run
    if any ``faults.registered_points()`` entry was never scheduled."""
    from paddle_tpu.resilience import faults
    _EXERCISED_POINTS.update(s.point for s in specs)
    return faults.injected(*specs, **kw)


def _reader(n_batches=8, bs=8, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        w = np.array([[2.0], [-1.0], [0.5], [3.0]], np.float32)
        for _ in range(n_batches):
            x = rng.randn(bs, 4).astype(np.float32)
            yield x, x @ w + 0.1
    return reader


def _train_phase(root: str, seed: int) -> None:
    import paddle_tpu as pt
    from paddle_tpu.resilience import ResilienceConfig, faults
    from paddle_tpu.trainer import CheckpointConfig, Trainer

    def net(x, y):
        pred = pt.layers.fc(x, size=1)
        return pt.layers.mean((pred - y) ** 2)

    losses = []
    with _inject(
        # one save fails with an IO error — retry_call must republish
        faults.FaultSpec(faults.CHECKPOINT_SAVE, "error", after=1, times=1),
        # two NaN-gradient steps — skip_step must drop them and continue
        faults.FaultSpec(faults.TRAINER_STEP, "nan", after=3, times=2),
        # one reader stall — must only cost latency, never correctness
        faults.FaultSpec(faults.READER_NEXT, "stall", after=5, times=1,
                         stall_s=0.05),
        seed=seed,
    ) as plan:
        trainer = Trainer(
            lambda: net, lambda: pt.optimizer.SGD(learning_rate=0.1),
            checkpoint_config=CheckpointConfig(root, step_interval=2,
                                               max_num_checkpoints=4),
            resilience=ResilienceConfig(nan_policy="skip_step",
                                        stall_timeout_s=30.0),
        )
        trainer.train(
            num_epochs=2, reader=_reader(),
            event_handler=lambda ev: losses.append(ev.metrics)
            if type(ev).__name__ == "EndStepEvent" else None,
        )
        check(plan.all_fired(), f"faults never fired: {plan.stats()}")
        check(trainer.bad_steps == 2,
              f"expected 2 skipped NaN steps, got {trainer.bad_steps}")
        good = [l for l in losses if l is not None and np.isfinite(l)]
        nan_steps = [l for l in losses if l is not None and not np.isfinite(l)]
        check(len(nan_steps) == 2, f"expected 2 NaN step metrics: {losses}")
        check(good and good[-1] < good[0],
              f"training did not converge through the chaos: {losses}")
        print(f"[chaos] train: {trainer.global_step} steps, "
              f"{trainer.bad_steps} skipped, faults={plan.stats()}")


def _corrupt_resume_phase(root: str, seed: int) -> None:
    import paddle_tpu as pt
    from paddle_tpu.resilience import faults
    from paddle_tpu.trainer import CheckpointConfig, Trainer

    def net(x, y):
        pred = pt.layers.fc(x, size=1)
        return pt.layers.mean((pred - y) ** 2)

    serials = sorted(
        d for d in os.listdir(root)
        if d.startswith("checkpoint_") and ".corrupt" not in d
    )
    check(len(serials) >= 2, f"need >= 2 serials to test fallback: {serials}")
    latest = os.path.join(root, serials[-1])
    npz = glob.glob(os.path.join(latest, "*.npz"))[0]
    with open(npz, "r+b") as f:  # torn write: truncate the shard mid-file
        f.truncate(max(1, os.path.getsize(npz) // 2))

    with _inject(
        # the latest serial ALSO throws an injected IO error on load (on
        # top of the torn write): either failure mode must quarantine it
        # and fall back to the previous good serial
        faults.FaultSpec(faults.CHECKPOINT_LOAD, "error", times=1),
        seed=seed,
    ) as plan:
        trainer = Trainer(
            lambda: net, lambda: pt.optimizer.SGD(learning_rate=0.1),
            checkpoint_config=CheckpointConfig(root, step_interval=1000),
        )
        trainer.train(num_epochs=3, reader=_reader())
        check(plan.all_fired(),
              f"checkpoint-load fault never fired: {plan.stats()}")
    quarantined = [d for d in os.listdir(root) if ".corrupt" in d]
    check(bool(quarantined), f"corrupt serial not quarantined: {os.listdir(root)}")
    check(np.isfinite(float(np.asarray(trainer.variables.params["fc/w"]).sum())),
          "params not finite after fallback resume")
    print(f"[chaos] resume: fell back past corrupt serial "
          f"(quarantined {quarantined})")


def _elastic_phase(work: str, seed: int) -> None:
    import jax
    import paddle_tpu as pt
    from paddle_tpu import checkpoint_sharded as cks
    from paddle_tpu.resilience import ResilienceConfig, faults
    from paddle_tpu.resilience.faults import DeviceLostError
    from paddle_tpu.trainer import CheckpointConfig, Trainer

    def net(x, y):
        pred = pt.layers.fc(x, size=1)
        return pt.layers.mean((pred - y) ** 2)

    n = jax.device_count()
    check(n >= 2, f"elastic phase needs >= 2 devices, got {n}")

    def make_trainer(root):
        return Trainer(
            lambda: net, lambda: pt.optimizer.SGD(learning_rate=0.1),
            parallel=True,
            checkpoint_config=CheckpointConfig(
                root, step_interval=2, sharded=True, async_save=True),
            resilience=ResilienceConfig(elastic=True),
        )

    try:
        # leg 1: a device vanishes mid-training — the mesh must shrink to
        # the survivors and resume from the freshest snapshot, losing at
        # most one checkpoint interval of steps
        root = os.path.join(work, "elastic_ckpt")
        with _inject(
            faults.FaultSpec(
                faults.DEVICE_LOST, "error", after=5, times=1,
                exc=DeviceLostError("chaos: device reclaimed",
                                    device_indices=(n - 1,)),
            ),
            seed=seed,
        ) as plan:
            t = make_trainer(root)
            t.train(num_epochs=1, reader=_reader())
            check(plan.all_fired(), f"device-loss fault never fired: {plan.stats()}")
        sup = t._elastic
        check(sup is not None and sup.shrinks == 1,
              f"mesh never shrank: {sup and sup.shrinks}")
        check(t._dp.num_devices == n - 1,
              f"expected {n - 1} surviving devices, got {t._dp.num_devices}")
        rec = sup.last_recovery
        check(rec is not None and 5 - rec["restored_step"] <= 2,
              f"resumed outside the checkpoint interval: {rec}")
        check(t.global_step == 12,
              f"epoch did not finish after recovery: step {t.global_step}")
        check(np.isfinite(float(np.asarray(t.variables.params["fc/w"]).sum())),
              "params not finite after elastic recovery")
        print(f"[chaos] elastic: shrank {n} -> {t._dp.num_devices} devices, "
              f"resumed from step {rec['restored_step']} ({rec['source']})")

        # leg 2: a preemption notice (real SIGTERM) — the trainer must
        # finish the step, drain a final save, exit cleanly with a resume
        # marker, and a fresh trainer must auto-resume from it
        root2 = os.path.join(work, "elastic_preempt")
        with _inject(
            faults.FaultSpec(faults.PREEMPT_NOTICE, "preempt", after=2, times=1),
            seed=seed,
        ) as plan:
            t1 = make_trainer(root2)
            t1.train(num_epochs=1, reader=_reader())
            check(plan.all_fired(), f"preempt notice never fired: {plan.stats()}")
        check(t1.preempted and t1.global_step == 3,
              f"preemption not honored at the step boundary: {t1.global_step}")
        check(cks.wait_pending_save() is None, "final save not drained at exit")
        t2 = make_trainer(root2)
        t2.train(num_epochs=1, reader=_reader())
        check(not t2.preempted and t2.global_step == 11,
              f"auto-resume after preemption failed: step {t2.global_step}")
        print(f"[chaos] elastic: preempted at step 3 with a drained save, "
              f"auto-resumed to step {t2.global_step}")
    finally:
        cks.set_snapshot_listener(None)


def _serving_phase(seed: int) -> None:
    import paddle_tpu as pt
    from paddle_tpu.reader.feeder import FeedSpec
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import ServingConfig, ServingEngine

    def net(x):
        return pt.layers.fc(x, size=3)

    rng = np.random.RandomState(seed)
    model = pt.build(net)
    variables = model.init(0, rng.randn(2, 5).astype(np.float32))
    engine = ServingEngine(
        model, variables, [FeedSpec("x", (5,), "float32")],
        config=ServingConfig(
            max_batch_size=4, max_queue_delay_s=0.002, num_replicas=2,
            replica_failure_threshold=2, replica_cooldown_s=0.2,
        ),
    )
    try:
        check(engine.num_replicas == 2, "chaos serving phase needs 2 replicas")
        x = rng.randn(1, 5).astype(np.float32)
        with _inject(
            # replica 0 fails EVERY batch: breaker must eject it and the
            # engine must keep serving on replica 1
            faults.FaultSpec(faults.SERVING_DISPATCH, "error",
                             times=10_000, match={"replica": 0}),
            seed=seed,
        ):
            for _ in range(12):
                out = engine.infer({"x": x})
                check(np.asarray(out).shape == (1, 3), "bad serving output")
        snap = engine.metrics.snapshot()
        check(snap["replica_ejections_total"] >= 1,
              f"sick replica never ejected: {snap}")
        check(snap["redispatches_total"] >= 1,
              f"failed batches never redispatched: {snap}")
        check(snap["errors_total"] == 0, f"requests failed: {snap}")
        # faults cleared: the half-open probe must re-admit replica 0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            engine.infer({"x": x})
            if engine.metrics.replica_recoveries_total >= 1:
                break
            time.sleep(0.05)
        check(engine.metrics.replica_recoveries_total >= 1,
              f"ejected replica never re-admitted: {engine.replica_health()}")
        print(f"[chaos] serving: ejections={snap['replica_ejections_total']} "
              f"redispatches={snap['redispatches_total']} "
              f"recoveries={engine.metrics.replica_recoveries_total}")
    finally:
        unjoined = engine.close(timeout=30)
        check(not unjoined, f"threads failed to join on close: {unjoined}")


def _decode_phase(work: str, seed: int) -> None:
    """Zero-loss continuous-batching decode under chaos — the three
    acceptance legs of the recovery subsystem, each asserting ZERO failed
    requests and token-exact outputs against fault-free references:

    1. a transient decode-step storm (quarantine + re-admission through
       the preempt/resume re-prefill path);
    2. an engine gone permanently sick mid-generation, with a second
       fault injected inside its recovery path — breaker trips, every
       live request migrates to the healthy engine on its ORIGINAL
       handle;
    3. a simulated process crash (``kill()``: no drain, no finish
       records) replayed from the durable token journal on a fresh
       engine, already-delivered tokens deduped.

    Plus the PR 9 invariants: preempt/resume under page starvation,
    cancel mid-generation, compile-once decode step, zero leaked pages.
    """
    import jax.numpy as jnp
    from paddle_tpu import models
    from paddle_tpu.models.transformer_lm import generate
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.circuit import OPEN
    from paddle_tpu.serving import (
        DecodeConfig,
        DecodeEngine,
        DecodeFleet,
        replay_journal,
        resume_incomplete,
    )

    rng = np.random.RandomState(seed)
    spec = models.get_model("transformer_lm", seq_len=64, vocab=97,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    variables = spec.model.init(0, *spec.synth_batch(2, rng))

    # 13 usable pages vs ~21 needed by three grown slots: page starvation
    # and fault recovery get exercised on the same pool
    def mk_engine(**over):
        kw = dict(max_slots=3, page_size=4, max_context=40, prefill_chunk=8,
                  num_pages=14, recovery_base_delay_s=0.001,
                  recovery_max_delay_s=0.005)
        kw.update(over)
        return DecodeEngine(variables, cfg, decode=DecodeConfig(**kw))

    # mixed-length cases with fault-free greedy references — "token-exact"
    # in every leg below means equal to these
    cases = []
    for _ in range(3):
        p = rng.randint(1, 97, size=(int(rng.randint(4, 12)),)).astype(np.int32)
        n = int(rng.randint(10, 20))
        ref = np.asarray(generate(variables, jnp.asarray(p[None]), n, cfg))[0]
        cases.append((p, n, ref))

    def check_exact(outs, tag):
        for (_, _, ref), out in zip(cases, outs):
            check(np.array_equal(out.tokens, ref),
                  f"{tag}: output not token-exact "
                  f"(got {list(out.tokens)}, want {ref.tolist()})")

    def prompt():
        return rng.randint(1, 97, size=(int(rng.randint(4, 12)),)
                           ).astype(np.int32)

    engine = mk_engine()
    try:
        # leg 1: transient decode-step storm — zero failed requests,
        # every output token-exact
        with _inject(
            faults.FaultSpec(faults.DECODE_STEP, "error", after=2, times=3),
            seed=seed,
        ) as plan:
            handles = [engine.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in handles]
            check(plan.all_fired(),
                  f"decode-step storm never fired: {plan.stats()}")
        check_exact(outs, "storm")
        snap = engine.metrics.snapshot()
        check(snap["errors_total"] == 0,
              f"decode-step storm failed requests: {snap}")
        check(snap["recovered_total"] >= 1,
              f"storm never took the recovery path: {snap}")

        # leg 2: page exhaustion — mixed lengths over the starved pool;
        # every request must still finish, via preempt/resume
        handles = [engine.submit(prompt(), int(rng.randint(12, 24)))
                   for _ in range(6)]
        outs = [h.result(timeout=300) for h in handles]
        check(all(o.finish_reason == "length" for o in outs),
              f"requests lost under page starvation: "
              f"{[o.finish_reason for o in outs]}")
        snap = engine.metrics.snapshot()
        check(snap["preempted_total"] >= 1,
              f"starved pool never preempted: {snap}")
        # recovery re-admits ride the same resume path as preemptions, so
        # the conservation law is: every resume is a preempt or a recover
        check(snap["resumed_total"]
              == snap["preempted_total"] + snap["recovered_total"],
              f"resumed != preempted + recovered: {snap}")

        # leg 3: cancel mid-generation
        h = engine.submit(prompt(), 25)
        deadline = time.monotonic() + 60
        while len(h._req.generated) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        h.cancel()
        out = h.result(timeout=60)
        check(out.finish_reason == "cancelled",
              f"cancel ignored: {out.finish_reason}")
        check(engine.decode_step_cache_size() == 1,
              "decode step recompiled under chaos traffic")
        print(f"[chaos] decode: storm recovered={snap['recovered_total']} "
              f"preempted={snap['preempted_total']} "
              f"resumed={snap['resumed_total']} cancel=ok, 0 failed")
    finally:
        unjoined = engine.close(timeout=30)
        check(not unjoined, f"decode threads failed to join: {unjoined}")
    engine.kv.assert_no_leaks()

    # leg 4: engine death mid-generation — permanent step faults on A plus
    # one inside A's own recovery path (DECODE_RECOVER escalates a rung):
    # the breaker must trip and every live request must finish on B,
    # token-exact, on the handle the client already holds
    ea, eb = mk_engine(), mk_engine()
    fleet = DecodeFleet([ea, eb])
    try:
        with _inject(
            faults.FaultSpec(faults.DECODE_STEP, "error", after=1,
                             times=10 ** 9,
                             match={"engine": ea.metrics.engine_label}),
            faults.FaultSpec(faults.DECODE_RECOVER, "error",
                             match={"engine": ea.metrics.engine_label}),
            seed=seed,
        ) as plan:
            handles = [ea.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in handles]
            check(plan.all_fired(),
                  f"migration faults never fired: {plan.stats()}")
        check_exact(outs, "migration")
        check(ea.breaker.state == OPEN,
              f"sick engine's breaker not open: {ea.breaker.state}")
        check(ea.metrics.snapshot()["migrated_total"] == len(cases),
              f"not every request migrated: {ea.metrics.snapshot()}")
        check(eb.metrics.snapshot()["errors_total"] == 0,
              f"rescue engine failed requests: {eb.metrics.snapshot()}")
        check(eb.decode_step_cache_size() == 1,
              "rescue engine recompiled for adopted requests")
        print(f"[chaos] decode: migrated "
              f"{ea.metrics.snapshot()['migrated_total']} requests "
              f"{ea.metrics.engine_label} -> {eb.metrics.engine_label}, "
              f"0 failed")
    finally:
        fleet.close(timeout=30)

    # leg 5: process crash + journal replay — kill() mid-generation (no
    # drain, no finish records), then a fresh engine resumes every
    # incomplete request from the WAL, deduping delivered tokens
    wal = os.path.join(work, "decode.wal")
    e1 = mk_engine(journal_path=wal, journal_fsync_every=4)
    handles = [e1.submit(p, n) for p, n, _ in cases]
    deadline = time.monotonic() + 120
    while (e1.metrics.snapshot()["tokens_total"] < 6
           and time.monotonic() < deadline):
        time.sleep(0.005)
    e1.kill()
    rep = replay_journal(wal)
    check(len(rep) == len(cases), f"journal lost admits: {len(rep)}")
    check(not any(r.finished for r in rep.values()),
          "crash left finish records in the journal")
    e2 = mk_engine(journal_path=wal)
    try:
        resumed = resume_incomplete(e2, wal)
        check(len(resumed) == len(cases),
              f"resumed {len(resumed)}/{len(cases)} after replay")
        by_prompt = {tuple(p.tolist()): ref for p, _, ref in cases}
        for rid, (rh, n_delivered) in resumed.items():
            out = rh.result(timeout=300)
            ref = by_prompt[tuple(rep[rid].prompt.tolist())]
            check(np.array_equal(out.tokens, ref),
                  f"replayed request {rid} not token-exact")
            check(out.tokens[:n_delivered].tolist()
                  == rep[rid].generated[:n_delivered],
                  f"dedup prefix mismatch for {rid}")
        e2._journal.flush()
        check(all(r.finished for r in replay_journal(wal).values()),
              "resumed requests never finished in the journal")
        check(resume_incomplete(e2, wal) == {},
              "second replay re-resumed finished requests (dedup broken)")
        check(e2.decode_step_cache_size() == 1,
              "replay engine recompiled for adopted requests")
        print(f"[chaos] decode: crash-replayed {len(resumed)} requests "
              f"from the journal, token-exact with dedup")
    finally:
        unjoined = e2.close(timeout=30)
        check(not unjoined, f"replay engine threads failed to join: {unjoined}")
    e2.kv.assert_no_leaks()


def _spec_decode_phase(work: str, seed: int) -> None:
    """Speculative decoding + radix prefix cache under chaos (ISSUE 12):
    ``DECODE_STEP`` faults land inside draft-and-verify iterations (the
    quarantine path must roll the draft block back), an engine dies
    mid-speculation and its live requests migrate token-exact, and a
    ``kill()`` mid-speculation replays from the durable journal — with
    the refcounted page pool (slot refs + radix tree refs + CoW copies)
    provably empty after every drain."""
    import jax.numpy as jnp
    from paddle_tpu import models
    from paddle_tpu.models.transformer_lm import generate
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.circuit import OPEN
    from paddle_tpu.serving import (
        DecodeConfig,
        DecodeEngine,
        DecodeFleet,
        replay_journal,
        resume_incomplete,
    )

    rng = np.random.RandomState(seed + 12)
    spec = models.get_model("transformer_lm", seq_len=64, vocab=97,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    variables = spec.model.init(0, *spec.synth_batch(2, rng))

    # self-draft (draft == target): acceptance stays high, so rollback,
    # trim and the verify fast path all run; the starved 13-page pool is
    # shared with the radix tree, so adopt/evict/preempt fire too
    def mk_engine(**over):
        kw = dict(max_slots=3, page_size=4, max_context=40, prefill_chunk=8,
                  num_pages=14, spec_tokens=3, prefix_cache=True,
                  recovery_base_delay_s=0.001, recovery_max_delay_s=0.005)
        kw.update(over)
        return DecodeEngine(variables, cfg, decode=DecodeConfig(**kw),
                            draft_variables=variables, draft_cfg=cfg)

    # prompts share a 14-token preamble that is neither page- nor
    # chunk-aligned, so prefix hits AND copy-on-write are reachable
    preamble = rng.randint(1, 97, size=(14,)).astype(np.int32)
    cases = []
    for _ in range(3):
        tail = rng.randint(1, 97,
                           size=(int(rng.randint(2, 8)),)).astype(np.int32)
        p = np.concatenate([preamble, tail])
        n = int(rng.randint(8, 16))
        ref = np.asarray(generate(variables, jnp.asarray(p[None]), n, cfg))[0]
        cases.append((p, n, ref))

    def check_exact(outs, tag):
        for (_, _, ref), out in zip(cases, outs):
            check(np.array_equal(out.tokens, ref),
                  f"{tag}: output not token-exact "
                  f"(got {list(out.tokens)}, want {ref.tolist()})")

    # leg 1: transient fault storm fires inside verify iterations — the
    # draft block rolls back, requests re-prefill (hitting the warm
    # tree), and every output is still token-exact
    engine = mk_engine()
    try:
        with _inject(
            faults.FaultSpec(faults.DECODE_STEP, "error", after=2, times=3),
            seed=seed,
        ) as plan:
            handles = [engine.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in handles]
            check(plan.all_fired(),
                  f"verify-step storm never fired: {plan.stats()}")
        check_exact(outs, "spec storm")
        snap = engine.metrics.snapshot()
        check(snap["errors_total"] == 0,
              f"verify-step storm failed requests: {snap}")
        check(snap["recovered_total"] >= 1,
              f"storm never took the recovery path: {snap}")
        check(snap["verify_steps_total"] >= 1,
              f"traffic never went through draft-and-verify: {snap}")
        # second round over the warm tree: prefix hits, still exact
        handles = [engine.submit(p, n) for p, n, _ in cases]
        check_exact([h.result(timeout=300) for h in handles], "warm prefix")
        snap = engine.metrics.snapshot()
        check(snap["prefix_hit_tokens_total"] > 0,
              f"warm rerun never hit the prefix cache: {snap}")
        check(engine.verify_step_cache_size() == 1,
              "verify step recompiled under chaos traffic")
        print(f"[chaos] spec decode: storm recovered="
              f"{snap['recovered_total']} verify_steps="
              f"{snap['verify_steps_total']} prefix_hit_tokens="
              f"{snap['prefix_hit_tokens_total']}, 0 failed")
    finally:
        unjoined = engine.close(timeout=30)
        check(not unjoined, f"spec engine threads failed to join: {unjoined}")
    engine.kv.assert_no_leaks()

    # leg 2: engine dies mid-speculation — permanent verify faults trip
    # A's breaker; every live request finishes on B token-exact
    ea, eb = mk_engine(), mk_engine()
    fleet = DecodeFleet([ea, eb])
    try:
        with _inject(
            faults.FaultSpec(faults.DECODE_STEP, "error", after=1,
                             times=10 ** 9,
                             match={"engine": ea.metrics.engine_label}),
            seed=seed,
        ):
            handles = [ea.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in handles]
        check_exact(outs, "spec migration")
        check(ea.breaker.state == OPEN,
              f"sick spec engine's breaker not open: {ea.breaker.state}")
        check(ea.metrics.snapshot()["migrated_total"] == len(cases),
              f"not every request migrated: {ea.metrics.snapshot()}")
        check(eb.metrics.snapshot()["errors_total"] == 0,
              f"rescue engine failed requests: {eb.metrics.snapshot()}")
        check(eb.verify_step_cache_size() == 1,
              "rescue engine recompiled its verify step")
        print(f"[chaos] spec decode: migrated "
              f"{ea.metrics.snapshot()['migrated_total']} requests "
              f"mid-speculation, 0 failed")
    finally:
        fleet.close(timeout=30)
    ea.kv.assert_no_leaks()
    eb.kv.assert_no_leaks()

    # leg 3: kill() mid-speculation — no drain, tree and slots torn down
    # with zero leaked refs; a fresh spec engine replays the journal
    wal = os.path.join(work, "spec_decode.wal")
    e1 = mk_engine(journal_path=wal, journal_fsync_every=4)
    handles = [e1.submit(p, n) for p, n, _ in cases]
    deadline = time.monotonic() + 120
    while (e1.metrics.snapshot()["tokens_total"] < 6
           and time.monotonic() < deadline):
        time.sleep(0.005)
    e1.kill()
    e1.kv.assert_no_leaks()  # kill dropped slot refs AND the tree's refs
    e2 = mk_engine(journal_path=wal)
    try:
        resumed = resume_incomplete(e2, wal)
        check(len(resumed) == len(cases),
              f"resumed {len(resumed)}/{len(cases)} after spec kill")
        rep = replay_journal(wal)
        by_prompt = {tuple(p.tolist()): ref for p, _, ref in cases}
        for rid, (rh, n_delivered) in resumed.items():
            out = rh.result(timeout=300)
            ref = by_prompt[tuple(rep[rid].prompt.tolist())]
            check(np.array_equal(out.tokens, ref),
                  f"spec-replayed request {rid} not token-exact")
        print(f"[chaos] spec decode: kill mid-speculation replayed "
              f"{len(resumed)} requests token-exact, 0 leaked pages")
    finally:
        unjoined = e2.close(timeout=30)
        check(not unjoined,
              f"spec replay engine threads failed to join: {unjoined}")
    e2.kv.assert_no_leaks()


def _disagg_phase(work: str, seed: int) -> None:
    """Disaggregated prefill/decode under chaos (ISSUE 15):

    1. a prefill STORM of long prompts mid-decode must not move the
       decode-side latency — steady interactive generations complete in
       the same envelope with or without the storm, and the decode
       worker never runs a prefill chunk (the role split is structural,
       not probabilistic);
    2. a faulted KV-page transfer (``DISAGG_HANDOFF``) degrades to a
       token-exact re-prefill on the decode worker (rung 2);
    3. a prefill worker killed mid-handoff — the ``hof`` journal record
       durable, the receiver's ``ack`` never written — loses zero
       requests: replay resumes every one on the decode worker,
       token-exact, with zero leaked pages;
    4. a drain-and-convert cycle (prefill -> decode -> prefill) under
       continuous load completes every request token-exact.
    """
    import threading

    import jax.numpy as jnp
    from paddle_tpu import models
    from paddle_tpu.models.transformer_lm import generate
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import (
        DecodeConfig,
        DecodeEngine,
        DisaggRouter,
        replay_journal,
        resume_incomplete,
    )
    from paddle_tpu.serving.disagg import DECODE, PREFILL

    rng = np.random.RandomState(seed + 15)
    spec = models.get_model("transformer_lm", seq_len=64, vocab=97,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    variables = spec.model.init(0, *spec.synth_batch(2, rng))

    def mk_engine(**over):
        kw = dict(max_slots=3, page_size=4, max_context=40, prefill_chunk=8,
                  num_pages=30, recovery_base_delay_s=0.001,
                  recovery_max_delay_s=0.005)
        kw.update(over)
        return DecodeEngine(variables, cfg, decode=DecodeConfig(**kw))

    cases = []
    for _ in range(3):
        p = rng.randint(1, 97, size=(int(rng.randint(4, 8)),)).astype(np.int32)
        n = int(rng.randint(10, 16))
        ref = np.asarray(generate(variables, jnp.asarray(p[None]), n, cfg))[0]
        cases.append((p, n, ref))

    def check_exact(outs, tag):
        for (_, _, ref), out in zip(cases, outs):
            check(np.array_equal(out.tokens, ref),
                  f"{tag}: output not token-exact "
                  f"(got {list(out.tokens)}, want {ref.tolist()})")

    # leg 1: prefill storm mid-decode — the decode side must not notice
    pre, dec = mk_engine(), mk_engine()
    router = DisaggRouter([pre, dec], [PREFILL, DECODE])
    try:
        # warm the jits so wave timings measure steady state, not compiles
        [h.result(timeout=300)
         for h in [router.submit(p, n) for p, n, _ in cases]]

        def steady_wave():
            t0 = time.monotonic()
            lats = []
            for p, n, _ in cases:
                s = time.monotonic()
                outs_one = router.submit(p, n).result(timeout=300)
                lats.append(time.monotonic() - s)
                check(len(outs_one.tokens) == n,
                      f"steady request truncated: {outs_one.finish_reason}")
            return max(lats), time.monotonic() - t0

        quiet_p99, _ = steady_wave()
        # 6 long-prompt requests flood the prefill worker...
        storm = [router.submit(
            rng.randint(1, 97, size=(26,)).astype(np.int32), 2)
            for _ in range(6)]
        # ...while the steady interactive wave runs mid-storm
        storm_p99, _ = steady_wave()
        storm_outs = [h.result(timeout=300) for h in storm]
        check(all(o.finish_reason == "length" for o in storm_outs),
              f"storm requests lost: {[o.finish_reason for o in storm_outs]}")
        budget = 3.0 * max(quiet_p99, 0.05) + 1.0
        check(storm_p99 <= budget,
              f"prefill storm moved decode p99: quiet={quiet_p99:.3f}s "
              f"storm={storm_p99:.3f}s (budget {budget:.3f}s)")
        # the role split is structural: every prefill chunk ran on the
        # prefill worker, the decode worker only ever adopted pages
        check(dec.metrics.snapshot()["prefill_chunks_total"] == 0,
              f"decode worker ran prefill chunks: {dec.metrics.snapshot()}")
        check(router.handoffs_total == 2 * len(cases) + len(cases) + 6,
              f"requests bypassed the handoff path: {router.snapshot()}")
        check(router.handoff_rejects_total == 0,
              f"unforced handoff rejects: {router.snapshot()}")
        print(f"[chaos] disagg: storm held decode p99 "
              f"(quiet={quiet_p99 * 1e3:.0f}ms storm={storm_p99 * 1e3:.0f}ms"
              f", {router.handoffs_total} handoffs, 0 rejects)")
    finally:
        unjoined = router.close(30)
        check(not unjoined, f"disagg threads failed to join: {unjoined}")
    pre.kv.assert_no_leaks()
    dec.kv.assert_no_leaks()

    # leg 2: faulted KV-page transfer — rung 2 re-prefills, token-exact
    pre, dec = mk_engine(), mk_engine()
    router = DisaggRouter([pre, dec], [PREFILL, DECODE],
                          transport="serialized")
    try:
        with _inject(
            faults.FaultSpec(faults.DISAGG_HANDOFF, "error", times=2),
            seed=seed,
        ) as plan:
            handles = [router.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in handles]
            check(plan.all_fired(),
                  f"handoff faults never fired: {plan.stats()}")
        check_exact(outs, "handoff fault")
        check(router.handoff_rejects_total == 2,
              f"faulted transfers not rejected: {router.snapshot()}")
        check(router.handoff_reprefills_total == 2,
              f"rejected transfers not re-prefilled: {router.snapshot()}")
        print(f"[chaos] disagg: {router.handoff_rejects_total} faulted "
              f"transfers rejected + re-prefilled, token-exact")
    finally:
        unjoined = router.close(30)
        check(not unjoined, f"disagg threads failed to join: {unjoined}")
    pre.kv.assert_no_leaks()
    dec.kv.assert_no_leaks()

    # leg 3: prefill worker killed mid-handoff. Draining the decode side
    # wedges every request inside the handoff window — the hof record is
    # durable (fsync'd BEFORE transfer) but no ack ever lands. kill() is
    # a simulated crash; replay over the shared WAL must resume every
    # request on the decode worker, token-exact, zero loss.
    wal = os.path.join(work, "disagg.wal")
    pre, dec = mk_engine(), mk_engine()
    router = DisaggRouter([pre, dec], [PREFILL, DECODE],
                          journal_path=wal, transport="serialized")
    try:
        router._draining.add(id(dec))
        handles = [router.submit(p, n) for p, n, _ in cases]
        deadline = time.monotonic() + 120
        rep = {}
        while time.monotonic() < deadline:
            router._journal.flush()
            rep = replay_journal(wal)
            if (len(rep) == len(cases)
                    and all(r.handed_off and not r.acked
                            for r in rep.values())
                    and not any(r.finished for r in rep.values())):
                break
            time.sleep(0.005)
        check(len(rep) == len(cases)
              and all(r.handed_off and not r.acked for r in rep.values()),
              f"handoff window never reached: {rep}")
        pre.kill()  # crash mid-handoff: hof durable, ack never written
        failed = 0
        for h in handles:
            try:
                h.result(timeout=10)
            except Exception:
                failed += 1
        check(failed == len(handles),
              f"killed worker's handles did not fail typed: {failed}")
        router._draining.discard(id(dec))
        router._journal.flush()
        rep = replay_journal(wal)
        check(not any(r.finished for r in rep.values()),
              "crash left finish records in the journal")
        resumed = resume_incomplete(dec, wal)
        check(len(resumed) == len(cases),
              f"resumed {len(resumed)}/{len(cases)} after the crash")
        by_prompt = {tuple(p.tolist()): ref for p, _, ref in cases}
        for rid, (rh, n_delivered) in resumed.items():
            out = rh.result(timeout=300)
            ref = by_prompt[tuple(rep[rid].prompt.tolist())]
            check(np.array_equal(out.tokens, ref),
                  f"request {rid} not token-exact after the crash")
            check(out.tokens[:n_delivered].tolist()
                  == rep[rid].generated[:n_delivered],
                  f"dedup prefix mismatch for {rid}")
        print(f"[chaos] disagg: killed the prefill worker mid-handoff, "
              f"resumed {len(resumed)} unacked requests token-exact, 0 lost")
    finally:
        unjoined = router.close(30)
        check(not unjoined, f"disagg threads failed to join: {unjoined}")
    pre.kv.assert_no_leaks()  # kill released every slot's pages
    dec.kv.assert_no_leaks()

    # leg 4: drain-and-convert cycle under continuous load
    built = []

    def factory(role):
        eng = mk_engine()
        built.append(eng)
        return eng

    p1, p2, d1 = mk_engine(), mk_engine(), mk_engine()
    router = DisaggRouter([p1, p2, d1], [PREFILL, PREFILL, DECODE],
                          factory=factory)
    stop = threading.Event()
    results = []

    def client():
        k = 0
        while not stop.is_set():
            p, n, ref = cases[k % len(cases)]
            k += 1
            try:
                out = router.submit(p, n).result(timeout=300)
                results.append(bool(np.array_equal(out.tokens, ref)))
            except Exception as e:  # any loss under conversion = failure
                results.append(repr(e))
    try:
        t = threading.Thread(target=client)
        t.start()
        mid = router.convert(p2, DECODE, timeout=30)
        check(p2.closed, "converted worker was not drained")
        back = router.convert(mid, PREFILL, timeout=30)
        time.sleep(0.1)  # a little more load on the reshaped fleet
        stop.set()
        t.join(timeout=120)
        check(not t.is_alive(), "disagg load client failed to finish")
        check(results and all(r is True for r in results),
              f"requests lost/corrupted during conversion: "
              f"{[r for r in results if r is not True][:3]} "
              f"({len(results)} total)")
        check(router.conversions_total == 2,
              f"conversions not recorded: {router.snapshot()}")
        check(router.n_prefill == 2 and router.n_decode == 1,
              f"role cycle did not restore the fleet shape: "
              f"{router.snapshot()}")
        print(f"[chaos] disagg: drain-and-convert cycle under load, "
              f"{len(results)} requests token-exact through 2 conversions")
    finally:
        stop.set()
        unjoined = router.close(30)
        check(not unjoined, f"disagg threads failed to join: {unjoined}")
    for e in [p1, p2, d1] + built:
        e.kv.assert_no_leaks()


def _host_tier_phase(work: str, seed: int) -> None:
    """Hierarchical KV host tier under chaos (ISSUE 18):

    1. a stalled demote (slow host memory) during a shared-system-prompt
       storm changes nothing: every output token-exact, the stall never
       wedges a lock or the decode loop;
    2. an engine killed mid-traffic loses zero requests AND its
       replacement repopulates its radix tree FROM THE HOST TIER: the
       shared pool survives ``kill()``, journal replay resumes the
       in-flight requests, and the replacement serves them with
       promoted pages (host hits), token-exact, no page leaks anywhere;
    3. a corrupted host page at promote time (bit flip before the CRC
       check) is quarantined — never implanted — and the affected
       requests still complete token-exact via ordinary re-prefill.
    """
    import jax.numpy as jnp
    from paddle_tpu import models
    from paddle_tpu.models.transformer_lm import generate
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import (
        DecodeConfig,
        DecodeEngine,
        DecodeFleet,
        HostPagePool,
        replay_journal,
        resume_incomplete,
    )

    rng = np.random.RandomState(seed + 18)
    spec = models.get_model("transformer_lm", seq_len=64, vocab=97,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    variables = spec.model.init(0, *spec.synth_batch(2, rng))

    pool = HostPagePool(max_bytes=1 << 20, page_size=4)

    def mk_engine(**over):
        kw = dict(max_slots=3, page_size=4, max_context=40, prefill_chunk=8,
                  num_pages=30, prefix_cache=True, prefix_digest=True,
                  recovery_base_delay_s=0.001, recovery_max_delay_s=0.005)
        kw.update(over)
        return DecodeEngine(variables, cfg, decode=DecodeConfig(**kw),
                            host_tier=pool)

    # the shared-system-prompt storm: every prompt opens with the same
    # 14-token prefix (3 full pages), the tier's natural working set
    sys_prefix = rng.randint(1, 97, size=(14,)).astype(np.int32)
    cases = []
    for _ in range(6):
        tail = rng.randint(1, 97,
                           size=(int(rng.randint(2, 8)),)).astype(np.int32)
        p = np.concatenate([sys_prefix, tail])
        n = int(rng.randint(8, 14))
        ref = np.asarray(generate(variables, jnp.asarray(p[None]), n, cfg))[0]
        cases.append((p, n, ref))
    by_prompt = {tuple(p.tolist()): ref for p, _, ref in cases}

    wal = os.path.join(work, "host_tier.wal")
    ea = mk_engine(journal_path=wal)
    eb = mk_engine()
    fleet = DecodeFleet([ea, eb])
    a2 = ec = None
    try:
        # leg 1: storm round with demotes STALLING (slow host memory) —
        # the tier is strictly best-effort, so nothing may change
        with _inject(
            faults.FaultSpec(faults.HOST_TIER, "stall", stall_s=0.05,
                             times=2, match={"op": "demote"}),
            seed=seed,
        ) as plan:
            outs = [fleet.submit(p, n).result(timeout=300)
                    for p, n, _ in cases]
            check(plan.all_fired(),
                  f"demote stalls never fired: {plan.stats()}")
        for (_, _, ref), out in zip(cases, outs):
            check(np.array_equal(out.tokens, ref),
                  "storm output not token-exact under stalled demotes")
        check(pool.num_pages > 0, "storm demoted nothing into the tier")

        # leg 2: kill one engine mid-traffic. Its handles fail typed (a
        # crash is a crash), but zero requests are LOST: journal replay
        # resumes every in-flight one on a replacement engine that warms
        # its empty radix tree from the host tier instead of re-paying
        # full prefill for the storm's shared prefix.
        handles = [ea.submit(p, n) for p, n, _ in cases]
        ea.kill()
        failed = 0
        for h, (_, _, ref) in zip(handles, cases):
            try:
                out = h.result(timeout=10)
                check(np.array_equal(out.tokens, ref),
                      "pre-kill completion not token-exact")
            except Exception:
                failed += 1
        check(failed >= 1, "kill() interrupted nothing — phase too slow")
        check(pool.num_pages > 0, "kill() wiped the host tier")
        a2 = mk_engine()
        rep = replay_journal(wal)
        resumed = resume_incomplete(a2, wal)
        # every resumed request failed its handle, but the converse has a
        # benign window: _finish writes the fin record BEFORE resolving
        # the handle, so a kill() landing between the two fails a handle
        # whose request the journal already marks finished
        check(1 <= len(resumed) <= failed,
              f"resumed {len(resumed)} vs {failed} failed in-flight")
        for rid, (rh, n_delivered) in resumed.items():
            out = rh.result(timeout=300)
            ref = by_prompt[tuple(rep[rid].prompt.tolist())]
            check(np.array_equal(out.tokens, ref),
                  f"request {rid} not token-exact after the crash")
        snap = a2.metrics.snapshot()
        check(snap["host_tier_hits_total"] > 0,
              f"replacement engine never probed the tier: {snap}")
        check(snap["host_promoted_pages_total"] > 0,
              f"replacement engine re-prefilled instead of promoting: "
              f"{snap}")
        print(f"[chaos] host_tier: killed an engine mid-storm, resumed "
              f"{failed} in-flight requests token-exact, replacement "
              f"promoted {snap['host_promoted_pages_total']} pages from "
              f"the host tier")

        # leg 3: corrupt-on-promote — a bit-flipped host page must be
        # quarantined by the CRC check, never implanted, and the
        # requests re-prefill token-exactly
        ec = mk_engine()
        with _inject(
            faults.FaultSpec(faults.HOST_TIER, "nan", times=2,
                             match={"op": "promote"}),
            seed=seed,
        ) as plan:
            outs = [ec.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in outs]
            check(plan.all_fired(),
                  f"promote corruptions never fired: {plan.stats()}")
        for (_, _, ref), out in zip(cases, outs):
            check(np.array_equal(out.tokens, ref),
                  "output not token-exact after a corrupted promote")
        snap = ec.metrics.snapshot()
        check(snap["host_quarantined_total"] == 2,
              f"corrupted pages not quarantined: {snap}")
        check(pool.stats()["quarantined"] == 2,
              f"pool quarantine counter wrong: {pool.stats()}")
        print(f"[chaos] host_tier: {snap['host_quarantined_total']} "
              f"corrupted host pages quarantined, every request "
              f"token-exact via re-prefill")
    finally:
        fleet.close(timeout=60)
        for e in (a2, ec):
            if e is not None:
                e.close()
    for e in (ea, eb, a2, ec):
        if e is not None:
            e.kv.assert_no_leaks()


def _shardgroup_phase(work: str, seed: int) -> None:
    """Tensor-parallel replica groups under chaos (ISSUE 16):

    1. ONE member of a tp=2 group hit by a ``GROUP_MEMBER`` canary fault
       — the WHOLE group must eject (breaker trip) and every live
       request finish token-exactly on the other group, zero loss; the
       healed group is re-admitted via the fleet's half-open probe;
    2. ONE member stalled (not failed) — the per-shard skew watch must
       localize the slow chip (``serving.group.shard_skew`` +
       straggler counter) while the group keeps serving token-exactly,
       without tripping any breaker.
    """
    import jax.numpy as jnp
    from paddle_tpu import models
    from paddle_tpu.models.transformer_lm import generate
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.circuit import CLOSED, OPEN
    from paddle_tpu.serving import DecodeConfig, DecodeFleet
    from paddle_tpu.serving.shardgroup import make_groups

    rng = np.random.RandomState(seed + 16)
    spec = models.get_model("transformer_lm", seq_len=64, vocab=97,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    variables = spec.model.init(0, *spec.synth_batch(2, rng))

    cases = []
    for _ in range(3):
        p = rng.randint(1, 97, size=(int(rng.randint(4, 8)),)).astype(np.int32)
        n = int(rng.randint(10, 16))
        ref = np.asarray(generate(variables, jnp.asarray(p[None]), n, cfg))[0]
        cases.append((p, n, ref))

    def check_exact(outs, tag):
        for (_, _, ref), out in zip(cases, outs):
            check(np.array_equal(out.tokens, ref),
                  f"{tag}: output not token-exact "
                  f"(got {list(out.tokens)}, want {ref.tolist()})")

    def mk_fleet():
        return DecodeFleet.from_groups(
            variables, cfg, make_groups(2)[:2],
            decode=DecodeConfig(
                max_slots=3, page_size=4, max_context=40, prefill_chunk=8,
                num_pages=14, recovery_base_delay_s=0.001,
                recovery_max_delay_s=0.005, breaker_cooldown_s=0.05,
                breaker_max_cooldown_s=0.2, group_probe_every_s=0.0))

    # leg 1: member fault -> whole-group ejection, zero-loss migration
    fleet = mk_fleet()
    ga, gb = fleet.engines
    try:
        handles = [ga.submit(p, n) for p, n, _ in cases]  # pin to A
        # arm the canary only once every case is live in decode: a probe
        # fault while some still sit in the admission queue migrates just
        # the admitted subset, and the queued rest would then finish on
        # the re-closed group — breaking the all-migrated assertion below
        total_chunks = sum(-(-len(p) // ga.decode_config.prefill_chunk)
                           for p, _, _ in cases)
        deadline = time.monotonic() + 120
        while (time.monotonic() < deadline
               and ga.metrics.snapshot()["prefill_chunks_total"]
               < total_chunks):
            time.sleep(0.005)
        check(ga.metrics.snapshot()["prefill_chunks_total"] == total_chunks,
              "group-kill leg: cases never finished prefill")
        with _inject(
            faults.FaultSpec(faults.GROUP_MEMBER, "error", times=1,
                             match={"engine": ga.metrics.engine_label,
                                    "shard": 1}),
            seed=seed,
        ) as plan:
            outs = [h.result(timeout=300) for h in handles]
            check(plan.all_fired(),
                  f"group member fault never fired: {plan.stats()}")
        check_exact(outs, "group-kill")
        check(ga.breaker.state == OPEN,
              "one member died but the group's breaker stayed closed")
        snap = ga.metrics.snapshot()
        check(snap["group_member_faults_total"] == 1,
              f"member fault not counted: {snap}")
        check(snap["migrated_total"] == len(cases),
              f"group ejection lost requests: {snap}")
        check(snap["errors_total"] == 0
              and gb.metrics.snapshot()["errors_total"] == 0,
              "group ejection failed requests")
        check(gb.decode_step_cache_size() == 1,
              "surviving group's step recompiled under migration")
        # healed member: half-open probing re-admits the whole group
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and ga.breaker.state != CLOSED:
            p, n, ref = cases[0]
            out = fleet.submit(p, n).result(timeout=120)
            check(np.array_equal(out.tokens, ref),
                  "re-admission probe output not token-exact")
            time.sleep(0.02)
        check(ga.breaker.state == CLOSED,
              "healed group never re-admitted by the half-open probe")
        print(f"[chaos] shardgroup: member fault ejected whole group, "
              f"{snap['migrated_total']} request(s) migrated token-exact, "
              f"group re-admitted")
    finally:
        fleet.close(timeout=30)

    # leg 2: member STALL -> straggler localized, nobody ejected
    fleet = mk_fleet()
    ga, gb = fleet.engines
    try:
        with _inject(
            faults.FaultSpec(faults.GROUP_MEMBER, "stall", times=10 ** 9,
                             stall_s=0.02,
                             match={"engine": ga.metrics.engine_label,
                                    "shard": 0}),
            seed=seed,
        ) as plan:
            handles = [ga.submit(p, n) for p, n, _ in cases]
            outs = [h.result(timeout=300) for h in handles]
            check(plan.all_fired(),
                  f"group member stall never fired: {plan.stats()}")
            check_exact(outs, "group-stall")
            snap = ga.metrics.snapshot()
            # the probe cadence may need a few more passes than the
            # traffic took to reach min_samples on both shards
            deadline = time.monotonic() + 60
            while (time.monotonic() < deadline
                   and snap["shard_stragglers_total"] == 0):
                time.sleep(0.01)
                snap = ga.metrics.snapshot()
        check(snap["shard_stragglers_total"] >= 1,
              f"stalled shard never localized: {snap}")
        check(snap["group_member_faults_total"] == 0,
              f"a stall must not count as a member fault: {snap}")
        check(ga.breaker.state == CLOSED,
              "a stalled (not failed) member must not eject the group")
        check(snap["errors_total"] == 0, f"stall leg failed requests: {snap}")
        print(f"[chaos] shardgroup: stalled shard localized "
              f"({snap['shard_stragglers_total']} straggler flag(s)), "
              f"group kept serving, 0 failed")
    finally:
        fleet.close(timeout=30)


def _overload_phase(work: str, seed: int) -> None:
    """Mixed-tenant overload at ~10x drain capacity with a transiently
    failing replica: interactive p99 must hold its SLO, batch must shed
    via typed ``AdmissionRejected`` while still making its guaranteed
    minimum progress, and every submitted request must resolve (result or
    typed rejection — zero silent drops). All of it proven from the
    exporter (``/metrics`` + ``/tenants``) and the runlog, not from
    in-process state."""
    import json
    import threading
    import urllib.request

    import paddle_tpu as pt
    from paddle_tpu.observability import runlog as runlog_mod
    from paddle_tpu.observability.exporter import (
        MetricsServer,
        parse_text_exposition,
    )
    from paddle_tpu.observability.metrics import histogram_quantile
    from paddle_tpu.reader.feeder import FeedSpec
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import (
        AdmissionRejected,
        DeadlineExceeded,
        ServingConfig,
        ServingEngine,
        TenantConfig,
    )

    slo_p99_s = 0.5
    label = "chaos_overload"

    def net(x):
        return pt.layers.fc(x, size=3)

    rng = np.random.RandomState(seed)
    model = pt.build(net)
    variables = model.init(0, rng.randn(2, 5).astype(np.float32))
    engine = ServingEngine(
        model, variables, [FeedSpec("x", (5,), "float32")],
        config=ServingConfig(
            max_batch_size=4, max_queue_delay_s=0.002, num_replicas=2,
            engine_label=label,
            tenants=[
                TenantConfig("interactive", weight=4.0, queue_capacity=8),
                TenantConfig("batch", weight=1.0, queue_capacity=2,
                             default_class="batch"),
            ],
            batch_min_share=0.2,
        ),
    )
    prev_runlog = runlog_mod.set_runlog(
        runlog_mod.RunLog(os.path.join(work, "overload_runlog.jsonl")))
    server = MetricsServer(port=0).start()
    stop_at = time.monotonic() + 1.5
    stats_lock = threading.Lock()
    stats = {"interactive": {"attempts": 0, "ok": 0, "shed": 0, "late": 0},
             "batch": {"attempts": 0, "ok": 0, "shed": 0, "late": 0}}

    def bump(tenant, key, n=1):
        with stats_lock:
            stats[tenant][key] += n

    def interactive_client(ci):
        r = np.random.RandomState(1000 + ci)
        while time.monotonic() < stop_at:
            x = r.randn(1, 5).astype(np.float32)
            bump("interactive", "attempts")
            try:
                out = engine.infer({"x": x}, deadline_s=slo_p99_s,
                                   tenant="interactive")
                check(np.asarray(out).shape == (1, 3), "bad overload output")
                bump("interactive", "ok")
            except AdmissionRejected:
                bump("interactive", "shed")  # typed early shed, not a drop
            except DeadlineExceeded:
                bump("interactive", "late")  # typed late reject, not a drop

    def batch_client(ci):
        r = np.random.RandomState(2000 + ci)
        while time.monotonic() < stop_at:
            pendings = []
            for _ in range(4):  # burst past the batch queue quota
                x = r.randn(1, 5).astype(np.float32)
                bump("batch", "attempts")
                try:
                    pendings.append(engine.submit({"x": x}, tenant="batch"))
                except AdmissionRejected:
                    bump("batch", "shed")
            for p in pendings:
                check(np.asarray(p.result(timeout=30)).shape == (1, 3),
                      "bad batch output")
                bump("batch", "ok")

    try:
        with _inject(
            # replica 0 drops a few batches mid-overload: redispatch must
            # absorb it without surfacing request errors
            faults.FaultSpec(faults.SERVING_DISPATCH, "error",
                             after=5, times=3, match={"replica": 0}),
            seed=seed,
        ):
            threads = (
                [threading.Thread(target=interactive_client, args=(i,))
                 for i in range(10)]
                + [threading.Thread(target=batch_client, args=(i,))
                   for i in range(3)]
            )
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            check(not any(t.is_alive() for t in threads),
                  "overload clients failed to finish")

        # zero silent drops: every attempt resolved one way, all typed
        for tenant, s in stats.items():
            check(s["attempts"] == s["ok"] + s["shed"] + s["late"],
                  f"silent drop for {tenant}: {s}")
        check(stats["interactive"]["ok"] > 0, f"interactive starved: {stats}")
        check(stats["batch"]["shed"] >= 1,
              f"batch never shed under 10x overload: {stats}")
        # guaranteed-share floor: batch keeps completing under the flood
        check(stats["batch"]["ok"] >= 10,
              f"batch below its guaranteed drain share: {stats}")
        snap = engine.metrics.snapshot()
        check(snap["errors_total"] == 0,
              f"requests errored (redispatch failed to absorb faults): {snap}")

        # interactive p99 from the exporter, the way a dashboard sees it
        with urllib.request.urlopen(server.url + "/metrics", timeout=10) as r:
            fams = parse_text_exposition(r.read().decode("utf-8"))
        fam = fams["serving_tenant_request_latency_seconds"]
        series = sorted(
            (float(s[1]["le"]) if s[1]["le"] != "+Inf" else float("inf"),
             int(float(s[2])))
            for s in fam["samples"]
            if s[0].endswith("_bucket") and s[1].get("engine") == label
            and s[1].get("tenant") == "interactive"
        )
        check(bool(series), "no interactive latency series exported")
        edges = [le for le, _ in series if le != float("inf")]
        cums = [c for le, c in series if le != float("inf")]
        count = series[-1][1]
        p99 = histogram_quantile(edges, cums, count, 0.99)
        check(p99 <= slo_p99_s,
              f"interactive p99 {p99:.3f}s blew the {slo_p99_s}s SLO")

        # typed sheds accounted end to end: /tenants and the runlog agree
        # with what the clients saw
        client_sheds = stats["interactive"]["shed"] + stats["batch"]["shed"]
        with urllib.request.urlopen(server.url + "/tenants", timeout=10) as r:
            tenants_snap = [s for s in json.loads(r.read().decode())
                            if s["engine"] == label]
        check(len(tenants_snap) == 1, f"/tenants missing {label}")
        endpoint_sheds = sum(
            sum(t["shed_total"].values())
            for t in tenants_snap[0]["tenants"].values())
        check(endpoint_sheds == client_sheds,
              f"/tenants sheds {endpoint_sheds} != client {client_sheds}")
        events = runlog_mod.read_runlog(
            os.path.join(work, "overload_runlog.jsonl"))
        shed_events = [e for e in events if e["kind"] == "admission_shed"]
        check(len(shed_events) == client_sheds,
              f"runlog sheds {len(shed_events)} != client {client_sheds}")
        check(all(e.get("trace_id") for e in shed_events),
              "admission_shed events missing trace ids")
        print(f"[chaos] overload: interactive p99={p99 * 1e3:.1f}ms "
              f"(SLO {slo_p99_s * 1e3:.0f}ms), "
              f"batch ok={stats['batch']['ok']} shed={stats['batch']['shed']}, "
              f"sheds accounted={client_sheds}, drops=0")
    finally:
        server.close()
        engine.close(timeout=30)
        runlog_mod.set_runlog(prev_runlog)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dir", default=None,
                    help="work dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    args = ap.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="paddle_tpu_chaos_")
    root = os.path.join(work, "ckpt")
    try:
        _train_phase(root, args.seed)
        _corrupt_resume_phase(root, args.seed)
        _elastic_phase(work, args.seed)
        _serving_phase(args.seed)
        _deadlock_canary("serving")
        _decode_phase(work, args.seed)
        _deadlock_canary("decode")
        _spec_decode_phase(work, args.seed)
        _deadlock_canary("spec_decode")
        _disagg_phase(work, args.seed)
        _deadlock_canary("disagg")
        _host_tier_phase(work, args.seed)
        _deadlock_canary("host_tier")
        _shardgroup_phase(work, args.seed)
        _deadlock_canary("shardgroup")
        _overload_phase(work, args.seed)
        _deadlock_canary("overload")

        # coverage gate: a fault point nobody injects is a recovery path
        # nobody proves — new points must arrive with their chaos leg
        from paddle_tpu.resilience import faults
        missing = set(faults.registered_points()) - _EXERCISED_POINTS
        check(not missing,
              f"registered fault points never exercised: {sorted(missing)}")
    except ChaosFailure as e:
        print(f"[chaos] FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        if not args.keep and args.dir is None:
            shutil.rmtree(work, ignore_errors=True)
    print(f"[chaos] OK: every injected fault fired, every recovery held, "
          f"all {len(_EXERCISED_POINTS)} registered fault points exercised, "
          f"no lock-order violations")
    return 0


if __name__ == "__main__":
    sys.exit(main())

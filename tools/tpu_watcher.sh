#!/bin/bash
# TPU-window watcher: the axon tunnel flaps (r2: never up; r3: one ~80-min
# window). Probe every ~3 min all round; the moment the chip answers, run
# the harvest chain IN VALUE ORDER, committing each artifact as it lands so
# a mid-chain drop loses nothing. Steps are check-pointed via .harvest/*.done
# markers; an interrupted step reruns at the next window.
#
# Usage: nohup bash tools/tpu_watcher.sh >/dev/null 2>&1 &
cd /root/repo || exit 1
mkdir -p .harvest
LOG=.harvest/watcher.log
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache

log() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

probe() {
  # single-sourced roundtrip probe — see tools/tpu_probe.py for why a
  # device_get roundtrip (not block_until_ready) is the pass condition
  timeout 150 python tools/tpu_probe.py >> "$LOG" 2>&1
}

commit_paths() {  # $1 = message; rest = paths. Only commits those paths.
  local msg="$1"; shift
  for i in 1 2 3; do
    if git add -- "$@" >> "$LOG" 2>&1 && \
       git commit -m "$msg" -- "$@" >> "$LOG" 2>&1; then
      log "committed: $msg"; return 0
    fi
    sleep 7
  done
  log "commit FAILED: $msg"
  return 1
}

# run_step <name> <timeout_s> <done_grep_file> <done_grep_pat> <commit_msg> <artifact...> -- <cmd...>
run_step() {
  local name=$1 tmo=$2 gfile=$3 gpat=$4 msg=$5; shift 5
  local arts=()
  while [ "$1" != "--" ]; do arts+=("$1"); shift; done
  shift
  [ -e ".harvest/$name.done" ] && return 0
  log "step $name: starting (timeout ${tmo}s)"
  timeout "$tmo" "$@" >> "$LOG" 2>&1
  local rc=$?
  if [ -f "$gfile" ] && grep -q "$gpat" "$gfile"; then
    commit_paths "$msg" "${arts[@]}"
    touch ".harvest/$name.done"
    log "step $name: DONE (rc=$rc)"
    return 0
  fi
  log "step $name: incomplete (rc=$rc); will retry next window"
  # partial artifacts are still worth committing if they show tpu data
  if [ -f "$gfile" ] && grep -q '"platform": "tpu"' "$gfile" 2>/dev/null; then
    commit_paths "$msg (partial)" "${arts[@]}"
  fi
  return 1
}

harvest() {
  # 0. quickshot: resnet img/s + lm_large MFU, FIRST (~2 min warm) — the two
  # numbers the north star needs must survive even a window that dies right
  # after the probe (VERDICT r4 #1)
  run_step quickshot 700 BENCH_QUICK_TPU.json '"complete": true' \
    "TPU window: quickshot resnet img/s + lm_large MFU" \
    BENCH_QUICK_TPU.json -- python tools/tpu_quickshot.py || return 1
  # 1. smoke: numerics + steady-state throughput per family (~5-10 min)
  PT_SMOKE_BUDGET_S=600 run_step smoke 700 SMOKE_TPU.json '"complete": true' \
    "TPU window: smoke numerics + steady-state family throughput" \
    SMOKE_TPU.json -- python tests/tpu_smoke.py || return 1
  # 2. full bench: resnet50 sweep + lm_large MFU + flash A/B + decode + feed
  if [ ! -e .harvest/bench.done ]; then
    log "step bench: starting"
    PT_BENCH_BUDGET_S=1600 PT_BENCH_CHILD_CAP_S=1500 \
      timeout 1700 python bench.py > .harvest/bench_out.txt 2>> "$LOG"
    tail -n 1 .harvest/bench_out.txt > BENCH_TPU_LIVE.json
    if grep -q '"platform": "tpu"' BENCH_TPU_LIVE.json; then
      commit_paths "TPU window: live bench (resnet50 sweep, MFU, decode, feed)" \
        BENCH_TPU_LIVE.json
      touch .harvest/bench.done
      log "step bench: DONE"
    else
      log "step bench: no tpu result; will retry"
      return 1
    fi
  fi
  # 3. flash block autotune
  PT_TUNE_BUDGET_S=900 run_step flashtune 1000 FLASH_TUNE_TPU.json '"ok": true' \
    "TPU window: flash kernel block autotune + GQA/window A/B" \
    FLASH_TUNE_TPU.json -- python tests/tpu_flash_tune.py || return 1
  # 4. convergence to accuracy target
  PT_CONV_BUDGET_S=1200 run_step convergence 1300 CONVERGENCE_r05.json '"ok": true' \
    "TPU window: real-digits-to-97% (+ linear-probe floor) + cifar resnet loss curve on chip" \
    CONVERGENCE_r05.json -- python tests/tpu_convergence.py || return 1
  # 5. op parity catalog on chip
  run_step opparity 900 OP_PARITY_TPU.json '"complete": true' \
    "TPU window: op catalog TPU-vs-CPU parity" \
    OP_PARITY_TPU.json -- python tests/tpu_op_parity.py || return 1
  return 0
}

log "watcher started (pid $$)"
while true; do
  if [ -e .harvest/smoke.done ] && [ -e .harvest/bench.done ] && \
     [ -e .harvest/flashtune.done ] && [ -e .harvest/convergence.done ] && \
     [ -e .harvest/opparity.done ]; then
    log "all harvest steps done; watcher idling"
    sleep 1800
    continue
  fi
  if probe; then
    log "chip UP — harvesting"
    harvest && log "harvest chain complete" || log "harvest interrupted"
  fi
  sleep 170
done

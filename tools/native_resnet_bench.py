"""Native C++ predictor throughput: ResNet-50 bs16 infer, the PARITY.md
anchor config (reference MKL-DNN anchor: IntelOptimizedPaddle.md:93,
217.69 img/s on 2S/40-core Xeon 6148 ~= 5.4 img/s/core — a DERIVED
per-core figure assuming linear scaling; the measured rows below are the
defensible comparison).

Two numbers per config (VERDICT r4 #5):
- ``kernel_only``: the C ABI ``pt_predictor_run`` call alone, inputs
  pre-marshalled — what the compute kernels deliver;
- ``end_to_end``: fresh input copy (f64 source -> f32 contiguous, a real
  conversion per call, as a serving boundary pays) + run + output
  extraction — what a caller observes.

``--scaling`` re-execs this script at 1/2/4/all threads (the thread count
latches at first parallel_for) and prints a table; on a 1-core host the
rows collapse and the output says so.

    python tools/native_resnet_bench.py [--bs 16] [--iters 3] [--json]
    python tools/native_resnet_bench.py --scaling
"""
import argparse
import ctypes
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")


def measure(args) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import functools

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models.resnet import resnet_imagenet
    from paddle_tpu.native import NativePredictor
    from paddle_tpu.native.export import save_native_model

    # infer-only program (logits; no label gather — matches the serving
    # artifact io.save_inference_model(native=True) produces)
    net = pt.build(functools.partial(resnet_imagenet, class_dim=102,
                                     depth=args.depth))
    rng = np.random.RandomState(0)
    x = rng.rand(args.bs, 224, 224, 3).astype(np.float32)
    variables = net.init(0, x)
    if not args.no_bn_fold:
        # the documented serving recipe: fold BN into conv weights so the
        # export-time identity elimination removes all BN arithmetic (the
        # reference's inference_transpiler step precedes its MKL-DNN numbers)
        variables = pt.transpiler.inference.fuse_batch_norm(variables)

    res = {"bs": args.bs, "depth": args.depth,
           "threads": int(os.environ.get("PT_NATIVE_THREADS", "0"))}
    with tempfile.TemporaryDirectory() as td:
        save_native_model(net, variables, [x], td)
        pred = NativePredictor(td)
        pred.run(x)  # warmup (weight prepack caches populate)

        # kernel-only: the run call with inputs already marshalled
        arr = np.ascontiguousarray(x, dtype=np.float32)
        ptrs = (ctypes.POINTER(ctypes.c_float) * 1)(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )
        t0 = time.perf_counter()
        for _ in range(args.iters):
            rc = pred._lib.pt_predictor_run(pred._h, ptrs, 1)
            assert rc == 0
        dt_k = (time.perf_counter() - t0) / args.iters
        res["kernel_only_img_per_sec"] = round(args.bs / dt_k, 2)

        # end-to-end: a serving boundary pays an input conversion (f64
        # source -> f32 contiguous is a REAL copy; same-dtype
        # ascontiguousarray would be a no-op view) + output extraction
        src = x.astype(np.float64)
        out = None
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = pred.run(np.ascontiguousarray(src, dtype=np.float32))
        dt_e = (time.perf_counter() - t0) / args.iters
        res["end_to_end_img_per_sec"] = round(args.bs / dt_e, 2)
        res["marshalling_overhead_pct"] = round(100.0 * (dt_e - dt_k) / dt_e, 1)
        assert out is not None and out[0].shape[0] == args.bs
    return res


def scaling(argv_base):
    import multiprocessing

    ncores = multiprocessing.cpu_count()
    rows = []
    for t in (1, 2, 4, 0):
        env = {**os.environ, "PT_NATIVE_THREADS": str(t)}
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--json", *argv_base],
            env=env, capture_output=True, text=True, cwd=_REPO,
        )
        line = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else "{}"
        try:
            rows.append({**json.loads(line), "requested_threads": t})
        except json.JSONDecodeError:
            rows.append({"requested_threads": t, "error": p.stderr[-200:]})
    print(json.dumps({
        "host_cores": ncores,
        "note": ("single-core host: thread rows collapse to 1 core"
                 if ncores == 1 else "per-thread scaling on this host"),
        "rows": rows,
    }, indent=1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--threads", type=int, default=0, help="0 = all cores")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--scaling", action="store_true",
                    help="re-exec at 1/2/4/all threads and tabulate")
    ap.add_argument("--no-bn-fold", action="store_true",
                    help="skip fuse_batch_norm (the r4-early 1.64 img/s "
                         "baseline config; default applies the documented "
                         "serving recipe)")
    args = ap.parse_args()
    if args.threads:
        os.environ["PT_NATIVE_THREADS"] = str(args.threads)
    if args.scaling:
        base = [f"--bs={args.bs}", f"--iters={args.iters}", f"--depth={args.depth}"]
        if args.no_bn_fold:
            base.append("--no-bn-fold")
        return scaling(base)
    res = measure(args)
    if args.json:
        print(json.dumps(res))
    else:
        print(f"native resnet{args.depth} bs{args.bs}: "
              f"kernel-only {res['kernel_only_img_per_sec']} img/s, "
              f"end-to-end {res['end_to_end_img_per_sec']} img/s "
              f"({res['marshalling_overhead_pct']}% marshalling)")


if __name__ == "__main__":
    main()

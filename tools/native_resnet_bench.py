"""Native C++ predictor throughput: ResNet-50 bs16 infer, the PARITY.md
anchor config (reference MKL-DNN anchor: IntelOptimizedPaddle.md:93,
217.69 img/s on 2S/40-core Xeon 6148 ~= 5.4 img/s/core).

    python tools/native_resnet_bench.py [--bs 16] [--iters 3] [--depth 50]
"""
import argparse
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--threads", type=int, default=0, help="0 = all cores")
    ap.add_argument("--no-bn-fold", action="store_true",
                    help="skip fuse_batch_norm (the r4-early 1.64 img/s "
                         "baseline config; default applies the documented "
                         "serving recipe)")
    args = ap.parse_args()
    if args.threads:
        os.environ["PT_NATIVE_THREADS"] = str(args.threads)

    import functools

    import paddle_tpu as pt
    from paddle_tpu.models.resnet import resnet_imagenet
    from paddle_tpu.native import NativePredictor
    from paddle_tpu.native.export import save_native_model

    # infer-only program (logits; no label gather — matches the serving
    # artifact io.save_inference_model(native=True) produces)
    net = pt.build(functools.partial(resnet_imagenet, class_dim=102,
                                     depth=args.depth))
    rng = np.random.RandomState(0)
    x = rng.rand(args.bs, 224, 224, 3).astype(np.float32)
    variables = net.init(0, x)
    if not args.no_bn_fold:
        # the documented serving recipe: fold BN into conv weights so the
        # export-time identity elimination removes all BN arithmetic (the
        # reference's inference_transpiler step precedes its MKL-DNN numbers)
        variables = pt.transpiler.inference.fuse_batch_norm(variables)

    with tempfile.TemporaryDirectory() as td:
        save_native_model(net, variables, [x], td)
        pred = NativePredictor(td)
        out = pred.run(x)  # warmup
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = pred.run(x)
        dt = (time.perf_counter() - t0) / args.iters
        print(f"native resnet{args.depth} bs{args.bs}: "
              f"{args.bs / dt:.2f} img/s ({dt * 1e3:.0f} ms/batch)")
        return out


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Observability smoke gate: scrape a live run and validate what it tells.

Runs a short CPU training job (with one injected NaN step so a resilience
event lands in the runlog) and a short serving burst with the Prometheus
exporter enabled, then:

- GETs ``/metrics`` once and strictly parses the exposition
  (``observability.exporter.parse_text_exposition``): every sample typed,
  histogram ``le`` edges monotone with a ``+Inf`` terminal bucket,
  ``_sum``/``_count`` consistent;
- checks the core metric families are present and populated — trainer
  step-time and serving latency histograms, step/response counters,
  MFU and goodput gauges;
- checks ``/healthz`` answers;
- reads the runlog back (``observability.read_runlog``) and checks every
  event carries ``ts``/``kind``/``step`` and that step, compile,
  checkpoint, and resilience event kinds all showed up;
- exports the merged Chrome trace (``tracing.export_chrome_trace``) and
  reconstructs complete parented span trees from it — one serving request
  (enqueue → queue_wait → dispatch → execute → reply under a
  ``serving.request`` root) and one training step (data_wait / h2d /
  step_compute under ``trainer.step``) — with ``device.hbm.*`` gauges in
  the scrape and the ``/trace`` + ``/runlog/tail?n=`` debug endpoints
  answering;
- runs a two-engine disaggregated request (prefill role → CRC'd handoff
  → decode role) and a forced cross-engine migration, then reconstructs
  each request's span tree from ``/trace/<trace_id>``: ONE trace id
  spanning ≥2 engines, zero orphaned spans
  (``validate_trace(multi_engine=True)`` returns no problems), with
  ``/fleet`` serving the merged ``serving.fleet.*`` rollup and a chaos
  ``kill()`` leaving a complete flight-recorder bundle on disk;
- runs a speculative decode burst and checks the roofline +
  token-latency contracts: every ``/roofline`` ledger entry carries a
  compute/memory/overhead-bound verdict with finite arithmetic
  intensity, a finished request's ``/waterfall/<rid>`` timeline is
  monotone (TTFT then one TPOT sample per generated token, verify steps
  notwithstanding), and the Chrome trace re-exports with the
  ``roofline.achieved_g{flops,bytes}_per_s`` counter tracks.

Exit code 0 = the scrape parsed and every contract held; 1 = anything
missing or malformed. CI-registered next to ``tools/chaos_smoke.py``
(see README "Observability").

Usage:
    python tools/obs_smoke.py [--seed N] [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


class ObsFailure(AssertionError):
    """One of the observability contracts did not hold."""


def check(cond, msg: str) -> None:
    if not cond:
        raise ObsFailure(msg)


def _reader(n_batches=8, bs=8, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        w = np.array([[2.0], [-1.0], [0.5], [3.0]], np.float32)
        for _ in range(n_batches):
            x = rng.randn(bs, 4).astype(np.float32)
            yield x, x @ w + 0.1
    return reader


def _train_phase(work: str, seed: int) -> None:
    import paddle_tpu as pt
    from paddle_tpu.resilience import ResilienceConfig, faults

    def net(x, y):
        pred = pt.layers.fc(x, size=1)
        return pt.layers.mean((pred - y) ** 2)

    with faults.injected(
        # one NaN step so nan_skip + fault_injected land in the runlog
        faults.FaultSpec(faults.TRAINER_STEP, "nan", after=3, times=1),
        seed=seed,
    ) as plan:
        trainer = pt.Trainer(
            lambda: net, lambda: pt.optimizer.SGD(learning_rate=0.1),
            checkpoint_config=pt.CheckpointConfig(
                os.path.join(work, "ckpt"), step_interval=4),
            resilience=ResilienceConfig(nan_policy="skip_step"),
            observability=pt.ObservabilityConfig(
                metrics_port=0,  # ephemeral port, read back from server()
                runlog_path=os.path.join(work, "run.jsonl")),
        )
        trainer.train(num_epochs=1, reader=_reader(seed=seed))
        check(plan.all_fired(), f"NaN fault never fired: {plan.stats()}")
    print(f"[obs] train: {trainer.global_step} steps, "
          f"{trainer.bad_steps} skipped")


def _serving_phase(seed: int) -> list:
    import paddle_tpu as pt
    from paddle_tpu.reader.feeder import FeedSpec
    from paddle_tpu.serving import ServingConfig, ServingEngine

    def net(x):
        return pt.layers.fc(x, size=3)

    rng = np.random.RandomState(seed)
    model = pt.build(net)
    variables = model.init(0, rng.randn(2, 5).astype(np.float32))
    engine = ServingEngine(
        model, variables, [FeedSpec("x", (5,), "float32")],
        config=ServingConfig(max_batch_size=4, max_queue_delay_s=0.002),
    )
    trace_ids = []
    try:
        x = rng.randn(1, 5).astype(np.float32)
        for _ in range(20):
            pending = engine.submit({"x": x})
            out = pending.result()
            check(np.asarray(out).shape == (1, 3), "bad serving output")
            check(pending.trace is not None, "completed request has no trace")
            trace_ids.append(pending.trace.trace_id)
        print(f"[obs] serving: engine={engine.metrics.engine_label} "
              f"requests={engine.metrics.requests_total}")
    finally:
        unjoined = engine.close(timeout=30)
        check(not unjoined, f"threads failed to join on close: {unjoined}")
    return trace_ids


def _tune_phase(work: str) -> None:
    """Call-time kernel-tune lookups: one miss against an empty store, one
    hit against a persisted winner — populating the ``tune.cache.*``
    counter families the scrape phase asserts on ``/metrics``."""
    import importlib

    import paddle_tpu as pt
    from paddle_tpu.core import profiler as prof
    from paddle_tpu.tune import autotune as tune_autotune
    from paddle_tpu.tune import search as tune_search
    from paddle_tpu.tune.store import TuneKey

    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
    pt.core.config.set_flags(tune_cache_dir=os.path.join(work, "tune"),
                             autotune=True)
    try:
        tune_autotune.reset_lookup_cache()
        check(fa.resolve_blocks(256, 256) == fa.tuned_blocks(256, 256),
              "empty-store lookup must fall back to the tuned table")
        st = tune_autotune.get_store()
        key = TuneKey.render(
            tune_autotune.KERNEL, tune_search.shape_bucket(256), "-",
            tune_search.variant_tag(False), tune_autotune.device_kind())
        st.put(key, tune_autotune.flash_fingerprint(),
               {"block_q": 256, "block_k": 128}, ms=1.0, candidates=1)
        st.save()
        tune_autotune.reset_lookup_cache()
        check(fa.resolve_blocks(256, 256) == (256, 128),
              "persisted tune winner not served at call time")
    finally:
        pt.core.config.set_flags(tune_cache_dir="", autotune=False)
        tune_autotune.reset_lookup_cache()
    c = prof.counters()
    check(c.get("tune.cache.miss", 0) >= 1, "tune.cache.miss never counted")
    check(c.get("tune.cache.hit", 0) >= 1, "tune.cache.hit never counted")
    print(f"[obs] tune: miss={c.get('tune.cache.miss', 0):.0f} "
          f"hit={c.get('tune.cache.hit', 0):.0f}")


def _scrape_phase() -> None:
    import paddle_tpu as pt
    from paddle_tpu.observability.exporter import parse_text_exposition

    srv = pt.observability.server()
    check(srv is not None, "exporter not running after setup(metrics_port=0)")

    health = json.loads(urllib.request.urlopen(
        srv.url + "/healthz", timeout=10).read().decode("utf-8"))
    check(health == {"status": "ok"}, f"bad /healthz answer: {health}")

    body = urllib.request.urlopen(
        srv.url + "/metrics", timeout=10).read().decode("utf-8")
    families = parse_text_exposition(body)  # raises ExpositionError on bad text

    for fam, kind in (
        ("trainer_step_seconds", "histogram"),
        ("serving_request_latency_seconds", "histogram"),
        ("trainer_steps_total", "counter"),
        ("serving_responses_total", "counter"),
        ("executor_compiles_total", "counter"),
        ("tune_cache_hit", "counter"),
        ("tune_cache_miss", "counter"),
        ("checkpoint_saves_total", "counter"),
        ("trainer_mfu", "gauge"),
        ("trainer_goodput_frac", "gauge"),
        ("device_hbm_bytes_in_use", "gauge"),
        ("device_hbm_peak_bytes_in_use", "gauge"),
    ):
        check(fam in families, f"family {fam!r} missing from /metrics")
        check(families[fam]["type"] == kind,
              f"{fam}: type {families[fam]['type']!r} != {kind!r}")
        check(families[fam]["samples"], f"{fam}: no samples")

    def _value(fam):
        return families[fam]["samples"][0][2]

    check(_value("trainer_mfu") > 0, "trainer_mfu not positive")
    check(0.0 < _value("trainer_goodput_frac") <= 1.0,
          f"goodput out of range: {_value('trainer_goodput_frac')}")
    count = [v for (n, _, v) in families["trainer_step_seconds"]["samples"]
             if n == "trainer_step_seconds_count"]
    check(count and count[0] > 0, "trainer_step_seconds has no observations")
    print(f"[obs] scrape: {len(families)} families, "
          f"mfu={_value('trainer_mfu'):.2e} "
          f"goodput={_value('trainer_goodput_frac'):.3f}")


def _runlog_phase(work: str) -> None:
    from paddle_tpu.observability import read_runlog

    events = read_runlog(os.path.join(work, "run.jsonl"))
    check(bool(events), "runlog is empty")
    for e in events:
        check("ts" in e and "kind" in e and "step" in e,
              f"runlog event missing ts/kind/step: {e}")
    kinds = {e["kind"] for e in events}
    for want in ("step", "compile", "checkpoint_save", "nan_skip",
                 "fault_injected"):
        check(want in kinds, f"runlog missing {want!r} events (have {kinds})")
    step_ev = next(e for e in events if e["kind"] == "step")
    for field in ("loss", "step_time_s", "examples_per_sec"):
        check(field in step_ev, f"step event missing {field!r}: {step_ev}")
    print(f"[obs] runlog: {len(events)} events, kinds={sorted(kinds)}")


def _trace_phase(work: str, serving_traces: list) -> None:
    """Reconstruct full span trees — one serving request and one training
    step — from the MERGED Chrome-trace export (not the in-memory store):
    the export is what an engineer actually opens in Perfetto, so the
    contract is checked on that artifact."""
    import paddle_tpu as pt
    from paddle_tpu import tracing

    check(bool(serving_traces), "serving phase produced no trace ids")

    # in-memory trees must be structurally valid before export
    for tid in serving_traces:
        tree = tracing.spans_for_trace(tid)
        problems = tracing.validate_trace(tree)
        check(not problems, f"serving trace {tid} invalid: {problems}")

    path = os.path.join(work, "trace.json")
    tracing.export_chrome_trace(path)
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)  # must be valid JSON straight off disk
    counts = tracing.validate_chrome_trace(doc)

    def _tree(trace_id):
        """span_id -> event for one trace, from the exported doc."""
        return {
            ev["args"]["span_id"]: ev
            for ev in doc["traceEvents"]
            if ev.get("cat") == "tracing"
            and ev.get("args", {}).get("trace_id") == trace_id
        }

    def _check_tree(trace_id, root_name, want_names, label):
        by_id = _tree(trace_id)
        check(by_id, f"{label}: trace {trace_id} absent from export")
        roots = [e for e in by_id.values() if not e["args"].get("parent_id")]
        check(len(roots) == 1,
              f"{label}: expected 1 root, got {[e['name'] for e in roots]}")
        root = roots[0]
        check(root["name"] == root_name,
              f"{label}: root is {root['name']!r}, want {root_name!r}")
        names = {e["name"] for e in by_id.values()}
        missing = want_names - names
        check(not missing, f"{label}: spans missing from export: {missing}")
        for ev in by_id.values():
            parent = ev["args"].get("parent_id")
            check(parent is None or parent in by_id,
                  f"{label}: {ev['name']} has dangling parent {parent}")
            # monotonic + contained in the root's window
            check(ev["dur"] >= 0, f"{label}: {ev['name']} negative duration")
            check(ev["ts"] >= root["ts"] - 1
                  and ev["ts"] + ev["dur"] <= root["ts"] + root["dur"] + 1000,
                  f"{label}: {ev['name']} outside root window")
        return by_id

    # ≥1 serving request reconstructs end-to-end: enqueue → … → reply
    by_id = _check_tree(
        serving_traces[0], "serving.request",
        {"serving.enqueue", "serving.queue_wait", "serving.dispatch",
         "serving.execute", "serving.reply"},
        "serving",
    )
    order = {e["name"]: e["ts"] for e in by_id.values()}
    check(order["serving.enqueue"] <= order["serving.execute"]
          <= order["serving.reply"],
          f"serving: span order not monotonic: {order}")

    # ≥1 training step reconstructs with its phase children
    step_roots = [s for s in tracing.spans() if s.name == "trainer.step"]
    check(bool(step_roots), "no trainer.step traces recorded")
    _check_tree(
        step_roots[0].context.trace_id, "trainer.step",
        {"trainer.data_wait", "trainer.h2d", "trainer.step_compute"},
        "trainer",
    )

    # debug endpoints serve the same artifacts over HTTP
    srv = pt.observability.server()
    tail = json.loads(urllib.request.urlopen(
        srv.url + "/runlog/tail?n=5", timeout=10).read().decode("utf-8"))
    check(isinstance(tail, list) and 0 < len(tail) <= 5,
          f"/runlog/tail?n=5 returned {type(tail).__name__} len "
          f"{len(tail) if isinstance(tail, list) else '?'}")
    http_doc = json.loads(urllib.request.urlopen(
        srv.url + "/trace", timeout=30).read().decode("utf-8"))
    check("traceEvents" in http_doc, "/trace response has no traceEvents")
    print(f"[obs] trace: export valid ({counts}), serving + trainer trees "
          f"reconstructed, /trace + /runlog/tail answered")


def _fleet_phase(work: str, seed: int) -> None:
    """Fleet observability: one request's trace across ≥2 engines via the
    disagg handoff AND via a forced migration, the ``/fleet`` rollup, the
    ``/trace/<id>`` endpoint, and a flight-recorder bundle after a chaos
    ``kill()``."""
    import urllib.error

    import paddle_tpu as pt
    from paddle_tpu import models, tracing
    from paddle_tpu.observability import fleet as obs_fleet
    from paddle_tpu.observability import flight_recorder
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import (
        DecodeConfig,
        DecodeEngine,
        DecodeFleet,
        DisaggRouter,
    )
    from paddle_tpu.serving.disagg import DECODE, PREFILL

    vocab = 97
    spec = models.get_model("transformer_lm", seq_len=64, vocab=vocab,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    rng = np.random.RandomState(seed)
    variables = spec.model.init(0, *spec.synth_batch(2, rng))
    dc = dict(max_slots=3, page_size=4, max_context=40, prefill_chunk=8,
              num_pages=16, recovery_base_delay_s=0.001,
              recovery_max_delay_s=0.005, breaker_cooldown_s=0.05)
    prompt = rng.randint(1, vocab, size=(10,)).astype(np.int32)
    srv = pt.observability.server()
    check(srv is not None, "exporter not running for the fleet phase")

    def _http_trace_doc(trace_id):
        return json.loads(urllib.request.urlopen(
            srv.url + "/trace/" + trace_id, timeout=30
        ).read().decode("utf-8"))

    def _check_cross_engine(doc, want_names, label):
        check(doc["problems"] == [],
              f"{label}: trace {doc['trace_id']} has problems "
              f"(orphans/structure): {doc['problems']}")
        check(len(doc["engines"]) >= 2,
              f"{label}: trace touched {doc['engines']}, want >= 2 engines")
        tids = {s["trace_id"] for s in doc["spans"]}
        check(tids == {doc["trace_id"]},
              f"{label}: more than one trace id in the tree: {tids}")
        by_id = {s["span_id"]: s for s in doc["spans"]}
        for s in doc["spans"]:
            check(s["parent_id"] is None or s["parent_id"] in by_id,
                  f"{label}: span {s['name']} orphaned "
                  f"(parent {s['parent_id']} not in trace)")
        names = {s["name"] for s in doc["spans"]}
        missing = want_names - names
        check(not missing, f"{label}: spans missing: {missing} (have {names})")

    # -- prefill → handoff → decode across two engines --------------------
    pre = DecodeEngine(variables, cfg, decode=DecodeConfig(**dc))
    dec = DecodeEngine(variables, cfg, decode=DecodeConfig(**dc))
    router = DisaggRouter([pre, dec], [PREFILL, DECODE])
    view = obs_fleet.install(obs_fleet.FleetView(router, name="smoke"))
    try:
        h = router.submit(prompt, 8)
        h.result(timeout=120)
        check(h.trace is not None, "disagg request completed without a trace")
        doc = _http_trace_doc(h.trace.trace_id)
        _check_cross_engine(
            doc,
            {"serving.decode.queue_wait", "serving.decode.prefill",
             "serving.handoff.transfer", "serving.handoff.adopt",
             "serving.decode.request"},
            "handoff")

        # /fleet serves the merged rollup for the installed view
        fleet_doc = json.loads(urllib.request.urlopen(
            srv.url + "/fleet", timeout=30).read().decode("utf-8"))
        check(isinstance(fleet_doc, list) and len(fleet_doc) == 1,
              f"/fleet: want one installed view, got {fleet_doc!r:.200}")
        roll = fleet_doc[0]["rollup"]
        for key in ("engines", "engines_healthy", "prefix_hit_frac",
                    "host_tier_hit_rate", "handoffs_total", "rescued_total"):
            check(key in roll, f"/fleet rollup missing {key!r}: {roll}")
        check(roll["engines"] == 2 and roll["engines_healthy"] == 2,
              f"/fleet rollup engine counts wrong: {roll}")
        check(roll["handoffs_total"] >= 1,
              f"/fleet rollup saw no handoffs: {roll}")
        from paddle_tpu.observability import metrics as obs_metrics
        reg = obs_metrics.default_registry()
        check(reg.get("serving.fleet.engines",
                      labels={"fleet": "smoke"}, default=None) == 2.0,
              "serving.fleet.engines gauge not published")
    finally:
        obs_fleet.uninstall(view)
        router.close(60)

    # -- forced migration + chaos kill() + flight recorder -----------------
    rec = flight_recorder.install(flight_recorder.FlightRecorder(
        os.path.join(work, "flightrec"), keep=4))
    ea = DecodeEngine(variables, cfg, decode=DecodeConfig(**dc))
    eb = DecodeEngine(variables, cfg, decode=DecodeConfig(**dc))
    fleet = DecodeFleet([ea, eb])
    try:
        with faults.injected(
            faults.FaultSpec(faults.DECODE_STEP, "error", after=1,
                             times=10 ** 9,
                             match={"engine": ea.metrics.engine_label}),
            seed=seed,
        ):
            mh = ea.submit(prompt, 8)  # pin to A; A's breaker will trip
            mh.result(timeout=120)
        check(mh.trace is not None, "migrated request has no trace")
        mdoc = _http_trace_doc(mh.trace.trace_id)
        _check_cross_engine(
            mdoc,
            {"serving.decode.queue_wait", "serving.rescue",
             "serving.decode.request"},
            "migration")

        eb.kill()  # chaos: the flight recorder must capture the wreck
        bundles = rec.bundles()
        check(bool(bundles), "no flight-recorder bundle after kill()")
        with open(bundles[-1], "r", encoding="utf-8") as f:
            bundle = json.load(f)
        check(bundle["reason"] == "kill",
              f"last bundle reason {bundle['reason']!r}, want 'kill'")
        for key in ("spans", "runlog", "locks", "breaker", "metrics",
                    "kv_refcounts", "engine"):
            check(key in bundle, f"flight bundle missing {key!r}")
        check(bundle["engine"] == eb.metrics.engine_label,
              f"bundle engine {bundle['engine']!r} != killed engine")
        reasons = {json.load(open(p))["reason"] for p in bundles}
        check("breaker_trip" in reasons,
              f"breaker trip left no bundle (have {reasons})")
        print(f"[obs] fleet: handoff trace {doc['trace_id'][:8]}… over "
              f"{doc['engines']}, migration trace {mdoc['trace_id'][:8]}… "
              f"over {mdoc['engines']}, {len(bundles)} flight bundles")
    finally:
        flight_recorder.uninstall()
        fleet.close(timeout=30)


def _roofline_phase(work: str, seed: int) -> None:
    """Roofline + waterfall contracts on a live speculative decode run:
    every ``/roofline`` ledger entry classified with finite intensity, a
    finished request's ``/waterfall/<rid>`` timeline monotone with one
    TPOT sample per generated token after the first (speculation-aware),
    and the Chrome trace re-exporting with the roofline counter tracks."""
    import urllib.error

    import paddle_tpu as pt
    from paddle_tpu import models, tracing
    from paddle_tpu.serving import DecodeConfig, DecodeEngine
    from paddle_tpu.tracing import waterfall

    srv = pt.observability.server()
    check(srv is not None, "exporter not running for the roofline phase")

    vocab = 97
    spec = models.get_model("transformer_lm", seq_len=64, vocab=vocab,
                            d_model=32, d_inner=64, num_heads=4, n_layers=2)
    cfg = spec.extra["cfg"]
    rng = np.random.RandomState(seed)
    variables = spec.model.init(0, *spec.synth_batch(2, rng))
    eng = DecodeEngine(variables, cfg, decode=DecodeConfig(
        max_slots=3, page_size=4, max_context=48, prefill_chunk=8,
        num_pages=24, spec_tokens=4), draft_variables=variables,
        draft_cfg=cfg)
    label = eng.metrics.engine_label
    n_new = 10
    try:
        prompt = rng.randint(1, vocab, size=(6,)).astype(np.int32)
        out = eng.infer(prompt, n_new)
        check(len(out.tokens) > 1,
              f"speculative decode generated {len(out.tokens)} tokens")
    finally:
        eng.close()

    # -- /roofline: every ledger entry classified, intensity finite -------
    roof = json.loads(urllib.request.urlopen(
        srv.url + "/roofline", timeout=30).read().decode("utf-8"))
    check(roof.get("enabled") is True, "/roofline reports ledger disabled")
    entries = roof.get("entries", [])
    check(bool(entries), "/roofline has no ledger entries after decode")
    kernels = {e["kernel"] for e in entries}
    for want in ("serving.decode.prefill", "serving.decode.verify"):
        check(want in kernels, f"/roofline missing {want!r} (have {kernels})")
    for e in entries:
        check(e.get("verdict") in ("compute_bound", "memory_bound",
                                   "overhead_bound"),
              f"/roofline entry {e.get('key')} unclassified: "
              f"{e.get('verdict')!r}")
        intensity = e.get("arithmetic_intensity")
        check(isinstance(intensity, (int, float)) and np.isfinite(intensity),
              f"/roofline entry {e.get('key')} intensity not finite: "
              f"{intensity!r}")
        check(len(e["key"].split("|")) == 4,
              f"/roofline key not kernel|bucket|dtype|kind: {e['key']!r}")
    summary = roof.get("summary", {})
    check(summary.get("entries") == len(entries),
          f"/roofline summary entries {summary.get('entries')} != "
          f"{len(entries)}")

    # -- /waterfall/<rid>: monotone TTFT → TPOT, one sample per token ----
    rid = next((r for r in reversed(waterfall.rids(finished_only=True))
                if (waterfall.doc(r) or {}).get("engine") == label), None)
    check(rid is not None, "no finished waterfall doc for the decode engine")
    wf = json.loads(urllib.request.urlopen(
        srv.url + "/waterfall/" + rid, timeout=30).read().decode("utf-8"))
    check(wf["finished"] and wf["reason"] in ("eos", "length"),
          f"waterfall {rid} not cleanly finished: {wf['reason']!r}")
    check(wf["ttft_s"] is not None and wf["ttft_s"] >= 0,
          f"waterfall {rid} has no TTFT")
    check(wf["tokens"] == len(out.tokens),
          f"waterfall tokens {wf['tokens']} != generated {len(out.tokens)}")
    check(len(wf["tpot_s"]) == len(out.tokens) - 1,
          f"TPOT samples {len(wf['tpot_s'])} != generated tokens - 1 "
          f"({len(out.tokens) - 1}) — speculation must book per-token, "
          f"not per-verify-step")
    check(wf["t_submit_pc"] <= wf["t_first_token_pc"]
          <= wf["t_last_token_pc"],
          f"waterfall {rid} timeline not monotone: submit/first/last = "
          f"{wf['t_submit_pc']}/{wf['t_first_token_pc']}/"
          f"{wf['t_last_token_pc']}")
    ts = [e["t_pc"] for e in wf["events"]]
    check(ts == sorted(ts), f"waterfall {rid} events not monotone")
    phases = [e["phase"] for e in wf["events"]]
    check(phases[0] == "prefill" and phases[-1] == "finish",
          f"waterfall {rid} phases not prefill→…→finish: {phases}")
    check(wf["tpot"]["count"] == len(wf["tpot_s"]),
          "waterfall tpot stats disagree with the sample list")
    # unknown rid → 404, not an empty doc
    try:
        urllib.request.urlopen(srv.url + "/waterfall/no-such-rid-0",
                               timeout=10)
        check(False, "/waterfall/<unknown> did not 404")
    except urllib.error.HTTPError as e:
        check(e.code == 404, f"/waterfall/<unknown> returned {e.code}")

    # -- Chrome trace re-export carries the roofline counter tracks ------
    path = os.path.join(work, "trace_roofline.json")
    tracing.export_chrome_trace(path)
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    counts = tracing.validate_chrome_trace(doc)
    names = {ev["name"] for ev in doc["traceEvents"] if ev.get("ph") == "C"}
    for want in ("roofline.achieved_gflops_per_s",
                 "roofline.achieved_gbytes_per_s"):
        check(want in names,
              f"Chrome trace missing counter track {want!r} (have {names})")
    print(f"[obs] roofline: {len(entries)} ledger entries classified "
          f"({summary.get('verdicts')}), waterfall {rid[:16]}… "
          f"ttft={wf['ttft_s']*1e3:.1f}ms + {len(wf['tpot_s'])} tpot "
          f"samples, trace counter tracks valid ({counts.get('C', 0)} C "
          f"events)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dir", default=None,
                    help="work dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    args = ap.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="paddle_tpu_obs_")
    try:
        _train_phase(work, args.seed)
        serving_traces = _serving_phase(args.seed)
        _tune_phase(work)
        _scrape_phase()
        _runlog_phase(work)
        _trace_phase(work, serving_traces)
        _fleet_phase(work, args.seed)
        _roofline_phase(work, args.seed)
    except ObsFailure as e:
        print(f"[obs] FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        import paddle_tpu as pt

        pt.observability.shutdown()
        if not args.keep and args.dir is None:
            shutil.rmtree(work, ignore_errors=True)
    print("[obs] OK: exposition valid, families populated, runlog complete, "
          "traces reconstruct, fleet rollup + flight recorder verified, "
          "roofline + waterfall contracts hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Observability smoke gate: scrape a live run and validate what it tells.

Runs a short CPU training job (with one injected NaN step so a resilience
event lands in the runlog) and a short serving burst with the Prometheus
exporter enabled, then:

- GETs ``/metrics`` once and strictly parses the exposition
  (``observability.exporter.parse_text_exposition``): every sample typed,
  histogram ``le`` edges monotone with a ``+Inf`` terminal bucket,
  ``_sum``/``_count`` consistent;
- checks the core metric families are present and populated — trainer
  step-time and serving latency histograms, step/response counters,
  MFU and goodput gauges;
- checks ``/healthz`` answers;
- reads the runlog back (``observability.read_runlog``) and checks every
  event carries ``ts``/``kind``/``step`` and that step, compile,
  checkpoint, and resilience event kinds all showed up.

Exit code 0 = the scrape parsed and every contract held; 1 = anything
missing or malformed. CI-registered next to ``tools/chaos_smoke.py``
(see README "Observability").

Usage:
    python tools/obs_smoke.py [--seed N] [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


class ObsFailure(AssertionError):
    """One of the observability contracts did not hold."""


def check(cond, msg: str) -> None:
    if not cond:
        raise ObsFailure(msg)


def _reader(n_batches=8, bs=8, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        w = np.array([[2.0], [-1.0], [0.5], [3.0]], np.float32)
        for _ in range(n_batches):
            x = rng.randn(bs, 4).astype(np.float32)
            yield x, x @ w + 0.1
    return reader


def _train_phase(work: str, seed: int) -> None:
    import paddle_tpu as pt
    from paddle_tpu.resilience import ResilienceConfig, faults

    def net(x, y):
        pred = pt.layers.fc(x, size=1)
        return pt.layers.mean((pred - y) ** 2)

    with faults.injected(
        # one NaN step so nan_skip + fault_injected land in the runlog
        faults.FaultSpec(faults.TRAINER_STEP, "nan", after=3, times=1),
        seed=seed,
    ) as plan:
        trainer = pt.Trainer(
            lambda: net, lambda: pt.optimizer.SGD(learning_rate=0.1),
            checkpoint_config=pt.CheckpointConfig(
                os.path.join(work, "ckpt"), step_interval=4),
            resilience=ResilienceConfig(nan_policy="skip_step"),
            observability=pt.ObservabilityConfig(
                metrics_port=0,  # ephemeral port, read back from server()
                runlog_path=os.path.join(work, "run.jsonl")),
        )
        trainer.train(num_epochs=1, reader=_reader(seed=seed))
        check(plan.all_fired(), f"NaN fault never fired: {plan.stats()}")
    print(f"[obs] train: {trainer.global_step} steps, "
          f"{trainer.bad_steps} skipped")


def _serving_phase(seed: int) -> None:
    import paddle_tpu as pt
    from paddle_tpu.reader.feeder import FeedSpec
    from paddle_tpu.serving import ServingConfig, ServingEngine

    def net(x):
        return pt.layers.fc(x, size=3)

    rng = np.random.RandomState(seed)
    model = pt.build(net)
    variables = model.init(0, rng.randn(2, 5).astype(np.float32))
    engine = ServingEngine(
        model, variables, [FeedSpec("x", (5,), "float32")],
        config=ServingConfig(max_batch_size=4, max_queue_delay_s=0.002),
    )
    try:
        x = rng.randn(1, 5).astype(np.float32)
        for _ in range(20):
            out = engine.infer({"x": x})
            check(np.asarray(out).shape == (1, 3), "bad serving output")
        print(f"[obs] serving: engine={engine.metrics.engine_label} "
              f"requests={engine.metrics.requests_total}")
    finally:
        unjoined = engine.close(timeout=30)
        check(not unjoined, f"threads failed to join on close: {unjoined}")


def _scrape_phase() -> None:
    import paddle_tpu as pt
    from paddle_tpu.observability.exporter import parse_text_exposition

    srv = pt.observability.server()
    check(srv is not None, "exporter not running after setup(metrics_port=0)")

    health = json.loads(urllib.request.urlopen(
        srv.url + "/healthz", timeout=10).read().decode("utf-8"))
    check(health == {"status": "ok"}, f"bad /healthz answer: {health}")

    body = urllib.request.urlopen(
        srv.url + "/metrics", timeout=10).read().decode("utf-8")
    families = parse_text_exposition(body)  # raises ExpositionError on bad text

    for fam, kind in (
        ("trainer_step_seconds", "histogram"),
        ("serving_request_latency_seconds", "histogram"),
        ("trainer_steps_total", "counter"),
        ("serving_responses_total", "counter"),
        ("executor_compiles_total", "counter"),
        ("checkpoint_saves_total", "counter"),
        ("trainer_mfu", "gauge"),
        ("trainer_goodput_frac", "gauge"),
    ):
        check(fam in families, f"family {fam!r} missing from /metrics")
        check(families[fam]["type"] == kind,
              f"{fam}: type {families[fam]['type']!r} != {kind!r}")
        check(families[fam]["samples"], f"{fam}: no samples")

    def _value(fam):
        return families[fam]["samples"][0][2]

    check(_value("trainer_mfu") > 0, "trainer_mfu not positive")
    check(0.0 < _value("trainer_goodput_frac") <= 1.0,
          f"goodput out of range: {_value('trainer_goodput_frac')}")
    count = [v for (n, _, v) in families["trainer_step_seconds"]["samples"]
             if n == "trainer_step_seconds_count"]
    check(count and count[0] > 0, "trainer_step_seconds has no observations")
    print(f"[obs] scrape: {len(families)} families, "
          f"mfu={_value('trainer_mfu'):.2e} "
          f"goodput={_value('trainer_goodput_frac'):.3f}")


def _runlog_phase(work: str) -> None:
    from paddle_tpu.observability import read_runlog

    events = read_runlog(os.path.join(work, "run.jsonl"))
    check(bool(events), "runlog is empty")
    for e in events:
        check("ts" in e and "kind" in e and "step" in e,
              f"runlog event missing ts/kind/step: {e}")
    kinds = {e["kind"] for e in events}
    for want in ("step", "compile", "checkpoint_save", "nan_skip",
                 "fault_injected"):
        check(want in kinds, f"runlog missing {want!r} events (have {kinds})")
    step_ev = next(e for e in events if e["kind"] == "step")
    for field in ("loss", "step_time_s", "examples_per_sec"):
        check(field in step_ev, f"step event missing {field!r}: {step_ev}")
    print(f"[obs] runlog: {len(events)} events, kinds={sorted(kinds)}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dir", default=None,
                    help="work dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    args = ap.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="paddle_tpu_obs_")
    try:
        _train_phase(work, args.seed)
        _serving_phase(args.seed)
        _scrape_phase()
        _runlog_phase(work)
    except ObsFailure as e:
        print(f"[obs] FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        import paddle_tpu as pt

        pt.observability.shutdown()
        if not args.keep and args.dir is None:
            shutil.rmtree(work, ignore_errors=True)
    print("[obs] OK: exposition valid, families populated, runlog complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Inspect an exported native serving program.

Prints per-primitive op counts, const payload sizes, and the live-value
high-water mark for a ``program.txt`` produced by
``paddle_tpu.native.export.export_program``. With ``--verify`` the full
IR verifier (``paddle_tpu.analysis.verifier``) runs too and the process
exits non-zero on any error diagnostic — usable as a CI gate over
exported artifacts.

Usage:
    python tools/lint_program.py EXPORT_DIR [--verify] [--top N]
"""

from __future__ import annotations

import argparse
import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.native.passes import Program  # noqa: E402


def _load(path: str):
    prog_path = os.path.join(path, "program.txt") if os.path.isdir(path) else path
    with open(prog_path, "r", encoding="utf-8") as f:
        text = f.read()
    weights = b""
    wpath = os.path.join(os.path.dirname(prog_path), "weights.bin")
    if os.path.exists(wpath):
        with open(wpath, "rb") as f:
            weights = f.read()
    return Program.parse(text, weights)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="export directory (or program.txt path)")
    ap.add_argument("--verify", action="store_true",
                    help="run the IR verifier; exit 1 on errors")
    ap.add_argument("--top", type=int, default=12,
                    help="show the N most frequent primitives")
    args = ap.parse_args(argv)

    prog = _load(args.path)
    kinds = collections.Counter(it.kind for it in prog.items)
    prims = collections.Counter(it.prim for it in prog.items if it.kind == "op")

    print(f"{prog.header.strip() or '(no header)'}")
    print(f"lines: {len(prog.items)}  inputs: {kinds['input']}  "
          f"consts: {kinds['const']}  ops: {kinds['op']}  "
          f"outputs: {kinds['output']}")
    print(f"weights.bin: {len(prog.weights)} bytes")
    if prims:
        print("top primitives:")
        for prim, n in prims.most_common(args.top):
            print(f"  {prim:24s} {n}")

    if args.verify:
        from paddle_tpu.analysis.diagnostics import format_diagnostics, has_errors
        from paddle_tpu.analysis.verifier import verify_program

        diags = verify_program(prog)
        if diags:
            print(format_diagnostics(diags))
        if has_errors(diags):
            print("verification FAILED")
            return 1
        print("verification OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

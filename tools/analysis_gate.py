#!/usr/bin/env python
"""Static-analysis CI gate: shard-layout analyzer + retrace lint.

Three legs, all zero-FLOP (no devices are touched anywhere):

1. **Shipped layout is clean** — ``analysis.shard_analysis.analyze_model``
   runs the ``default_layout()`` over ``transformer_lm``'s
   ``jax.eval_shape`` param tree at tp ∈ {1, 2, 4}: ZERO findings
   allowed, and the comm report must show exactly the Megatron boundary
   set (one all-reduce per row-parallel weight — 2 × n_layers).
2. **Seeded violations are caught** — a deliberately broken layout (dead
   rule, rank mismatch, silent degrade, cross-layout conflict, sharded
   KV page ids) must produce EXACTLY the expected stable diagnostic
   codes; a gate that cannot see a planted bug proves nothing.
3. **Tree is retrace-clean** — ``analysis.retrace_lint`` over the whole
   package reports no errors, and a reconstructed dynamic-closure
   retrace bug (the trap the compile-once invariant exists to stop) is
   caught in a fixture.

Exit code 0 = every leg held; 1 = anything less. CI-registered next to
``tools/chaos_smoke.py`` and ``tools/perf_gate.py`` (README "Static
analysis").
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILURES = []


def _check(ok: bool, label: str, detail: str = "") -> None:
    status = "ok" if ok else "FAIL"
    print(f"[analysis_gate] {status:4s} {label}" + (f" — {detail}" if detail and not ok else ""))
    if not ok:
        _FAILURES.append(label)


def leg_shipped_layout_clean() -> None:
    from paddle_tpu.analysis.shard_analysis import analyze_model

    for tp in (1, 2, 4):
        diags, report = analyze_model(tp=tp)
        _check(diags == [],
               f"default_layout() clean on transformer_lm @ tp={tp}",
               "; ".join(str(d) for d in diags))
        n_layers = 6  # transformer_lm BASE_CFG
        _check(len(report.boundaries) == 2 * n_layers,
               f"comm report has {2 * n_layers} row-parallel boundaries @ tp={tp}",
               f"got {len(report.boundaries)}")
        if tp == 4:
            print(report.format())


def leg_seeded_violations_caught() -> None:
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.analysis.shard_analysis import (
        analyze_layout,
        compare_layouts,
    )
    from paddle_tpu.serving.shardgroup import GroupLayout

    params = {
        "layer_0/self_attn/q/w": (512, 512),
        "layer_0/self_attn/q/b": (512,),
        "emb/embedding/word_emb": (97, 512),
    }
    axes = {"tp": 4}

    bad = GroupLayout(rules=(
        ("*/self_attn/qq/w", P(None, "tp")),   # dead rule (typo)
        ("*/self_attn/q/b", P(None, "tp")),    # rank mismatch on 1-d bias
        ("emb/*", P("tp", None)),              # 97 % 4: silent degrade
    ), optional=())
    got = sorted(d.code for d in analyze_layout(params, bad, axes))
    want = ["shard-dead-rule", "shard-rank-mismatch", "shard-silent-degrade"]
    _check(got == want, "seeded bad layout yields exact codes",
           f"want {want}, got {got}")

    serving = GroupLayout(rules=(("*/q/w", P(None, "tp")),), optional=())
    training = GroupLayout(rules=(("*/q/w", P("tp", None)),), optional=())
    conf = compare_layouts({"serving": serving, "training": training},
                           params, axes)
    _check([d.code for d in conf] == ["shard-conflict"],
           "cross-layout conflict detected",
           f"got {[d.code for d in conf]}")

    kv_bad = GroupLayout(rules=(), optional=(),
                         kv_rule=P(None, "tp", None, None, None))
    kv = analyze_layout(
        {}, kv_bad, {"tp": 2}, kv_page_shape=(2, 14, 4, 4, 8),
        kv_geometry={"num_pages": 14, "page_size": 4})
    _check([d.code for d in kv] == ["shard-kv-geometry"],
           "sharded KV page ids rejected",
           f"got {[d.code for d in kv]}")


def leg_tree_retrace_clean() -> None:
    from paddle_tpu.analysis.retrace_lint import lint_file, lint_retrace

    diags = [d for d in lint_retrace() if d.severity == "error"]
    _check(diags == [], "whole tree retrace-lints clean",
           "; ".join(str(d) for d in diags))

    fixture = (
        "import jax\n"
        "pending = []\n"
        "def step(params, tokens):\n"
        "    return params, tokens[: len(pending)]\n"
        "def serve(params, reqs):\n"
        "    for r in reqs:\n"
        "        f = jax.jit(step)\n"
        "        params, _ = f(params, r)\n"
    )
    codes = sorted(d.code for d in lint_file("fixture.py", fixture))
    want = ["retrace-dynamic-len", "retrace-jit-in-loop"]
    _check(codes == want, "dynamic-closure retrace bug caught in fixture",
           f"want {want}, got {codes}")


def main(argv=None) -> int:
    leg_shipped_layout_clean()
    leg_seeded_violations_caught()
    leg_tree_retrace_clean()
    if _FAILURES:
        print(f"[analysis_gate] FAILED: {len(_FAILURES)} check(s): "
              + ", ".join(_FAILURES))
        return 1
    print("[analysis_gate] all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())

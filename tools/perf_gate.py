#!/usr/bin/env python
"""Perf-regression gate: diff a fresh ``bench.py`` JSON line against the
persistent baseline store and exit non-zero on regression.

The reference framework's answer to "did this change slow us down?" was a
human reading ``FLAGS_benchmark`` timer logs; here the bench artifact is
structured (one JSON object) and the baselines are rolling statistics
(``paddle_tpu.watch.baseline.BaselineStore``), so the comparison is a CI
gate instead of an eyeball:

- every numeric top-level bench metric is classified by name —
  throughput-shaped (``*_per_sec*``, ``mfu``, ``goodput_frac``) must not
  drop, time-shaped (``*_ms*``, ``*_seconds``) must not grow, anything
  else is informational;
- the allowed band per metric is ``max(--noise-band, 2 * stddev)`` of the
  stored rolling stats, so noisy metrics earn wider bands from their own
  history instead of a hand-tuned global fudge factor;
- baselines are keyed by ``(metric, "-", "-", device_kind)`` — a CPU
  fallback run is never judged against TPU numbers;
- metrics with no stored baseline report ``new`` and never fail;
  ``--update`` folds the run into the store afterwards (tmp+rename, so a
  crashed gate never leaves a torn store).

Exit 0: no metric regressed beyond its band. Exit 1: at least one did
(or the inputs were unreadable). One JSON summary line on stdout either
way; the per-metric table goes to stderr.

Usage:
    python tools/perf_gate.py --baseline perf_baseline.json \
        --bench-json BENCH.json [--update] [--noise-band 0.25]
    bench.py | python tools/perf_gate.py --baseline perf_baseline.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# metadata / non-judgeable top-level keys in a bench JSON line
_SKIP_KEYS = {
    "metric", "unit", "notes", "platform", "device_kind", "phase_breakdown",
    "vs_baseline", "vs_v100_target", "resnet_batch_size",
    "decode_scan_layers",
}


def load_bench_line(source: str) -> dict:
    """Parse the bench JSON object from a file path, a literal JSON string,
    or stdin (``-``). For multi-line input, the LAST parseable JSON object
    with a ``metric`` field wins (bench children checkpoint interim lines)."""
    if source == "-":
        text = sys.stdin.read()
    elif source.lstrip().startswith("{"):
        text = source
    else:
        with open(source) as f:
            text = f.read()
    found = None
    for line in text.strip().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            found = parsed
    if found is None:
        raise ValueError(f"no bench JSON object found in {source!r}")
    return found


def judge(bench: dict, store, noise_band: float) -> list:
    """One verdict dict per judgeable metric (see BaselineStore.check)."""
    from paddle_tpu.watch import baseline as bl

    device_kind = str(bench.get("device_kind", "-")) or "-"
    verdicts = []
    for key, value in bench.items():
        if key in _SKIP_KEYS or not isinstance(value, (int, float)):
            continue
        if isinstance(value, bool):
            continue
        # "value" is the headline metric: judge it under its real name
        name = str(bench.get("metric", "value")) if key == "value" else key
        direction = bl.metric_direction(name)
        verdicts.append(store.check(
            name, float(value), device_kind=device_kind,
            noise_band=noise_band, direction=direction))
    return verdicts


def apply_update(bench: dict, store) -> int:
    from paddle_tpu.watch import baseline as bl  # noqa: F401 (same keying)

    device_kind = str(bench.get("device_kind", "-")) or "-"
    n = 0
    for key, value in bench.items():
        if key in _SKIP_KEYS or not isinstance(value, (int, float)):
            continue
        if isinstance(value, bool):
            continue
        name = str(bench.get("metric", "value")) if key == "value" else key
        store.update(name, float(value), device_kind=device_kind)
        n += 1
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", required=True,
                    help="baseline store JSON (created empty if missing)")
    ap.add_argument("--bench-json", default="-",
                    help="bench JSON line: file path, literal JSON, or - "
                         "for stdin (default)")
    ap.add_argument("--noise-band", type=float, default=0.25,
                    help="minimum allowed relative delta before a "
                         "directional metric counts as changed (default "
                         "0.25 = 25%%)")
    ap.add_argument("--update", action="store_true",
                    help="fold this run into the baseline store (after "
                         "judging against the PRE-update baselines)")
    args = ap.parse_args(argv)

    from paddle_tpu.watch.baseline import BaselineStore

    summary = {"gate": "perf_gate", "baseline": args.baseline,
               "regressions": [], "improved": [], "new": [], "ok": []}
    try:
        bench = load_bench_line(args.bench_json)
        store = BaselineStore(args.baseline)
        verdicts = judge(bench, store, args.noise_band)
    except Exception as e:
        summary["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(summary))
        print(f"perf_gate: FAILED to judge: {e}", file=sys.stderr)
        return 1

    for v in verdicts:
        name = v["key"].split("|", 1)[0]
        bucket = {"regression": "regressions", "improved": "improved",
                  "new": "new", "ok": "ok"}[v["verdict"]]
        summary[bucket].append(name)
        if v["verdict"] == "ok" and v.get("direction") == "info":
            continue  # keep the stderr table signal-dense
        base = v.get("baseline")
        delta = v.get("delta_frac")
        print(
            f"perf_gate: {v['verdict']:<10} {name:<40} "
            f"value={v['value']:.6g}"
            + (f" baseline={base:.6g}" if base is not None else "")
            + (f" delta={delta:+.1%}" if delta is not None else "")
            + (f" band=±{v['tolerance_frac']:.1%}"
               if v.get("tolerance_frac") is not None else ""),
            file=sys.stderr)

    if args.update:
        n = apply_update(bench, store)
        store.save()
        summary["updated_metrics"] = n
        print(f"perf_gate: baseline updated with {n} metrics "
              f"-> {args.baseline}", file=sys.stderr)

    failed = bool(summary["regressions"])
    summary["status"] = "fail" if failed else "pass"
    print(json.dumps(summary))
    print(f"perf_gate: {summary['status'].upper()} "
          f"({len(summary['regressions'])} regression(s), "
          f"{len(summary['improved'])} improved, {len(summary['new'])} new, "
          f"{len(summary['ok'])} ok)", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

"""Single-sourced TPU liveness probe: exits 0 iff the default backend is a
real chip AND a compiled matmul completes a device_get ROUNDTRIP.

block_until_ready can return before any data flows on the axon tunnel
(observed r3/r4: it inflated timings 8x and green-lit harvests that then
hung at their first op), so a roundtrip is the only trustworthy pass
condition. Shared by bench.py and tools/tpu_watcher.sh — refine the probe
HERE, in one place.
"""
import sys

import jax
import jax.numpy as jnp

d = jax.devices()
if d[0].platform == "cpu":
    print(f"PROBE_CPU_ONLY {d}", flush=True)
    sys.exit(1)
o = jax.jit(lambda a: a @ a)(jnp.ones((128, 128)))
v = float(jax.device_get(o.ravel()[0]))
print("PROBE_OK", d[0].platform, d[0].device_kind, "roundtrip", v, flush=True)

"""SE-ResNeXt (50/101/152) — grouped convolutions + squeeze-and-excitation.

Reference: ``benchmark/fluid/models/se_resnext.py`` — bottleneck_block with
cardinality-32 grouped 3×3 conv, squeeze_excitation (global pool → fc/r →
fc sigmoid scale), reduction_ratio 16, three-conv stem for depth 152,
Momentum + piecewise-decay LR.

Grouped conv maps to ``lax.conv_general_dilated(feature_group_count=...)``,
which XLA tiles onto the MXU directly — no im2col split like the reference's
``conv2d(groups=)`` CUDA path.
"""

from __future__ import annotations

import functools

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.enforce import enforce
from paddle_tpu.models import ModelSpec


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1, act=None):
    conv = layers.conv2d(
        input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = layers.pool2d(input, pool_size=0, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, size=num_channels // reduction_ratio, act="relu")
    excitation = layers.fc(squeeze, size=num_channels, act="sigmoid")
    return input * excitation[:, None, None, :]


def shortcut(input, ch_out, stride):
    ch_in = input.shape[-1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride)
    return input


def bottleneck_block(input, num_filters, stride, cardinality, reduction_ratio):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, groups=cardinality, act="relu")
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride)
    return layers.relu(short + scale)


def se_resnext(images, class_dim=1000, layers_depth=50):
    cardinality = 64 if layers_depth == 152 else 32
    reduction_ratio = 16
    cfg = {
        50: ([3, 4, 6, 3], [128, 256, 512, 1024]),
        101: ([3, 4, 23, 3], [128, 256, 512, 1024]),
        152: ([3, 8, 36, 3], [128, 256, 512, 1024]),
    }
    enforce(layers_depth in cfg, f"unsupported se_resnext depth {layers_depth}")
    depth, num_filters = cfg[layers_depth]

    if layers_depth == 152:
        conv = conv_bn_layer(images, 64, 3, stride=2, act="relu")
        conv = conv_bn_layer(conv, 64, 3, act="relu")
        conv = conv_bn_layer(conv, 128, 3, act="relu")
    else:
        conv = conv_bn_layer(images, 64, 7, stride=2, act="relu")
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max")

    for block in range(len(depth)):
        for i in range(depth[block]):
            conv = bottleneck_block(
                conv,
                num_filters=num_filters[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality,
                reduction_ratio=reduction_ratio,
            )

    pool = layers.pool2d(conv, pool_size=7, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.2)
    return layers.fc(drop, size=class_dim)


def _forward(images, labels, *, class_dim, depth):
    logits = se_resnext(images, class_dim=class_dim, layers_depth=depth)
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(logits, labels)
    return avg_loss, acc, logits


def get_model(
    depth: int = 50,
    class_dim: int = 102,
    image_size: int = 224,
    learning_rate: float = 0.1,
    batch_size: int = 32,
    **_unused,
) -> ModelSpec:
    model = pt.build(
        functools.partial(_forward, class_dim=class_dim, depth=depth),
        name=f"se_resnext{depth}",
    )

    # piecewise decay on epoch boundaries (reference se_resnext.py optimizer)
    epochs = [40, 80, 100]
    total_images = 6149
    step = max(1, int(total_images / batch_size + 1))
    bd = [e * step for e in epochs]
    lr_values = [learning_rate * (0.1 ** i) for i in range(len(bd) + 1)]

    def synth_batch(bs: int, rng: np.random.RandomState):
        images = rng.rand(bs, image_size, image_size, 3).astype(np.float32)
        labels = rng.randint(0, class_dim, size=(bs,)).astype(np.int32)
        return images, labels

    return ModelSpec(
        name=f"se_resnext{depth}",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Momentum(
            learning_rate=pt.lr_scheduler.PiecewiseDecay(bd, lr_values),
            momentum=0.9,
            regularization=pt.regularizer.L2Decay(1e-4),
        ),
        unit="images/sec",
        extra={"class_dim": class_dim, "image_size": image_size},
    )

"""ResNet (cifar10 / flowers-ImageNet configs).

Reference: ``benchmark/fluid/models/resnet.py`` — basicblock (cifar10,
ResNet-32-style depth arg) and bottleneck (flowers 224×224, ResNet-50/101/152)
residual towers, conv_bn_layer building block, Momentum(lr=0.01, momentum=0.9).

TPU-first notes: NHWC layout throughout (MXU-friendly), BN moving stats in the
functional state collection, the whole tower is one XLA program — residual
adds fuse into the conv epilogues. bf16 activations are enabled by the
benchmark driver via dtype arg; params stay fp32.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import name_scope
from paddle_tpu.models import ModelSpec


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu"):
    """conv → BN(act) with no conv bias (reference resnet.py conv_bn_layer)."""
    conv = layers.conv2d(
        input,
        num_filters=ch_out,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[-1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None)
    return input


def basicblock(input, ch_out, stride):
    short = shortcut(input, ch_out, stride)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None)
    return layers.relu(conv2 + short)


def bottleneck(input, ch_out, stride):
    short = shortcut(input, ch_out * 4, stride)
    conv1 = conv_bn_layer(input, ch_out, 1, 1, 0)
    conv2 = conv_bn_layer(conv1, ch_out, 3, stride, 1)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None)
    return layers.relu(conv3 + short)


def layer_warp(block_func, input, ch_out, count, stride):
    res = block_func(input, ch_out, stride)
    for _ in range(count - 1):
        res = block_func(res, ch_out, 1)
    return res


def resnet_imagenet(images, class_dim=1000, depth=50):
    """Bottleneck tower for 224×224 inputs (reference resnet.py
    resnet_imagenet)."""
    cfg = {
        18: ([2, 2, 2, 2], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    enforce(depth in cfg, f"unsupported resnet depth {depth}")
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(images, ch_out=64, filter_size=7, stride=2, padding=3)
    pool1 = layers.pool2d(conv1, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max")
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2)
    pool2 = layers.pool2d(res4, pool_size=7, pool_stride=1, global_pooling=True, pool_type="avg")
    return layers.fc(pool2, size=class_dim)


def resnet_cifar10(images, class_dim=10, depth=32):
    """Basic-block tower for 32×32 inputs (reference resnet.py
    resnet_cifar10)."""
    enforce((depth - 2) % 6 == 0, "cifar resnet depth must be 6n+2")
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(images, ch_out=16, filter_size=3, stride=1, padding=1)
    res1 = layer_warp(basicblock, conv1, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 64, n, 2)
    pool = layers.pool2d(res3, pool_size=8, pool_stride=1, global_pooling=True, pool_type="avg")
    return layers.fc(pool, size=class_dim)


def _forward(images, labels, *, net, class_dim):
    logits = net(images, class_dim=class_dim)
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(logits, labels)
    return avg_loss, acc, logits


def get_model(
    dataset: str = "flowers",
    depth: int = 50,
    class_dim: int = None,
    learning_rate: float = 0.01,
    image_size: int = None,
    dtype: str = "float32",
    **_unused,
) -> ModelSpec:
    if dataset == "cifar10":
        class_dim = class_dim or 10
        image_size = image_size or 32
        net = functools.partial(resnet_cifar10, depth=depth if depth != 50 else 32)
    else:
        class_dim = class_dim or (102 if dataset == "flowers" else 1000)
        image_size = image_size or 224
        net = functools.partial(resnet_imagenet, depth=depth)

    model = pt.build(
        functools.partial(_forward, net=net, class_dim=class_dim),
        name=f"resnet{depth}_{dataset}",
    )

    np_dtype = np.dtype(dtype) if dtype != "bfloat16" else np.float32

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        images = rng.rand(batch_size, image_size, image_size, 3).astype(np_dtype)
        labels = rng.randint(0, class_dim, size=(batch_size,)).astype(np.int32)
        return images, labels

    return ModelSpec(
        name=f"resnet{depth}",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Momentum(learning_rate=learning_rate, momentum=0.9),
        unit="images/sec",
        extra={"class_dim": class_dim, "image_size": image_size},
    )

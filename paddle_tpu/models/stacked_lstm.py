"""Stacked LSTM sentiment model (stacked_dynamic_lstm).

Reference: ``benchmark/fluid/models/stacked_dynamic_lstm.py`` — IMDB
sentiment: embedding(512) → stacked fc+LSTM layers → [max,last] pooling →
fc(2) softmax, Adam(lr=0.002). Variable-length LoD input becomes padded
[B, T] + lengths; ``lax.scan`` replaces the dynamic_lstm C++ sequence kernel
(``operators/lstm_op.cc``), and pooling masks pad positions.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.models import ModelSpec
from paddle_tpu.ops import sequence as oseq


def stacked_lstm_net(word_ids, lengths, labels, *, vocab_size, emb_dim, hidden_dim, stacked_num, class_dim):
    emb = layers.embedding(word_ids, size=[vocab_size, emb_dim])
    x = layers.fc(emb, size=hidden_dim, num_flatten_dims=2, act="tanh", name="fc0")
    for i in range(stacked_num):
        # fluid structure: fc to 4H is the LSTM input projection (dynamic_lstm
        # itself carries only recurrent weights, proj_input=False)
        proj = layers.fc(x, size=hidden_dim * 4, num_flatten_dims=2, name=f"fc_{i}")
        lstm_out, _ = layers.dynamic_lstm(
            proj, size=hidden_dim, lengths=lengths, proj_input=False, name=f"lstm_{i}"
        )
        x = lstm_out
    max_pool = layers.sequence_pool(x, lengths, pool_type="max")
    last = layers.sequence_last_step(x, lengths)
    feat = jnp.concatenate([max_pool, last], axis=-1)
    logits = layers.fc(feat, size=class_dim)
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(logits, labels)
    return avg_loss, acc, logits


def get_model(
    vocab_size: int = 5147,
    emb_dim: int = 512,
    hidden_dim: int = 512,
    stacked_num: int = 3,
    class_dim: int = 2,
    seq_len: int = 80,
    learning_rate: float = 0.002,
    **_unused,
) -> ModelSpec:
    model = pt.build(
        functools.partial(
            stacked_lstm_net,
            vocab_size=vocab_size,
            emb_dim=emb_dim,
            hidden_dim=hidden_dim,
            stacked_num=stacked_num,
            class_dim=class_dim,
        ),
        name="stacked_dynamic_lstm",
    )

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        ids = rng.randint(0, vocab_size, size=(batch_size, seq_len)).astype(np.int32)
        lens = rng.randint(seq_len // 2, seq_len + 1, size=(batch_size,)).astype(np.int32)
        labels = rng.randint(0, class_dim, size=(batch_size,)).astype(np.int32)
        return ids, lens, labels

    return ModelSpec(
        name="stacked_dynamic_lstm",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Adam(learning_rate=learning_rate),
        unit="words/sec",
        examples_per_row=seq_len,
    )

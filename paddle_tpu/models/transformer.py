"""Transformer NMT — the flagship model.

Reference: the Transformer config used by ``benchmark/fluid`` /
``python/paddle/fluid/tests/unittests/dist_transformer.py`` (post-LN
encoder-decoder, d_model 512, 8 heads, ffn 2048, 6+6 layers, label smoothing
0.1, Adam + Noam warmup) — attention built from composed ops
(``python/paddle/fluid/nets.py:332``).

TPU-first design:
- one fused attention path (``ops.attention.scaled_dot_product_attention``,
  fp32 softmax, MXU-friendly [B,N,T,D] batched matmuls); a Pallas
  flash-attention kernel takes over for long sequences.
- every projection carries a logical sharding spec so the same program runs
  unsharded, data-parallel, or tensor-parallel under a mesh: column-parallel
  qkv/ffn-in (shard output dim on ``tp``), row-parallel out/ffn-out (shard
  input dim on ``tp``) — the Megatron layout expressed purely as pjit
  constraints; XLA inserts the psums (no hand-written collectives).
- static shapes: [B, T] padded + additive masks (the LoD replacement).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import ParamAttr, create_parameter, name_scope
from paddle_tpu.models import ModelSpec
from paddle_tpu.ops import attention as oattn

# canonical tensor-parallel mesh axis; absent from a mesh → replicated
from paddle_tpu.parallel.mesh import MODEL_AXIS as TP


def _proj(x, size, *, shard_out: bool, name: str, bias: bool = True):
    """Linear projection over the last axis of [B, T, D] with a tensor-
    parallel sharding annotation (column- or row-parallel)."""
    sharding = (None, TP) if shard_out else (TP, None)
    return layers.fc(
        x,
        size=size,
        num_flatten_dims=x.ndim - 1,
        param_attr=ParamAttr(sharding=sharding),
        bias_attr=None if bias else False,
        name=name,
    )


def multi_head_attention(
    queries,
    keys,
    values,
    d_model: int,
    num_heads: int,
    mask=None,
    dropout_rate: float = 0.0,
    cache: Optional[dict] = None,
    name: str = "mha",
    causal: bool = False,
    core=None,
    kv_len=None,
    num_kv_heads: Optional[int] = None,
    window: Optional[int] = None,
):
    """Projected multi-head attention (q/k/v/out linear maps + fused core).

    ``cache`` (decode-time) holds accumulated k/v: {"k": [B,N,T,D], "v": ...};
    when given, new k/v are appended (static-size cache with a write index is
    used in the beam-search decoder). ``core`` overrides the attention core
    ``(qh, kh, vh) -> ctx`` — e.g. a ring-attention body for sequence-
    parallel long context. ``num_kv_heads`` < num_heads enables
    grouped-query attention (MQA at 1): k/v project to fewer heads, cutting
    KV projection FLOPs, cache size, and HBM traffic proportionally."""
    h_kv = num_kv_heads or num_heads
    if num_heads % h_kv:
        raise ValueError(f"num_heads {num_heads} not divisible by num_kv_heads {h_kv}")
    d_kv = d_model // num_heads * h_kv
    with name_scope(name):
        q = _proj(queries, d_model, shard_out=True, name="q")
        k = _proj(keys, d_kv, shard_out=True, name="k")
        v = _proj(values, d_kv, shard_out=True, name="v")
        qh = oattn.split_heads(q, num_heads)
        kh = oattn.split_heads(k, h_kv)
        vh = oattn.split_heads(v, h_kv)
        if cache is not None:
            kh = jnp.concatenate([cache["k"], kh], axis=2)
            vh = jnp.concatenate([cache["v"], vh], axis=2)
            cache["k"], cache["v"] = kh, vh
        if core is not None:
            from paddle_tpu.core.enforce import enforce

            enforce(
                mask is None
                and cache is None
                and (dropout_rate == 0.0 or not pt.framework.is_training()),
                "multi_head_attention: a custom attention core supports neither "
                "an additive mask, nor a decode-time k/v cache (the core "
                "assumes q and k share global sequence alignment), nor "
                "attention dropout — got "
                f"mask={'set' if mask is not None else None}, "
                f"cache={'set' if cache is not None else None}, "
                f"dropout_rate={dropout_rate}",
            )
            # kv_len DOES pass through: ring/ulysses cores mask global key
            # positions >= kv_len[b] (ragged batches under seq parallelism)
            ctx = core(qh, kh, vh, kv_len=kv_len) if kv_len is not None else core(qh, kh, vh)
        else:
            ctx = oattn.scaled_dot_product_attention(
                qh, kh, vh, mask=mask, dropout_rate=dropout_rate,
                is_test=not pt.framework.is_training(),
                dropout_key=pt.framework.next_rng_key() if (dropout_rate > 0 and pt.framework.is_training()) else None,
                causal=causal,
                kv_len=kv_len,
                window=window,
            )
        out = oattn.combine_heads(ctx)
        return _proj(out, d_model, shard_out=False, name="out")


def positionwise_ffn(x, d_inner: int, d_model: int, dropout_rate: float,
                     name: str = "ffn", activation: str = "relu"):
    """``activation='swiglu'`` gates the up-projection with a SiLU branch
    (modern LM FFN; two column-parallel matmuls instead of one)."""
    with name_scope(name):
        if activation == "swiglu":
            up = _proj(x, d_inner, shard_out=True, name="fc1")
            gate = _proj(x, d_inner, shard_out=True, name="gate")
            hidden = up * jax.nn.silu(gate)
        else:
            hidden = _proj(x, d_inner, shard_out=True, name="fc1")
            hidden = layers.relu(hidden)
        if dropout_rate:
            hidden = layers.dropout(hidden, dropout_rate)
        return _proj(hidden, d_model, shard_out=False, name="fc2")


def _post_process(prev, out, dropout_rate):
    """residual add + LayerNorm (post-LN, reference-era transformer)."""
    if dropout_rate:
        out = layers.dropout(out, dropout_rate)
    return layers.layer_norm(prev + out, begin_norm_axis=prev.ndim - 1)


def sinusoid_position_encoding(max_len: int, d_model: int, dtype=jnp.float32):
    pos = np.arange(max_len)[:, None].astype(np.float64)
    dim = np.arange(d_model // 2)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2 * dim / d_model)
    enc = np.concatenate([np.sin(angle), np.cos(angle)], axis=1)
    return jnp.asarray(enc, dtype)


def prepare_embedding(ids, vocab_size, d_model, max_len, dropout_rate, name,
                      pos_offset=0, add_position_encoding=True):
    """token embedding * sqrt(d) (+ fixed sinusoid position encoding unless
    ``add_position_encoding=False`` — RoPE models inject position at the
    attention rotation instead). ``pos_offset`` (int or traced scalar)
    shifts positions for incremental decode with a k/v cache."""
    with name_scope(name):
        emb = layers.embedding(
            ids,
            size=[vocab_size, d_model],
            param_attr=ParamAttr(name="word_emb", sharding=(None, TP)),
        )
        emb = emb * (d_model ** 0.5)
        if add_position_encoding:
            t = ids.shape[-1]
            pe = sinusoid_position_encoding(max_len, d_model, emb.dtype)
            emb = emb + jax.lax.dynamic_slice_in_dim(pe, pos_offset, t, axis=0)
        if dropout_rate:
            emb = layers.dropout(emb, dropout_rate)
        return emb


def encoder_layer(x, self_mask, cfg, name, kv_len=None):
    with name_scope(name):
        attn = multi_head_attention(
            x, x, x, cfg["d_model"], cfg["num_heads"], mask=self_mask,
            dropout_rate=cfg["attn_dropout"], name="self_attn", kv_len=kv_len,
        )
        x = _post_process(x, attn, cfg["residual_dropout"])
        ffn = positionwise_ffn(x, cfg["d_inner"], cfg["d_model"], cfg["relu_dropout"])
        return _post_process(x, ffn, cfg["residual_dropout"])


def decoder_layer(x, enc_out, self_mask, cross_mask, cfg, name, cache=None,
                  self_causal=False, cross_kv_len=None):
    with name_scope(name):
        attn = multi_head_attention(
            x, x, x, cfg["d_model"], cfg["num_heads"], mask=self_mask,
            dropout_rate=cfg["attn_dropout"], cache=cache, name="self_attn",
            causal=self_causal,
        )
        x = _post_process(x, attn, cfg["residual_dropout"])
        cross = multi_head_attention(
            x, enc_out, enc_out, cfg["d_model"], cfg["num_heads"], mask=cross_mask,
            dropout_rate=cfg["attn_dropout"], name="cross_attn",
            kv_len=cross_kv_len,
        )
        x = _post_process(x, cross, cfg["residual_dropout"])
        ffn = positionwise_ffn(x, cfg["d_inner"], cfg["d_model"], cfg["relu_dropout"])
        return _post_process(x, ffn, cfg["residual_dropout"])


def _pad_mask(pad_flags):
    """[B, T] bool (True = padding) → additive [B, 1, 1, T]."""
    return jnp.where(pad_flags, -jnp.inf, 0.0).astype(jnp.float32)[:, None, None, :]


def _structural_masking() -> bool:
    """With the flash flag on, padding travels as per-row kv_len bounds and
    causality as the kernel's block structure — no additive [T, T] masks.
    Valid because padding is a SUFFIX (ragged FeedSpec layout) and the loss
    zero-weights pad positions: pad QUERIES may compute garbage that never
    reaches the loss, while pad KEYS are excluded for every valid query."""
    from paddle_tpu.core import config as _cfg

    return _cfg.flags().use_flash_attention


def _lens(pad_flags):
    return jnp.sum(1 - pad_flags.astype(jnp.int32), axis=1)


def encode(src_ids, src_pad, cfg):
    structural = _structural_masking()
    self_mask = None if structural else _pad_mask(src_pad)
    src_len = _lens(src_pad) if structural else None
    x = prepare_embedding(
        src_ids, cfg["src_vocab"], cfg["d_model"], cfg["max_len"],
        cfg["residual_dropout"], name="src_emb",
    )
    if cfg.get("scan_layers") and not pt.framework.is_initializing():
        # one lax.scan body over stacked params (framework.scan_layer_stack:
        # compile cost and program size O(1) in n_layers); init stays
        # unrolled for trace-time param creation
        return pt.framework.scan_layer_stack(
            x, cfg["n_layers"], lambda i: f"enc_layer_{i}", "enc_layer_tpl",
            lambda h, name: encoder_layer(h, self_mask, cfg, name, kv_len=src_len),
        )
    for i in range(cfg["n_layers"]):
        x = encoder_layer(x, self_mask, cfg, name=f"enc_layer_{i}", kv_len=src_len)
    return x


def decode(trg_ids, trg_pad, enc_out, src_pad, cfg, caches=None, pos_offset=0):
    t = trg_ids.shape[1]
    structural = _structural_masking() and caches is None
    if caches is not None:
        self_mask = None
    elif structural:
        # causal alone suffices for decoder self-attention: pad keys sit at
        # positions >= len, and every valid query q has q < len <= pad key
        # positions, so causality already excludes them
        self_mask = None
    else:
        self_mask = oattn.causal_mask(t, t)[None, None] + _pad_mask(trg_pad)
    cross_mask = None if structural else _pad_mask(src_pad)
    cross_len = _lens(src_pad) if structural else None
    x = prepare_embedding(
        trg_ids, cfg["trg_vocab"], cfg["d_model"], cfg["max_len"],
        cfg["residual_dropout"], name="trg_emb",
        pos_offset=pos_offset if caches is not None else 0,
    )
    if (
        cfg.get("scan_layers")
        and caches is None  # cached decode keeps its per-layer loop
        and not pt.framework.is_initializing()
    ):
        x = pt.framework.scan_layer_stack(
            x, cfg["n_layers"], lambda i: f"dec_layer_{i}", "dec_layer_tpl",
            lambda h, name: decoder_layer(
                h, enc_out, self_mask, cross_mask, cfg, name,
                self_causal=structural, cross_kv_len=cross_len,
            ),
        )
    else:
        for i in range(cfg["n_layers"]):
            cache = caches[i] if caches is not None else None
            x = decoder_layer(
                x, enc_out, self_mask, cross_mask, cfg, name=f"dec_layer_{i}",
                cache=cache, self_causal=structural, cross_kv_len=cross_len,
            )
    with name_scope("project"):
        logits = _proj(x, cfg["trg_vocab"], shard_out=True, name="logits", bias=False)
    return logits


def transformer_forward(src_ids, src_pad, trg_ids, trg_pad, labels, label_pad, *, cfg):
    """Training forward: returns (avg_loss, token_count, logits).

    Loss = label-smoothed softmax CE, averaged over non-pad tokens
    (reference transformer label_smooth eps=0.1)."""
    enc_out = encode(src_ids, src_pad, cfg)
    logits = decode(trg_ids, trg_pad, enc_out, src_pad, cfg)
    vocab = cfg["trg_vocab"]
    eps = cfg["label_smooth_eps"]
    onehot = jax.nn.one_hot(labels, vocab, dtype=jnp.float32)
    smooth = onehot * (1 - eps) + eps / vocab
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tok_loss = -jnp.sum(smooth * logp, axis=-1)  # [B, T]
    weight = 1.0 - label_pad.astype(jnp.float32)
    n_tok = jnp.maximum(jnp.sum(weight), 1.0)
    avg_loss = jnp.sum(tok_loss * weight) / n_tok
    return avg_loss, n_tok, logits


BASE_CFG = dict(
    src_vocab=10000,
    trg_vocab=10000,
    d_model=512,
    d_inner=2048,
    num_heads=8,
    n_layers=6,
    max_len=256,
    attn_dropout=0.1,
    relu_dropout=0.1,
    residual_dropout=0.1,
    label_smooth_eps=0.1,
    # run encoder/decoder stacks as one lax.scan body each over stacked
    # params (framework.scan_layer_stack); cached decode stays unrolled
    scan_layers=False,
)


def get_model(
    seq_len: int = 64,
    learning_rate: float = 2.0,
    warmup_steps: int = 8000,
    **overrides,
) -> ModelSpec:
    cfg = dict(BASE_CFG)
    cfg.update({k: v for k, v in overrides.items() if k in cfg})

    model = pt.build(functools.partial(transformer_forward, cfg=cfg), name="transformer")

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        src = rng.randint(1, cfg["src_vocab"], size=(batch_size, seq_len)).astype(np.int32)
        trg = rng.randint(1, cfg["trg_vocab"], size=(batch_size, seq_len)).astype(np.int32)
        labels = rng.randint(1, cfg["trg_vocab"], size=(batch_size, seq_len)).astype(np.int32)
        # ragged lengths → pad flags (the LoD replacement)
        lens = rng.randint(seq_len // 2, seq_len + 1, size=(batch_size,))
        pos = np.arange(seq_len)[None, :]
        src_pad = (pos >= lens[:, None])
        return src, src_pad, trg, src_pad.copy(), labels, src_pad.copy()

    def make_optimizer():
        return pt.optimizer.Adam(
            learning_rate=pt.lr_scheduler.NoamDecay(cfg["d_model"], warmup_steps, learning_rate),
            beta1=0.9,
            beta2=0.98,
            epsilon=1e-9,
        )

    return ModelSpec(
        name="transformer",
        model=model,
        synth_batch=synth_batch,
        optimizer=make_optimizer,
        unit="tokens/sec",
        examples_per_row=seq_len,
        extra={"cfg": cfg, "seq_len": seq_len},
    )

"""MNIST conv net (recognize_digits).

Reference: ``benchmark/fluid/models/mnist.py`` (cnn_model: two
simple_img_conv_pool blocks then fc softmax, Adam lr=0.001) and the book test
``python/paddle/fluid/tests/book/test_recognize_digits.py``.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, nets
from paddle_tpu.models import ModelSpec

IMG_SHAPE = (28, 28, 1)  # NHWC (reference feeds NCHW [1,28,28])
NUM_CLASSES = 10


def cnn_model(images, labels):
    """Forward: images [B,28,28,1] float, labels [B] int32 →
    (avg_loss, accuracy, logits)."""
    conv1 = nets.simple_img_conv_pool(
        images, num_filters=20, filter_size=5, pool_size=2, pool_stride=2, act="relu"
    )
    conv2 = nets.simple_img_conv_pool(
        conv1, num_filters=50, filter_size=5, pool_size=2, pool_stride=2, act="relu"
    )
    logits = layers.fc(conv2, size=NUM_CLASSES)
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(logits, labels)
    return avg_loss, acc, logits


def synth_batch(batch_size: int, rng: np.random.RandomState):
    images = rng.rand(batch_size, *IMG_SHAPE).astype(np.float32)
    labels = rng.randint(0, NUM_CLASSES, size=(batch_size,)).astype(np.int32)
    return images, labels


def get_model(learning_rate: float = 0.001, **_unused) -> ModelSpec:
    model = pt.build(cnn_model, name="mnist")
    return ModelSpec(
        name="mnist",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Adam(learning_rate=learning_rate),
        unit="images/sec",
    )

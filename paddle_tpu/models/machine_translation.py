"""Seq2seq LSTM with attention (machine_translation config).

Reference: ``benchmark/fluid/models/machine_translation.py`` — WMT16
encoder-decoder: embedding → fc → dynamic_lstm encoder; decoder DynamicRNN
with dot-product attention over encoder states, fc softmax per step; Adam.
The reference's DynamicRNN + LoD sequence walk becomes a ``lax.scan`` over
padded [B, T] steps with length masks; attention is a batched matmul the MXU
executes directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import create_parameter, name_scope
from paddle_tpu.models import ModelSpec
from paddle_tpu.ops import rnn as orn
from paddle_tpu.ops import sequence as oseq


def encoder(src_ids, src_lens, *, vocab_size, emb_dim, hidden_dim):
    with name_scope("encoder"):
        emb = layers.embedding(src_ids, size=[vocab_size, emb_dim])
        # fluid structure: the fc IS the LSTM input projection (reference
        # machine_translation.py:59-65), dynamic_lstm carries only w_hh
        proj = layers.fc(emb, size=hidden_dim * 4, num_flatten_dims=2, act=None)
        out, (h, c) = layers.dynamic_lstm(
            proj, size=hidden_dim, lengths=src_lens, proj_input=False
        )
        return out, (h, c)


def attention_step(dec_h, enc_out, enc_mask):
    """Dot-product attention: scores over encoder steps, masked softmax,
    context vector (reference simple_attention in machine_translation.py)."""
    scores = jnp.einsum("bh,bth->bt", dec_h, enc_out)
    scores = jnp.where(enc_mask, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bt,bth->bh", weights, enc_out)


def _decoder_params(vocab_size, emb_dim, hidden_dim, dtype):
    """Decoder parameter set, created once so the train and beam-decode graphs
    share names AND initializers (must be called inside name_scope('decoder'),
    right after the target embedding)."""
    d = emb_dim + hidden_dim
    w_ih = create_parameter([d, 4 * hidden_dim], dtype, name="w_ih")
    w_hh = create_parameter([hidden_dim, 4 * hidden_dim], dtype, name="w_hh")
    b = create_parameter([4 * hidden_dim], dtype, name="b",
                         default_initializer=pt.initializer.Constant(0.0))
    w_out = create_parameter([hidden_dim, vocab_size], dtype, name="w_out")
    b_out = create_parameter([vocab_size], dtype, name="b_out",
                             default_initializer=pt.initializer.Constant(0.0))
    return w_ih, w_hh, b, w_out, b_out


def decoder_train(trg_ids, enc_out, enc_mask, init_state, *, vocab_size, emb_dim, hidden_dim):
    """Teacher-forced decoder: per step, LSTM cell on [emb; context]."""
    with name_scope("decoder"):
        emb = layers.embedding(trg_ids, size=[vocab_size, emb_dim])
        w_ih, w_hh, b, w_out, b_out = _decoder_params(
            vocab_size, emb_dim, hidden_dim, emb.dtype
        )

        def step(carry, x_t):
            ctx = attention_step(carry.h, enc_out, enc_mask)
            inp = jnp.concatenate([x_t, ctx], axis=-1)
            x_proj = jnp.matmul(inp, w_ih, preferred_element_type=jnp.float32).astype(inp.dtype)
            new = orn.lstm_cell(x_proj, carry, w_hh, b)
            return new, new.h

        xs = jnp.swapaxes(emb, 0, 1)  # [T, B, E]
        _, hs = jax.lax.scan(step, orn.LSTMState(*init_state), xs)
        hs = jnp.swapaxes(hs, 0, 1)  # [B, T, H]
        logits = jnp.matmul(hs, w_out, preferred_element_type=jnp.float32) + b_out
        return logits.astype(jnp.float32)


def seq_to_seq_infer(
    src_ids, src_lens, *, vocab_size, emb_dim, hidden_dim,
    beam_size, max_len, bos_id, eos_id,
):
    """Beam-search decode (reference ``machine_translation.py`` decode() built
    on beam_search/beam_search_decode ops). Parameter creation order mirrors
    :func:`seq_to_seq_net` exactly so the trained params resolve by name."""
    from paddle_tpu.ops import control_flow as ocf

    enc_out, (h, c) = encoder(
        src_ids, src_lens, vocab_size=vocab_size, emb_dim=emb_dim, hidden_dim=hidden_dim
    )
    enc_mask = oseq.length_mask(src_lens, src_ids.shape[1])
    with name_scope("decoder"):
        with name_scope("embedding"):
            table = create_parameter([vocab_size, emb_dim], enc_out.dtype, name="w")
        w_ih, w_hh, b, w_out, b_out = _decoder_params(
            vocab_size, emb_dim, hidden_dim, enc_out.dtype
        )

    # enc_out/enc_mask are beam-invariant: tile once and close over them so
    # the beam gather only permutes the (small) LSTM state, not [B*K, T, H]
    enc_out_k = jnp.repeat(enc_out, beam_size, axis=0)
    enc_mask_k = jnp.repeat(enc_mask, beam_size, axis=0)

    def step_fn(state, tokens):
        emb = table[tokens]
        ctx = attention_step(state.h, enc_out_k, enc_mask_k)
        inp = jnp.concatenate([emb, ctx], axis=-1)
        x_proj = jnp.matmul(inp, w_ih, preferred_element_type=jnp.float32).astype(inp.dtype)
        new = orn.lstm_cell(x_proj, state, w_hh, b)
        logits = jnp.matmul(new.h, w_out, preferred_element_type=jnp.float32) + b_out
        return new, jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    return ocf.beam_search(
        step_fn,
        orn.LSTMState(h, c),
        batch_size=src_ids.shape[0],
        beam_size=beam_size,
        vocab_size=vocab_size,
        max_len=max_len,
        bos_id=bos_id,
        eos_id=eos_id,
    )


def seq_to_seq_net(src_ids, src_lens, trg_ids, labels, trg_lens, *, vocab_size, emb_dim, hidden_dim):
    enc_out, (h, c) = encoder(src_ids, src_lens, vocab_size=vocab_size, emb_dim=emb_dim, hidden_dim=hidden_dim)
    enc_mask = oseq.length_mask(src_lens, src_ids.shape[1])
    logits = decoder_train(
        trg_ids, enc_out, enc_mask, (h, c),
        vocab_size=vocab_size, emb_dim=emb_dim, hidden_dim=hidden_dim,
    )
    tok_loss = layers.softmax_with_cross_entropy(logits, labels)[..., 0]
    weight = oseq.length_mask(trg_lens, trg_ids.shape[1]).astype(jnp.float32)
    n_tok = jnp.maximum(jnp.sum(weight), 1.0)
    avg_loss = jnp.sum(tok_loss * weight) / n_tok
    return avg_loss, n_tok, logits


def get_model(
    vocab_size: int = 10000,
    emb_dim: int = 512,
    hidden_dim: int = 512,
    seq_len: int = 50,
    learning_rate: float = 2e-4,
    **_unused,
) -> ModelSpec:
    model = pt.build(
        functools.partial(
            seq_to_seq_net, vocab_size=vocab_size, emb_dim=emb_dim, hidden_dim=hidden_dim
        ),
        name="machine_translation",
    )

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        src = rng.randint(0, vocab_size, size=(batch_size, seq_len)).astype(np.int32)
        trg = rng.randint(0, vocab_size, size=(batch_size, seq_len)).astype(np.int32)
        labels = rng.randint(0, vocab_size, size=(batch_size, seq_len)).astype(np.int32)
        src_lens = rng.randint(seq_len // 2, seq_len + 1, size=(batch_size,)).astype(np.int32)
        trg_lens = rng.randint(seq_len // 2, seq_len + 1, size=(batch_size,)).astype(np.int32)
        return src, src_lens, trg, labels, trg_lens

    def make_infer_model(beam_size: int = 4, max_len: int = 32, bos_id: int = 0, eos_id: int = 1):
        return pt.build(
            functools.partial(
                seq_to_seq_infer,
                vocab_size=vocab_size, emb_dim=emb_dim, hidden_dim=hidden_dim,
                beam_size=beam_size, max_len=max_len, bos_id=bos_id, eos_id=eos_id,
            ),
            name="machine_translation_infer",
        )

    return ModelSpec(
        name="machine_translation",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Adam(learning_rate=learning_rate),
        unit="words/sec",
        examples_per_row=seq_len,
        extra={"make_infer_model": make_infer_model},
    )

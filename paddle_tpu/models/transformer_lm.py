"""Decoder-only causal language model (GPT-style) — the long-context
flagship for the flash-attention + bf16 training path.

The reference benchmark suite has no decoder-only config (its transformer
is the NMT encoder-decoder, ``benchmark/fluid/models/transformer.py``);
this model extends the family the TPU-first way: causal masking is
STRUCTURAL (``scaled_dot_product_attention(causal=True)`` → the Pallas
flash kernel skips above-diagonal blocks and never materializes [T, T]),
sequence length is a config knob up to 8k+ (ring attention / seq-axis
sharding take over beyond single-chip VMEM), and matmuls run bf16 under
``flags().use_bf16_compute``.

Sharding: reuses the Megatron-style column/row-parallel projections of
``models/transformer.py`` (q/k/v/fc1 column, out/fc2 row over the model
axis).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import name_scope
from paddle_tpu.models import ModelSpec
from paddle_tpu.models.transformer import (
    _post_process,
    _proj,
    multi_head_attention,
    positionwise_ffn,
    prepare_embedding,
)

__all__ = ["get_model", "lm_forward", "generate", "generate_beam",
           "stack_decode_params", "BASE_CFG",
           "paged_cache_shape", "paged_prefill_chunk", "paged_decode_step",
           "paged_verify_step"]


def _ring_core(ring_mesh, window=None):
    """Attention core for sequence-parallel long context: exact causal
    attention over the seq-sharded global sequence via the ring
    (``ops/ring_attention.py``) instead of XLA's all-gather lowering."""
    from paddle_tpu.ops.ring_attention import ring_attention_sharded

    return lambda qh, kh, vh, kv_len=None: ring_attention_sharded(
        qh, kh, vh, ring_mesh, causal=True, window=window, kv_len=kv_len
    )


def _ulysses_core(mesh, window=None):
    """All-to-all sequence parallelism (``ops/ulysses.py``): re-shard
    seq->head, plain flash attention on full local sequences, shard back."""
    from paddle_tpu.ops.ulysses import ulysses_attention_sharded

    return lambda qh, kh, vh, kv_len=None: ulysses_attention_sharded(
        qh, kh, vh, mesh, causal=True, window=window, kv_len=kv_len
    )


def _rope_core(cfg):
    """Attention core applying rotary position embeddings to q/k before the
    (flash-routed) fused attention; positions are absolute so scores are
    relative-position functions."""
    from paddle_tpu.ops.attention import apply_rope, rope_tables, scaled_dot_product_attention

    def core(qh, kh, vh, kv_len=None):
        cos, sin = rope_tables(qh.shape[-1], qh.shape[-2])
        return scaled_dot_product_attention(
            apply_rope(qh, cos, sin), apply_rope(kh, cos, sin), vh, causal=True,
            window=cfg.get("attention_window"), kv_len=kv_len,
        )

    return core


def _decode_ffn_fn(proj, swiglu: bool):
    """FFN for the cached decoders, pinned to ``positionwise_ffn``:
    relu(fc1) or fc1 * silu(gate). One copy shared by generate and
    generate_beam so train/decode FFN parity has a single edit point."""
    def ffn(x, i):
        if swiglu:
            h = proj(x, f"layer_{i}/ffn/fc1") * jax.nn.silu(proj(x, f"layer_{i}/ffn/gate"))
        else:
            h = jax.nn.relu(proj(x, f"layer_{i}/ffn/fc1"))
        return proj(h, f"layer_{i}/ffn/fc2")

    return ffn


def _live_mask(t_max: int, t, window):
    """[t_max] bool mask of cache positions a token at position ``t`` may
    attend: <= t, and within the last ``window`` positions when sliding."""
    live = jnp.arange(t_max) <= t
    if window is not None:
        live &= jnp.arange(t_max) > t - window
    return live


def _with_rope(core):
    """Wrap a sequence-parallel attention core with RoPE: the rotation is
    per-position (applied on the GLOBAL [B, H, T, d] arrays before the core
    shards them), so rope composes exactly with ring/ulysses."""
    from paddle_tpu.ops.attention import apply_rope, rope_tables

    def rotated(qh, kh, vh, kv_len=None):
        cos, sin = rope_tables(qh.shape[-1], qh.shape[-2])
        q_r, k_r = apply_rope(qh, cos, sin), apply_rope(kh, cos, sin)
        return core(q_r, k_r, vh, kv_len=kv_len) if kv_len is not None else core(q_r, k_r, vh)

    return rotated


def lm_block(x, cfg, name, kv_len=None):
    """One decoder block: attention + FFN (dense or mixture-of-experts).
    Returns ``(x, aux_loss)`` — aux is the router load-balance loss when
    ``cfg['moe_experts']`` selects an expert-parallel MoE FFN
    (``parallel/moe.py``), else 0."""
    ring_mesh = cfg.get("ring_mesh")
    ulysses_mesh = cfg.get("ulysses_mesh")
    window = cfg.get("attention_window")
    if ring_mesh is not None:
        core = _ring_core(ring_mesh, window=window)
    elif ulysses_mesh is not None:
        core = _ulysses_core(ulysses_mesh, window=window)
    else:
        core = None
    if cfg.get("pos_encoding") == "rope":
        core = _with_rope(core) if core is not None else _rope_core(cfg)
    with name_scope(name):
        attn = multi_head_attention(
            x, x, x, cfg["d_model"], cfg["num_heads"],
            dropout_rate=cfg["attn_dropout"], causal=True, name="self_attn",
            core=core, num_kv_heads=cfg.get("num_kv_heads"),
            window=cfg.get("attention_window"), kv_len=kv_len,
        )
        x = _post_process(x, attn, cfg["residual_dropout"])
        if cfg.get("moe_experts"):
            from paddle_tpu.parallel.moe import moe_ffn

            # ragged batches: padding tokens are masked out of routing so
            # they consume no expert capacity and don't skew the balance
            token_mask = None
            if kv_len is not None:
                token_mask = (
                    jnp.arange(x.shape[-2])[None, :] < kv_len[:, None]
                )
            mo = moe_ffn(
                x, num_experts=cfg["moe_experts"], d_ff=cfg["d_inner"],
                capacity_factor=cfg.get("moe_capacity_factor", 1.25),
                router=cfg.get("moe_router", "top1"), name="moe_ffn",
                token_mask=token_mask,
            )
            ffn, aux = mo.output, mo.aux_loss
        else:
            ffn = positionwise_ffn(
                x, cfg["d_inner"], cfg["d_model"], cfg["relu_dropout"],
                activation=cfg.get("ffn_activation", "relu"),
            )
            aux = jnp.float32(0.0)
        return _post_process(x, ffn, cfg["residual_dropout"]), aux


def _block_caller(cfg):
    """Returns ``call(x, name) -> (x, aux)``; with cfg['remat'] each layer
    runs under jax.checkpoint — activations recompute in backward, so
    training memory scales with ONE layer's activations instead of
    n_layers (the standard long-context trade; transpiler/memory.py holds
    the named-policy variants). cfg/name are closed over (static); the
    framework's trace-time param creation fires inside the checkpointed
    region, which is safe — creation is name-keyed and idempotent across
    the fwd/bwd re-traces."""
    if not cfg.get("remat"):
        return lambda x, name, kv_len=None: lm_block(x, cfg, name, kv_len)

    def call(x, name, kv_len=None):
        # remat only matters for the backward pass: during init the param
        # initializer outputs would leak out of checkpoint's inner trace,
        # and in eval mode checkpoint's CSE barriers are a pure slowdown
        if pt.framework.is_initializing() or not pt.framework.is_training():
            return lm_block(x, cfg, name, kv_len)
        return jax.checkpoint(lambda y: lm_block(y, cfg, name, kv_len))(x)

    return call


def _scan_lm_blocks(x, cfg, seq_lens):
    """Run the layer stack as ONE ``lax.scan`` over stacked per-layer params
    instead of an unrolled Python loop — the canonical TPU pattern: the
    block body appears ONCE in the traced program regardless of depth
    (measured, 12-layer d_model=256 train step: 291 → 27 dot_generals in
    the lowered HLO). That bounds the expensive per-instance TPU kernel
    compilation (each unrolled layer is its own Mosaic flash fwd+bwd
    compile; scanned pays one) and keeps program size flat as n_layers
    grows. On CPU-XLA, where per-op compile is cheap, measured wall-clock
    compile is neutral-to-slightly-slower (scan adds loop/grad machinery)
    — the flag targets the TPU toolchain. Math is identical to the
    unrolled loop; the dropout STREAM differs (per-layer keys are
    pre-split rather than drawn from the frame sequence), so
    seeded-dropout runs are not bit-comparable across the two modes —
    loss statistics are unaffected.

    Mechanics: :func:`framework.scan_layer_stack` — per-layer parameter
    arrays (identical names/shapes across layers by construction) stack to
    [L, ...] pytrees; the scan body re-enters ``lm_block`` under a fresh
    :func:`framework.overlay_frame` mapping ``layer_tpl/...`` to the
    scanned slice. With ``cfg['remat']`` the body runs under
    ``jax.checkpoint`` (scan-of-checkpoint: activation memory O(one
    layer))."""
    return pt.framework.scan_layer_stack(
        x,
        cfg["n_layers"],
        lambda i: f"layer_{i}",
        "layer_tpl",
        lambda h, name: lm_block(h, cfg, name, seq_lens),
        remat=bool(cfg.get("remat")) and pt.framework.is_training(),
        with_aux=True,
    )


def _pipeline_lm_blocks(x, cfg):
    """Run the layer stack pipeline-parallel over cfg['pipe_mesh']'s
    ``pipe`` axis: layers split into n_stages contiguous groups, each pipe
    device owns one group's (stacked) params, and microbatch activations
    flow stage-to-stage through :func:`parallel.pipeline_apply` (GPipe
    schedule by ``ppermute``+``scan``; ``cfg['remat']`` gives the 1F1B
    memory profile). Inside a stage the group runs as a ``lax.scan`` over
    its layers — the same overlay mechanics as
    :func:`framework.scan_layer_stack`. Embedding/projection compute stays
    replicated across pipe ranks (their params are small next to the
    stack). v1 scope: dense batches (``seq_lens`` unsupported) and
    deterministic layers (dropout must be 0 — the pipeline body takes no
    rng stream); both are enforced at dispatch in :func:`lm_forward`.
    """
    from paddle_tpu.parallel.pipeline import pipeline_apply, split_microbatches

    mesh = cfg["pipe_mesh"]
    n_stages = mesh.shape["pipe"]
    L = cfg["n_layers"]
    pt.check(
        L % n_stages == 0,
        f"pipe parallelism needs n_layers ({L}) divisible by the pipe axis "
        f"({n_stages})",
    )
    lps = L // n_stages
    # [S, L/S, ...] per suffix: leading dim shards over the pipe axis
    stacked = {
        s: v.reshape((n_stages, lps) + v.shape[1:])
        for s, v in pt.framework.gather_layer_params(
            L, lambda i: f"layer_{i}"
        ).items()
    }

    def stage_fn(stage_params, h):
        def layer_body(carry, sl):
            overlay = {f"layer_tpl/{s}": v for s, v in sl.items()}
            with pt.framework.overlay_frame(overlay):
                # pipe stages carry activations only; MoE (whose aux loss
                # would be dropped here) is guarded off in lm_forward
                y, _ = lm_block(carry, cfg, "layer_tpl", None)
            return y, None

        h, _ = jax.lax.scan(layer_body, h, stage_params)
        return h

    n_micro = int(cfg.get("pipe_n_micro") or 2 * n_stages)
    mbs = split_microbatches(x, n_micro)
    out = pipeline_apply(
        stage_fn, stacked, mbs, mesh,
        # remat matters only for the backward; in eval it is a pure slowdown
        remat=bool(cfg.get("remat")) and pt.framework.is_training(),
    )
    return out.reshape(x.shape)


def lm_forward(ids, labels, seq_lens=None, *, cfg):
    """Next-token LM training forward: returns (loss, token_count, logits).

    ``ids``/``labels`` are [B, T] int32. ``seq_lens`` ([B] int32, optional)
    marks suffix padding for ragged batches: attention masks key positions
    >= seq_lens[b] structurally (kv_len through the flash kernels — and
    through ring/ulysses when a sequence-parallel mesh is configured), and
    the loss averages only positions p with p < seq_lens[b] - 1 (the last
    real token has no next-token target). Without it every position is a
    target (synthetic data has no padding)."""
    x = prepare_embedding(
        ids, cfg["vocab"], cfg["d_model"], cfg["max_len"],
        cfg["residual_dropout"], name="emb",
        add_position_encoding=cfg.get("pos_encoding", "sinusoid") != "rope",
    )
    if cfg.get("moe_experts"):
        pt.check(
            cfg.get("ffn_activation", "relu") == "relu",
            "moe_experts: expert FFNs are two-layer ReLU; "
            f"ffn_activation={cfg.get('ffn_activation')!r} is not supported "
            "in the MoE path (v1 scope)",
        )
        pt.check(
            not cfg["relu_dropout"],
            "moe_experts: expert FFNs have no dropout; set relu_dropout=0 "
            "(v1 scope)",
        )
    aux_total = jnp.float32(0.0)
    # dispatch precedence: pipe_mesh subsumes scan_layers (each pipe stage
    # already runs its layer group as a lax.scan — see _pipeline_lm_blocks),
    # so setting both is harmless and scan_layers adds nothing under pipe
    if cfg.get("pipe_mesh") is not None and not pt.framework.is_initializing():
        pt.check(
            cfg.get("ring_mesh") is None and cfg.get("ulysses_mesh") is None,
            "pipe_mesh: sequence parallelism (ring_mesh/ulysses_mesh) does "
            "not compose with the pipelined path (v1 scope)",
        )
        pt.check(seq_lens is None,
                 "pipe_mesh: ragged seq_lens unsupported in the pipelined "
                 "path (v1 scope)")
        pt.check(
            not (cfg["attn_dropout"] or cfg["relu_dropout"]
                 or cfg["residual_dropout"]),
            "pipe_mesh: dropout must be 0 (the pipeline body is "
            "deterministic; no rng stream threads through the schedule)",
        )
        pt.check(not cfg.get("moe_experts"),
                 "pipe_mesh: MoE FFNs unsupported in the pipelined path "
                 "(the stage schedule carries activations only, so the "
                 "router aux loss would be dropped)")
        x = _pipeline_lm_blocks(x, cfg)
    elif cfg.get("scan_layers") and not pt.framework.is_initializing():
        # init stays unrolled (trace-time param creation needs the real
        # per-layer names); apply scans — compile time O(1) in n_layers
        x, aux_total = _scan_lm_blocks(x, cfg, seq_lens)
    else:
        block = _block_caller(cfg)
        for i in range(cfg["n_layers"]):
            x, aux = block(x, name=f"layer_{i}", kv_len=seq_lens)
            aux_total = aux_total + aux
    x = layers.layer_norm(x, begin_norm_axis=x.ndim - 1)
    with name_scope("project"):
        logits = _proj(x, cfg["vocab"], shard_out=True, name="logits", bias=False)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    # MoE router load-balance term (0 for dense-FFN configs) — a TRAINING
    # regularizer only: eval loss must stay the pure NLL so perplexity and
    # dense-baseline comparisons are unbiased
    aux_term = (
        jnp.float32(cfg.get("moe_aux_weight", 0.01)) * aux_total
        if pt.framework.is_training()
        else jnp.float32(0.0)
    )
    if seq_lens is not None:
        valid = (jnp.arange(labels.shape[1])[None, :] < seq_lens[:, None] - 1)
        valid = valid.astype(jnp.float32)
        n_tok = jnp.maximum(jnp.sum(valid), 1.0)
        return jnp.sum(nll * valid) / n_tok + aux_term, n_tok, logits
    n_tok = float(np.prod(labels.shape))
    return jnp.mean(nll) + aux_term, n_tok, logits


def stack_decode_params(variables_or_params, cfg: dict) -> dict:
    """Stack the per-layer parameter arrays for ``scan_layers`` decode:
    {suffix: [L, ...]}. Call ONCE outside the jitted decode (or let jit
    close over the result) so the stack is not re-copied per call; pass to
    :func:`generate` as ``stacked_params``."""
    params = (variables_or_params.params
              if hasattr(variables_or_params, "params") else variables_or_params)
    return pt.framework.stack_layer_params(
        params, cfg["n_layers"], lambda i: f"layer_{i}"
    )


def generate(
    variables,
    prompt: jax.Array,
    max_new_tokens: int,
    cfg: dict,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
    cache_dtype=None,
    stacked_params: dict | None = None,
) -> jax.Array:
    """Autoregressive decode with a static k/v cache — prefill once over the
    prompt, then one ``lax.scan`` step per new token (single compile, no
    shape growth; the TPU-idiomatic replacement for the reference's
    per-step re-run of a decode program). Returns [B, max_new_tokens] int32.

    Implemented directly over the trained params dict (names as created by
    :func:`lm_forward`) so the decode loop is a plain jittable function —
    greedy at ``temperature=0``, else softmax sampling with ``rng``
    (required then). Deliberately NOT built on ``lm_block``: a scan-stepped
    static cache can't use ``multi_head_attention``'s shape-growing
    concatenate cache, and re-entering ``name_scope``s inside a scan body
    would re-uniquify parameter names. The decode math is pinned to
    ``lm_forward`` by ``test_transformer_lm_generate_matches_naive_decode``
    — change one, and that exact-match test catches the drift.

    ``cache_dtype`` (default f32): the k/v cache dtype. ``jnp.bfloat16``
    halves decode HBM traffic — the decode-throughput lever on TPU, where
    each step streams the whole cache — at bf16 rounding of cached keys/
    values (scores still accumulate f32; confident predictions are
    unaffected, see the memorized-decode test).
    """
    from paddle_tpu.core.enforce import enforce
    from paddle_tpu.models.transformer import sinusoid_position_encoding

    params = variables.params if hasattr(variables, "params") else variables
    B, Tp = prompt.shape
    T_max = Tp + max_new_tokens
    D, H, L = cfg["d_model"], cfg["num_heads"], cfg["n_layers"]
    dh = D // H
    H_kv = cfg.get("num_kv_heads") or H  # GQA: cache holds H_kv heads
    G = H // H_kv
    enforce(max_new_tokens >= 1, f"max_new_tokens must be >= 1, got {max_new_tokens}")
    enforce(
        temperature == 0.0 or rng is not None,
        "generate: sampling (temperature > 0) needs an explicit rng key — "
        "a silent fixed default would return identical 'samples' every call",
    )
    enforce(
        not cfg.get("moe_experts"),
        "generate: MoE FFNs are not supported in the cached decoders yet — "
        "decode with lm_forward teacher-forcing, or use a dense-FFN config",
    )
    rope = cfg.get("pos_encoding", "sinusoid") == "rope"
    swiglu = cfg.get("ffn_activation", "relu") == "swiglu"
    window = cfg.get("attention_window")
    pe = sinusoid_position_encoding(max(cfg["max_len"], T_max), D)
    if rope:
        from paddle_tpu.ops.attention import apply_rope, rope_tables

        rope_cos, rope_sin = rope_tables(dh, max(cfg["max_len"], T_max))
    scale = 1.0 / np.sqrt(dh)

    # scan-over-layers decode (cfg['scan_layers']): layer params stack to
    # [L, ...] by suffix and the per-token layer loop runs as a lax.scan;
    # inside the scan body the block's name-based lookups resolve through
    # ``scan_view`` via the reserved 'layer_SCAN/' prefix (the decode-side
    # analogue of framework.scan_layer_stack — compile cost O(1) in depth)
    scan_layers = bool(cfg.get("scan_layers"))
    scan_view: dict = {}
    if scan_layers:
        # prefer a caller-prestacked tree (stack_decode_params, built once
        # OUTSIDE jit / closed over by it) — stacking here would copy the
        # full parameter set on every jitted decode call
        stacked = (stacked_params if stacked_params is not None
                   else stack_decode_params(params, cfg))

    def p(name):
        if name.startswith("layer_SCAN/"):
            return scan_view[name[len("layer_SCAN/"):]]
        return params[name]

    def ln(x, pfx):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p(f"{pfx}/scale") + p(f"{pfx}/bias")

    def proj(x, pfx, bias=True):
        out = x @ p(f"{pfx}/w")
        return out + p(f"{pfx}/b") if bias else out

    ffn = _decode_ffn_fn(proj, swiglu)

    def heads(x, n=None):  # [B, T, n*dh] -> [B, n, T, dh]
        n = n or H
        return x.reshape(x.shape[0], x.shape[1], n, dh).transpose(0, 2, 1, 3)

    def grouped(q):  # [B, H, T, dh] -> [B, H_kv, G, T, dh]
        return q.reshape(q.shape[0], H_kv, G, q.shape[2], dh)

    def ungrouped(o):  # [B, H_kv, G, T, dh] -> [B, H, T, dh]
        return o.reshape(o.shape[0], H, o.shape[3], dh)

    def embed(ids, pos0):
        e = jnp.take(p("emb/embedding/word_emb"), ids, axis=0) * (D ** 0.5)
        if rope:  # position enters at the attention rotation instead
            return e
        t = ids.shape[1]
        return e + jax.lax.dynamic_slice_in_dim(pe, pos0, t, axis=0)

    def rotate(x, pos0):
        """RoPE at absolute positions [pos0, pos0+T): cached K is stored
        PRE-rotated (rotation depends only on the key's own position, and
        scores depend only on relative offsets)."""
        t = x.shape[2]
        cos = jax.lax.dynamic_slice_in_dim(rope_cos, pos0, t, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(rope_sin, pos0, t, axis=0)
        return apply_rope(x, cos, sin)

    def block(x, i, attend, pos0=0):
        pfx = f"layer_{i}/self_attn"
        q = heads(proj(x, f"{pfx}/q"))
        k = heads(proj(x, f"{pfx}/k"), H_kv)
        v = heads(proj(x, f"{pfx}/v"), H_kv)
        if rope:
            q = rotate(q, pos0)
            k = rotate(k, pos0)
        ctx = attend(q, k, v, i)  # [B, H, Tq, dh]
        ctx = ctx.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], D)
        x = ln(x + proj(ctx, f"{pfx}/out"), f"layer_{i}/layer_norm")
        return ln(x + ffn(x, i), f"layer_{i}/layer_norm_1")

    def logits_of(x_last):  # [B, D] -> [B, vocab]
        return ln(x_last, "layer_norm") @ p("project/logits/w")

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, -1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None:
            # nucleus: keep the smallest prefix of sorted probs with
            # cumulative mass >= top_p (the top token always survives)
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = cum - probs < top_p
            cutoff = jnp.min(
                jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
            )
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    # ---- prefill: full causal pass over the prompt fills caches [0, Tp)
    cdt = cache_dtype or jnp.float32
    kc0 = jnp.zeros((L, B, H_kv, T_max, dh), cdt)
    vc0 = jnp.zeros((L, B, H_kv, T_max, dh), cdt)
    caches = {"k": kc0, "v": vc0}

    # sdpa routes long prompts through the flash kernel when the flag is
    # on (no [Tp, Tp] materialization) and composes the identical
    # causal+window einsum math otherwise — same path as the training
    # forward, so decode-vs-forward stays exact
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    def run_layer_scan(x0, kc, vc, pos0, make_attend):
        """The shared layer-scan body for scan_layers prefill AND decode:
        repopulate the scan_view overlay from the stacked slice, run the
        block with an attend built for this layer index, carry caches."""
        def body(carry, sl):
            y, kc, vc = carry
            scan_view.clear()
            scan_view.update(sl["p"])
            li = sl["i"]

            def attend(q, k, v, _i):
                nonlocal kc, vc
                ctx, kc, vc = make_attend(q, k, v, li, kc, vc)
                return ctx

            y = block(y, "SCAN", attend, pos0=pos0)
            return (y, kc, vc), None

        return jax.lax.scan(
            body, (x0, kc, vc), {"p": stacked, "i": jnp.arange(L)}
        )[0]

    if scan_layers:
        def prefill_write(q, k, v, li, kc, vc):
            kc = kc.at[li, :, :, :Tp].set(k.astype(cdt))
            vc = vc.at[li, :, :, :Tp].set(v.astype(cdt))
            ctx = scaled_dot_product_attention(
                q, k, v, causal=True, window=window
            )
            return ctx, kc, vc

        x, kc_f, vc_f = run_layer_scan(
            embed(prompt, 0), kc0, vc0, 0, prefill_write
        )
        caches = {"k": kc_f, "v": vc_f}
    else:
        def prefill_attend(q, k, v, i):
            caches["k"] = caches["k"].at[i, :, :, :Tp].set(k.astype(cdt))
            caches["v"] = caches["v"].at[i, :, :, :Tp].set(v.astype(cdt))
            return scaled_dot_product_attention(
                q, k, v, causal=True, window=window
            )

        x = embed(prompt, 0)
        for i in range(L):
            x = block(x, i, prefill_attend, pos0=0)
    first_key, scan_rng = (
        jax.random.split(rng) if rng is not None else (None, None)
    )
    first_tok = sample(logits_of(x[:, -1]), first_key)

    # ---- decode: one token per scan step against the cache
    def step(carry, s):
        tok, kc, vc, key = carry
        t = Tp + s  # position of this token
        xt = embed(tok[:, None], t)  # [B, 1, D] — pos0 is traced; ok for slice

        def cached_attend(q, k, v, li, kc, vc):
            """One token's attention against layer ``li``'s cache rows
            (li may be traced under the layer scan); returns the updated
            caches alongside the context."""
            kc = jax.lax.dynamic_update_slice(kc, k[None].astype(cdt), (li, 0, 0, t, 0))
            vc = jax.lax.dynamic_update_slice(vc, v[None].astype(cdt), (li, 0, 0, t, 0))
            kci = jax.lax.dynamic_index_in_dim(kc, li, 0, keepdims=False)
            vci = jax.lax.dynamic_index_in_dim(vc, li, 0, keepdims=False)
            s_ = jnp.einsum("bkgqd,bktd->bkgqt", grouped(q), kci) * scale
            live = _live_mask(T_max, t, window)
            s_ = jnp.where(live[None, None, None, None, :], s_, -1e9)
            ctx = ungrouped(
                jnp.einsum("bkgqt,bktd->bkgqd", jax.nn.softmax(s_, -1), vci)
            )
            return ctx, kc, vc

        if scan_layers:
            y, kc, vc = run_layer_scan(xt, kc, vc, t, cached_attend)
        else:
            def attend_i(q, k, v, i):
                nonlocal kc, vc
                ctx, kc, vc = cached_attend(q, k, v, i, kc, vc)
                return ctx

            y = xt
            for i in range(L):
                y = block(y, i, attend_i, pos0=t)
        if key is not None:
            key, sub = jax.random.split(key)
        else:
            sub = None
        nxt = sample(logits_of(y[:, -1]), sub)
        return (nxt, kc, vc, key), tok

    if max_new_tokens == 1:
        return first_tok[:, None]
    carry = (first_tok, caches["k"], caches["v"], scan_rng)
    (last_tok, _, _, _), toks = jax.lax.scan(
        step, carry, jnp.arange(max_new_tokens - 1)
    )
    return jnp.concatenate([toks.transpose(1, 0), last_tok[:, None]], axis=1)


# ---- paged decode (serving.kv_cache / serving.decode) ---------------------
#
# The paged variant of generate()'s cache read/write: K/V live in fixed-size
# pages ([L, num_pages, H_kv, page_size, dh]) and each sequence maps logical
# positions to physical pages through an int32 page-table row. Every array
# shape below is a function of static config (slot count, table width, page
# size) — never of which requests are in flight — so the serving decode step
# compiles once and continuous batching (admit/evict between steps) never
# pays XLA again. Same parameter names and attention math as generate();
# the exactness test pins the two against each other.


def _paged_enforce(cfg, temperature, rng):
    from paddle_tpu.core.enforce import enforce

    enforce(
        not cfg.get("scan_layers"),
        "paged decode: scan_layers is not supported in the paged path yet "
        "(v1 scope: the layer loop is unrolled; use generate() for "
        "scan-layers decode)",
    )
    enforce(
        not cfg.get("moe_experts"),
        "paged decode: MoE FFNs are not supported in the cached decoders — "
        "use a dense-FFN config",
    )
    enforce(
        temperature == 0.0 or rng is not None,
        "paged decode: sampling (temperature > 0) needs an explicit rng key",
    )


def paged_cache_shape(cfg: dict, num_pages: int, page_size: int):
    """Shape of ``k_pages``/``v_pages`` for ``cfg``:
    ``[L, num_pages, H_kv, page_size, dh]``."""
    H = cfg["num_heads"]
    H_kv = cfg.get("num_kv_heads") or H
    dh = cfg["d_model"] // H
    return (cfg["n_layers"], num_pages, H_kv, page_size, dh)


def _paged_ops(params, cfg):
    """The p/ln/proj/ffn/logits/sample closures shared by the paged prefill
    and decode-step entry points — the same math as :func:`generate`'s
    inline copies (parameter names as created by :func:`lm_forward`)."""
    D, H = cfg["d_model"], cfg["num_heads"]
    dh = D // H
    swiglu = cfg.get("ffn_activation", "relu") == "swiglu"

    def p(name):
        return params[name]

    def ln(x, pfx):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p(f"{pfx}/scale") + p(f"{pfx}/bias")

    def proj(x, pfx, bias=True):
        out = x @ p(f"{pfx}/w")
        return out + p(f"{pfx}/b") if bias else out

    ffn = _decode_ffn_fn(proj, swiglu)

    def logits_of(x_last):
        return ln(x_last, "layer_norm") @ p("project/logits/w")

    def sample(logits, key, temperature, top_k, top_p):
        if temperature == 0.0:
            return jnp.argmax(logits, -1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None:
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = cum - probs < top_p
            cutoff = jnp.min(
                jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
                keepdims=True,
            )
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    return p, ln, proj, ffn, logits_of, sample


def _paged_live_mask(q_pos, t_eff: int, window):
    """[..., T_eff] bool: key position t visible from query position
    ``q_pos`` ([...] int32) — causal, and within the sliding window when
    configured. The gathered pages cover logical positions [0, T_eff); any
    slot beyond the sequence's written length is > q_pos and masks out."""
    t = jnp.arange(t_eff)
    live = t <= q_pos[..., None]
    if window is not None:
        live &= t > q_pos[..., None] - window
    return live


def paged_prefill_chunk(
    params,
    tokens: jax.Array,
    pos0: jax.Array,
    last_index: jax.Array,
    page_table: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    rng: jax.Array | None = None,
    *,
    cfg: dict,
    page_size: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
):
    """Prefill ONE sequence's chunk into its pages: ``tokens`` [C] int32 at
    absolute positions ``[pos0, pos0+C)``, mapped through ``page_table``
    [P] int32. Returns ``(next_token, k_pages, v_pages)`` where
    ``next_token`` (scalar int32) is sampled from the logits at chunk
    index ``last_index`` — meaningful only on the prompt's final chunk
    (the first generated token); earlier chunks ignore it.

    Long prompts run as a sequence of fixed-``C`` chunks (the final one
    padded up), so prompt length never changes the compiled program and a
    long prefill never monopolizes the decode loop — the engine interleaves
    one chunk per iteration. Queries at padded positions (>= the prompt
    end) write K/V that decode overwrites position-by-position before ever
    attending to them, and their own outputs are discarded.
    """
    from paddle_tpu.models.transformer import sinusoid_position_encoding

    params = params.params if hasattr(params, "params") else params
    _paged_enforce(cfg, temperature, rng)
    (C,) = tokens.shape
    P = page_table.shape[0]
    t_eff = P * page_size
    D, H = cfg["d_model"], cfg["num_heads"]
    dh = D // H
    H_kv = cfg.get("num_kv_heads") or H
    G = H // H_kv
    L = cfg["n_layers"]
    rope = cfg.get("pos_encoding", "sinusoid") == "rope"
    window = cfg.get("attention_window")
    scale = 1.0 / np.sqrt(dh)
    cdt = k_pages.dtype
    p, ln, proj, ffn, logits_of, sample = _paged_ops(params, cfg)

    e = jnp.take(p("emb/embedding/word_emb"), tokens, axis=0) * (D ** 0.5)
    if rope:
        from paddle_tpu.ops.attention import apply_rope, rope_tables

        rope_cos, rope_sin = rope_tables(dh, max(cfg["max_len"], t_eff))
    else:
        pe = sinusoid_position_encoding(max(cfg["max_len"], t_eff), D)
        e = e + jax.lax.dynamic_slice_in_dim(pe, pos0, C, axis=0)
    x = e[None]  # [1, C, D]
    pos = pos0 + jnp.arange(C, dtype=jnp.int32)
    phys = page_table[pos // page_size]  # [C] physical page per position
    off = pos % page_size
    live = _paged_live_mask(pos, t_eff, window)  # [C, T_eff]

    def heads(y, n):  # [1, C, n*dh] -> [1, n, C, dh]
        return y.reshape(1, C, n, dh).transpose(0, 2, 1, 3)

    for i in range(L):
        pfx = f"layer_{i}/self_attn"
        q = heads(proj(x, f"{pfx}/q"), H)
        k = heads(proj(x, f"{pfx}/k"), H_kv)
        v = heads(proj(x, f"{pfx}/v"), H_kv)
        if rope:
            cos = jax.lax.dynamic_slice_in_dim(rope_cos, pos0, C, axis=0)
            sin = jax.lax.dynamic_slice_in_dim(rope_sin, pos0, C, axis=0)
            q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
        # scatter the chunk's K/V into this sequence's pages (pre-rotated
        # K, exactly as generate() stores it)
        k_pages = k_pages.at[i, phys, :, off].set(
            k[0].transpose(1, 0, 2).astype(cdt))
        v_pages = v_pages.at[i, phys, :, off].set(
            v[0].transpose(1, 0, 2).astype(cdt))
        # gather the sequence's whole logical context back through the
        # table (includes the chunk just written) and mask by position
        kl = k_pages[i][page_table].transpose(1, 0, 2, 3).reshape(
            H_kv, t_eff, dh)[None]
        vl = v_pages[i][page_table].transpose(1, 0, 2, 3).reshape(
            H_kv, t_eff, dh)[None]
        qg = q.reshape(1, H_kv, G, C, dh)
        s = jnp.einsum("bkgqd,bktd->bkgqt", qg, kl) * scale
        s = jnp.where(live[None, None, None], s, -1e9)
        ctx = jnp.einsum("bkgqt,bktd->bkgqd", jax.nn.softmax(s, -1), vl)
        ctx = ctx.reshape(1, H, C, dh).transpose(0, 2, 1, 3).reshape(1, C, D)
        x = ln(x + proj(ctx, f"{pfx}/out"), f"layer_{i}/layer_norm")
        x = ln(x + ffn(x, i), f"layer_{i}/layer_norm_1")

    x_last = jax.lax.dynamic_index_in_dim(x[0], last_index, 0, keepdims=False)
    tok = sample(logits_of(x_last), rng, temperature, top_k, top_p)
    return tok, k_pages, v_pages


def paged_decode_step(
    params,
    tokens: jax.Array,
    positions: jax.Array,
    page_tables: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    rng: jax.Array | None = None,
    *,
    cfg: dict,
    page_size: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
):
    """One decode iteration for ``S`` independent sequences against the
    paged cache: embed ``tokens`` [S] at per-slot absolute ``positions``
    [S], write each token's K/V into its slot's pages, attend over each
    slot's gathered context, and sample the next token. Returns
    ``(next_tokens [S], k_pages, v_pages)``.

    Shapes depend only on (S, table width, page size, model config) — the
    continuous-batching contract: slots change occupants between calls
    without recompiling. Inactive slots point at the scratch page; their
    writes and outputs are garbage the engine ignores.

    The gather materializes each slot's ``[H_kv, T_eff, dh]`` context per
    layer — the straightforward XLA lowering. A Pallas paged-attention
    kernel that streams pages from HBM without the copy is the known TPU
    follow-up; the interface (pages + tables) is already shaped for it.
    """
    from paddle_tpu.models.transformer import sinusoid_position_encoding

    params = params.params if hasattr(params, "params") else params
    _paged_enforce(cfg, temperature, rng)
    (S,) = tokens.shape
    P = page_tables.shape[1]
    t_eff = P * page_size
    D, H = cfg["d_model"], cfg["num_heads"]
    dh = D // H
    H_kv = cfg.get("num_kv_heads") or H
    G = H // H_kv
    L = cfg["n_layers"]
    rope = cfg.get("pos_encoding", "sinusoid") == "rope"
    window = cfg.get("attention_window")
    scale = 1.0 / np.sqrt(dh)
    cdt = k_pages.dtype
    p, ln, proj, ffn, logits_of, sample = _paged_ops(params, cfg)

    x = jnp.take(p("emb/embedding/word_emb"), tokens, axis=0) * (D ** 0.5)
    if rope:
        from paddle_tpu.ops.attention import rope_tables

        rope_cos, rope_sin = rope_tables(dh, max(cfg["max_len"], t_eff))
        cos, sin = rope_cos[positions], rope_sin[positions]  # [S, dh//2]

        def rot(y):  # [S, n, dh] rotated at each slot's own position
            half = dh // 2
            y1, y2 = y[..., :half], y[..., half:]
            c, s_ = cos[:, None, :], sin[:, None, :]
            yf1, yf2 = y1.astype(jnp.float32), y2.astype(jnp.float32)
            return jnp.concatenate(
                [yf1 * c - yf2 * s_, yf1 * s_ + yf2 * c], -1
            ).astype(y.dtype)
    else:
        pe = sinusoid_position_encoding(max(cfg["max_len"], t_eff), D)
        x = x + pe[positions]
    phys = page_tables[jnp.arange(S), positions // page_size]  # [S]
    off = positions % page_size
    live = _paged_live_mask(positions, t_eff, window)  # [S, T_eff]

    for i in range(L):
        pfx = f"layer_{i}/self_attn"
        q = proj(x, f"{pfx}/q").reshape(S, H, dh)
        k = proj(x, f"{pfx}/k").reshape(S, H_kv, dh)
        v = proj(x, f"{pfx}/v").reshape(S, H_kv, dh)
        if rope:
            q, k = rot(q), rot(k)
        k_pages = k_pages.at[i, phys, :, off].set(k.astype(cdt))
        v_pages = v_pages.at[i, phys, :, off].set(v.astype(cdt))
        kl = k_pages[i][page_tables].transpose(0, 2, 1, 3, 4).reshape(
            S, H_kv, t_eff, dh)
        vl = v_pages[i][page_tables].transpose(0, 2, 1, 3, 4).reshape(
            S, H_kv, t_eff, dh)
        qg = q.reshape(S, H_kv, G, dh)
        s = jnp.einsum("skgd,sktd->skgt", qg, kl) * scale
        s = jnp.where(live[:, None, None], s, -1e9)
        ctx = jnp.einsum("skgt,sktd->skgd", jax.nn.softmax(s, -1), vl)
        ctx = ctx.reshape(S, D)
        x = ln(x + proj(ctx, f"{pfx}/out"), f"layer_{i}/layer_norm")
        x = ln(x + ffn(x, i), f"layer_{i}/layer_norm_1")

    nxt = sample(logits_of(x), rng, temperature, top_k, top_p)
    return nxt, k_pages, v_pages


def paged_verify_step(
    params,
    tokens: jax.Array,
    positions: jax.Array,
    page_tables: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    *,
    cfg: dict,
    page_size: int,
):
    """One speculative verify iteration for ``S`` sequences: score a block
    of ``K+1`` tokens per slot against the paged cache in a single jitted
    call. ``tokens`` [S, K+1] holds slot ``s``'s last sampled token followed
    by its ``K`` draft proposals; they occupy absolute positions
    ``positions[s] .. positions[s]+K``. All K+1 K/V rows are written into
    the slot's pages, the block attends causally over the gathered context
    (token ``j`` sees every earlier position plus drafts ``< j`` written
    this same call, exactly like a prefill chunk), and the return value
    ``out`` [S, K+1] is the greedy argmax after each position — i.e.
    ``out[s, j]`` is what sequential decode would have sampled after
    consuming ``tokens[s, :j+1]``. The engine accepts the longest prefix
    with ``draft[j] == out[s, j-1]``, which makes greedy speculative decode
    token-exact by construction.

    Greedy only: acceptance compares argmaxes, so sampling temperature
    would break exactness — the engine enforces ``temperature == 0``.
    Shapes depend only on (S, K, table width, page size, model config), so
    this compiles once ever, same as :func:`paged_decode_step`. Rejected
    draft positions need no device-side rollback: their K/V rows sit past
    the accepted frontier, masked (``t > q_pos``) until the next block
    overwrites them.
    """
    from paddle_tpu.models.transformer import sinusoid_position_encoding

    params = params.params if hasattr(params, "params") else params
    _paged_enforce(cfg, 0.0, None)
    S, K1 = tokens.shape
    P = page_tables.shape[1]
    t_eff = P * page_size
    D, H = cfg["d_model"], cfg["num_heads"]
    dh = D // H
    H_kv = cfg.get("num_kv_heads") or H
    G = H // H_kv
    L = cfg["n_layers"]
    rope = cfg.get("pos_encoding", "sinusoid") == "rope"
    window = cfg.get("attention_window")
    scale = 1.0 / np.sqrt(dh)
    cdt = k_pages.dtype
    p, ln, proj, ffn, logits_of, _ = _paged_ops(params, cfg)

    x = jnp.take(p("emb/embedding/word_emb"), tokens, axis=0) * (D ** 0.5)
    pos = positions[:, None] + jnp.arange(K1, dtype=jnp.int32)  # [S, K1]
    if rope:
        from paddle_tpu.ops.attention import rope_tables

        rope_cos, rope_sin = rope_tables(dh, max(cfg["max_len"], t_eff))
        cos, sin = rope_cos[pos], rope_sin[pos]  # [S, K1, dh//2]

        def rot(y):  # [S, K1, n, dh] rotated at each token's own position
            half = dh // 2
            y1, y2 = y[..., :half], y[..., half:]
            c, s_ = cos[:, :, None, :], sin[:, :, None, :]
            yf1, yf2 = y1.astype(jnp.float32), y2.astype(jnp.float32)
            return jnp.concatenate(
                [yf1 * c - yf2 * s_, yf1 * s_ + yf2 * c], -1
            ).astype(y.dtype)
    else:
        pe = sinusoid_position_encoding(max(cfg["max_len"], t_eff), D)
        x = x + pe[pos]
    phys = page_tables[jnp.arange(S)[:, None], pos // page_size]  # [S, K1]
    off = pos % page_size
    live = _paged_live_mask(pos, t_eff, window)  # [S, K1, T_eff]

    for i in range(L):
        pfx = f"layer_{i}/self_attn"
        q = proj(x, f"{pfx}/q").reshape(S, K1, H, dh)
        k = proj(x, f"{pfx}/k").reshape(S, K1, H_kv, dh)
        v = proj(x, f"{pfx}/v").reshape(S, K1, H_kv, dh)
        if rope:
            q, k = rot(q), rot(k)
        k_pages = k_pages.at[i, phys, :, off].set(k.astype(cdt))
        v_pages = v_pages.at[i, phys, :, off].set(v.astype(cdt))
        kl = k_pages[i][page_tables].transpose(0, 2, 1, 3, 4).reshape(
            S, H_kv, t_eff, dh)
        vl = v_pages[i][page_tables].transpose(0, 2, 1, 3, 4).reshape(
            S, H_kv, t_eff, dh)
        qg = q.transpose(0, 2, 1, 3).reshape(S, H_kv, G, K1, dh)
        s = jnp.einsum("skgqd,sktd->skgqt", qg, kl) * scale
        s = jnp.where(live[:, None, None], s, -1e9)
        ctx = jnp.einsum("skgqt,sktd->skgqd", jax.nn.softmax(s, -1), vl)
        ctx = ctx.reshape(S, H, K1, dh).transpose(0, 2, 1, 3).reshape(
            S, K1, D)
        x = ln(x + proj(ctx, f"{pfx}/out"), f"layer_{i}/layer_norm")
        x = ln(x + ffn(x, i), f"layer_{i}/layer_norm_1")

    out = jnp.argmax(logits_of(x), -1).astype(jnp.int32)  # [S, K1]
    return out, k_pages, v_pages


BASE_CFG = dict(
    vocab=32000,
    d_model=512,
    d_inner=2048,
    num_heads=8,
    num_kv_heads=None,  # < num_heads -> grouped-query attention
    pos_encoding="sinusoid",  # or "rope" (rotary, applied at attention)
    ffn_activation="relu",  # or "swiglu"
    attention_window=None,  # int -> sliding-window attention (O(T*W))
    n_layers=6,
    max_len=8192,
    attn_dropout=0.0,
    relu_dropout=0.0,
    residual_dropout=0.0,
    remat=False,
    # run the layer stack as one lax.scan over stacked params: compile time
    # O(1) in n_layers (see _scan_lm_blocks); dropout stream differs from
    # the unrolled loop, math is otherwise identical
    scan_layers=False,
    # mixture-of-experts FFN (parallel/moe.py): 0 = dense. Expert weights
    # shard over the 'expert' mesh axis; the router aux (load-balance) loss
    # joins the training loss with moe_aux_weight
    moe_experts=0,
    moe_router="top1",  # or "top2" (GShard pair dispatch)
    moe_capacity_factor=1.25,
    moe_aux_weight=0.01,
)


def get_model(
    seq_len: int = 1024, learning_rate: float = 1e-3, ring_mesh=None,
    ulysses_mesh=None, **overrides
) -> ModelSpec:
    """``ring_mesh``: a Mesh with a ``seq`` axis → attention runs as ring
    attention over it (sequence-parallel exact attention; batch tokens must
    be fed sharded [data, seq]). ``ulysses_mesh``: same contract but via
    all-to-all head resharding (``ops/ulysses.py``) — pick ring for
    T >> heads, ulysses for heads >= seq-axis size."""
    cfg = dict(BASE_CFG)
    cfg.update({k: v for k, v in overrides.items() if k in cfg})
    cfg["max_len"] = max(cfg["max_len"], seq_len)
    if ring_mesh is not None:
        cfg["ring_mesh"] = ring_mesh
    if ulysses_mesh is not None:
        cfg["ulysses_mesh"] = ulysses_mesh
    if overrides.get("pipe_mesh") is not None:
        cfg["pipe_mesh"] = overrides["pipe_mesh"]
        cfg["pipe_n_micro"] = overrides.get("pipe_n_micro")

    model = pt.build(functools.partial(lm_forward, cfg=cfg), name="transformer_lm")

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        ids = rng.randint(1, cfg["vocab"], size=(batch_size, seq_len)).astype(np.int32)
        labels = rng.randint(1, cfg["vocab"], size=(batch_size, seq_len)).astype(np.int32)
        return ids, labels

    return ModelSpec(
        name="transformer_lm",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Adam(learning_rate=learning_rate),
        unit="tokens/sec",
        examples_per_row=seq_len,
        extra={"cfg": cfg, "seq_len": seq_len},
    )


def generate_beam(
    variables,
    prompt: jax.Array,
    max_new_tokens: int,
    cfg: dict,
    beam_size: int = 4,
    eos_id: int = 1,
    length_penalty_alpha: float = 0.0,
    cache_dtype=None,
    stacked_params: dict | None = None,
):
    """Beam-search continuation of ``prompt``: returns
    ``(sequences [B, beam, max_new_tokens], scores [B, beam])`` best-first.

    Built on the generic :func:`paddle_tpu.ops.control_flow.beam_search`
    (the reference's beam_search/beam_search_decode op pair — beam search is
    a first-class path there, ``operators/beam_search_op.cc``) over the same
    static k/v cache layout as :func:`generate`: the prompt minus its last
    token is prefilled into the cache, each row's last prompt token seeds
    its beams, and every scan step attends against cache[0..t]. Same decode
    math as ``generate`` (same param names/ops); GQA cache layout included.

    ``cfg['scan_layers']`` runs the per-token (and prefill) layer loop as a
    ``lax.scan`` over stacked params, exactly as in :func:`generate` — one
    traced layer body regardless of depth, so deep-model beam decode pays
    O(1) compile cost (VERDICT r4 #6). Beam caches keep the layer axis at
    dim 1 (beam tiling stays on dim 0); the scan indexes it dynamically.
    Pass ``stacked_params`` (from :func:`stack_decode_params`) to avoid
    re-stacking per jitted call.
    """
    from paddle_tpu.core.enforce import enforce
    from paddle_tpu.models.transformer import sinusoid_position_encoding
    from paddle_tpu.ops import control_flow as ocf

    params = variables.params if hasattr(variables, "params") else variables
    B, Tp = prompt.shape
    enforce(Tp >= 1, "generate_beam needs a non-empty prompt")
    enforce(
        not cfg.get("moe_experts"),
        "generate_beam: MoE FFNs are not supported in the cached decoders "
        "yet — use a dense-FFN config",
    )
    T_max = Tp + max_new_tokens
    D, H, L = cfg["d_model"], cfg["num_heads"], cfg["n_layers"]
    dh = D // H
    H_kv = cfg.get("num_kv_heads") or H
    G = H // H_kv
    rope = cfg.get("pos_encoding", "sinusoid") == "rope"
    swiglu = cfg.get("ffn_activation", "relu") == "swiglu"
    window = cfg.get("attention_window")
    pe = sinusoid_position_encoding(max(cfg["max_len"], T_max), D)
    if rope:
        from paddle_tpu.ops.attention import apply_rope, rope_tables

        rope_cos, rope_sin = rope_tables(dh, max(cfg["max_len"], T_max))
    scale = 1.0 / np.sqrt(dh)

    scan_layers = bool(cfg.get("scan_layers"))
    scan_view: dict = {}
    if scan_layers:
        stacked = (stacked_params if stacked_params is not None
                   else stack_decode_params(params, cfg))

    def p(name):
        if name.startswith("layer_SCAN/"):
            return scan_view[name[len("layer_SCAN/"):]]
        return params[name]

    def ln(x, pfx):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p(f"{pfx}/scale") + p(f"{pfx}/bias")

    def proj(x, pfx, bias=True):
        out = x @ p(f"{pfx}/w")
        return out + p(f"{pfx}/b") if bias else out

    ffn = _decode_ffn_fn(proj, swiglu)

    def heads(x, n):
        return x.reshape(x.shape[0], x.shape[1], n, dh).transpose(0, 2, 1, 3)

    def embed(ids, pos0):
        e = jnp.take(p("emb/embedding/word_emb"), ids, axis=0) * (D ** 0.5)
        if rope:
            return e
        return e + jax.lax.dynamic_slice_in_dim(pe, pos0, ids.shape[1], axis=0)

    def rotate(x, pos0):  # pre-rotated K cache (see generate())
        t = x.shape[2]
        cos = jax.lax.dynamic_slice_in_dim(rope_cos, pos0, t, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(rope_sin, pos0, t, axis=0)
        return apply_rope(x, cos, sin)

    def attn_vs_cache(q, kc_l, vc_l, t):
        # q [N, H, 1, dh]; kc_l/vc_l [N, H_kv, T_max, dh]; attend over [0, t]
        n = q.shape[0]
        qg = q.reshape(n, H_kv, G, 1, dh)
        s = jnp.einsum("bkgqd,bktd->bkgqt", qg, kc_l) * scale
        live = _live_mask(T_max, t, window)
        s = jnp.where(live[None, None, None, None, :], s, -1e9)
        o = jnp.einsum("bkgqt,bktd->bkgqd", jax.nn.softmax(s, -1), vc_l)
        return o.reshape(n, H, 1, dh)

    def block(x, i, attend, pos0=0):
        pfx = f"layer_{i}/self_attn"
        q = heads(proj(x, f"{pfx}/q"), H)
        k = heads(proj(x, f"{pfx}/k"), H_kv)
        v = heads(proj(x, f"{pfx}/v"), H_kv)
        if rope:
            q = rotate(q, pos0)
            k = rotate(k, pos0)
        ctx = attend(q, k, v, i)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], D)
        x = ln(x + proj(ctx, f"{pfx}/out"), f"layer_{i}/layer_norm")
        return ln(x + ffn(x, i), f"layer_{i}/layer_norm_1")

    def logits_of(x_last):
        return ln(x_last, "layer_norm") @ p("project/logits/w")

    def run_layer_scan(x0, kc, vc, pos0, make_attend):
        """generate()'s scanned layer loop, beam cache layout (layer axis at
        dim 1): repopulate the scan_view overlay per slice, carry caches."""
        def body(carry, sl):
            y, kc, vc = carry
            scan_view.clear()
            scan_view.update(sl["p"])
            li = sl["i"]

            def attend(q, k, v, _i):
                nonlocal kc, vc
                ctx, kc, vc = make_attend(q, k, v, li, kc, vc)
                return ctx

            y = block(y, "SCAN", attend, pos0=pos0)
            return (y, kc, vc), None

        return jax.lax.scan(
            body, (x0, kc, vc), {"p": stacked, "i": jnp.arange(L)}
        )[0]

    # --- prefill positions [0, Tp-1): full causal pass over the prompt head
    from paddle_tpu.ops.attention import scaled_dot_product_attention

    cdt = cache_dtype or jnp.float32  # bf16 halves decode HBM traffic
    kc0 = jnp.zeros((B, L, H_kv, T_max, dh), cdt)
    vc0 = jnp.zeros((B, L, H_kv, T_max, dh), cdt)
    caches = {"k": kc0, "v": vc0}
    Thead = Tp - 1
    if Thead > 0 and scan_layers:
        def prefill_write(q, k, v, li, kc, vc):
            kc = jax.lax.dynamic_update_slice(
                kc, k[:, None].astype(cdt), (0, li, 0, 0, 0)
            )
            vc = jax.lax.dynamic_update_slice(
                vc, v[:, None].astype(cdt), (0, li, 0, 0, 0)
            )
            ctx = scaled_dot_product_attention(q, k, v, causal=True, window=window)
            return ctx, kc, vc

        x, kc_f, vc_f = run_layer_scan(
            embed(prompt[:, :Thead], 0), kc0, vc0, 0, prefill_write
        )
        caches = {"k": kc_f, "v": vc_f}
    elif Thead > 0:
        def prefill_attend(q, k, v, i):
            caches["k"] = caches["k"].at[:, i, :, :Thead].set(k.astype(cdt))
            caches["v"] = caches["v"].at[:, i, :, :Thead].set(v.astype(cdt))
            # flash-capable prefill, exactly as in generate()
            return scaled_dot_product_attention(q, k, v, causal=True, window=window)

        x = embed(prompt[:, :Thead], 0)
        for i in range(L):
            x = block(x, i, prefill_attend, pos0=0)

    # --- beam decode: carry leaves are [B, ...] (beam_search tiles dim 0)
    init_carry = {"k": caches["k"], "v": caches["v"],
                  "t": jnp.full((B,), Thead, jnp.int32)}

    def step_fn(carry, tokens):
        t = carry["t"][0]
        xt = embed(tokens[:, None], t)
        kc, vc = carry["k"], carry["v"]

        if scan_layers:
            def cached_attend(q, k, v, li, kc, vc):
                kc = jax.lax.dynamic_update_slice(
                    kc, k[:, None].astype(kc.dtype), (0, li, 0, t, 0)
                )
                vc = jax.lax.dynamic_update_slice(
                    vc, v[:, None].astype(vc.dtype), (0, li, 0, t, 0)
                )
                kci = jax.lax.dynamic_index_in_dim(kc, li, 1, keepdims=False)
                vci = jax.lax.dynamic_index_in_dim(vc, li, 1, keepdims=False)
                return attn_vs_cache(q, kci, vci, t), kc, vc

            y, kc, vc = run_layer_scan(xt, kc, vc, t, cached_attend)
        else:
            def attend(q, k, v, i):
                nonlocal kc, vc
                kc = jax.lax.dynamic_update_slice(kc, k[:, None].astype(kc.dtype), (0, i, 0, t, 0))
                vc = jax.lax.dynamic_update_slice(vc, v[:, None].astype(vc.dtype), (0, i, 0, t, 0))
                return attn_vs_cache(q, kc[:, i], vc[:, i], t)

            y = xt
            for i in range(L):
                y = block(y, i, attend, pos0=t)
        logp = jax.nn.log_softmax(logits_of(y[:, -1]).astype(jnp.float32), -1)
        return {"k": kc, "v": vc, "t": carry["t"] + 1}, logp

    return ocf.beam_search(
        step_fn,
        init_carry,
        batch_size=B,
        beam_size=beam_size,
        vocab_size=cfg["vocab"],
        max_len=max_new_tokens,
        bos_id=prompt[:, -1],
        eos_id=eos_id,
        length_penalty_alpha=length_penalty_alpha,
    )

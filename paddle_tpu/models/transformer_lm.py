"""Decoder-only causal language model (GPT-style) — the long-context
flagship for the flash-attention + bf16 training path.

The reference benchmark suite has no decoder-only config (its transformer
is the NMT encoder-decoder, ``benchmark/fluid/models/transformer.py``);
this model extends the family the TPU-first way: causal masking is
STRUCTURAL (``scaled_dot_product_attention(causal=True)`` → the Pallas
flash kernel skips above-diagonal blocks and never materializes [T, T]),
sequence length is a config knob up to 8k+ (ring attention / seq-axis
sharding take over beyond single-chip VMEM), and matmuls run bf16 under
``flags().use_bf16_compute``.

Sharding: reuses the Megatron-style column/row-parallel projections of
``models/transformer.py`` (q/k/v/fc1 column, out/fc2 row over the model
axis).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import name_scope
from paddle_tpu.models import ModelSpec
from paddle_tpu.models.transformer import (
    _post_process,
    _proj,
    multi_head_attention,
    positionwise_ffn,
    prepare_embedding,
)

__all__ = ["get_model", "lm_forward", "BASE_CFG"]


def _ring_core(ring_mesh):
    """Attention core for sequence-parallel long context: exact causal
    attention over the seq-sharded global sequence via the ring
    (``ops/ring_attention.py``) instead of XLA's all-gather lowering."""
    from paddle_tpu.ops.ring_attention import ring_attention_sharded

    return lambda qh, kh, vh: ring_attention_sharded(
        qh, kh, vh, ring_mesh, causal=True
    )


def lm_block(x, cfg, name):
    ring_mesh = cfg.get("ring_mesh")
    with name_scope(name):
        attn = multi_head_attention(
            x, x, x, cfg["d_model"], cfg["num_heads"],
            dropout_rate=cfg["attn_dropout"], causal=True, name="self_attn",
            core=_ring_core(ring_mesh) if ring_mesh is not None else None,
        )
        x = _post_process(x, attn, cfg["residual_dropout"])
        ffn = positionwise_ffn(x, cfg["d_inner"], cfg["d_model"], cfg["relu_dropout"])
        return _post_process(x, ffn, cfg["residual_dropout"])


def lm_forward(ids, labels, *, cfg):
    """Next-token LM training forward: returns (loss, token_count, logits).

    ``ids``/``labels`` are [B, T] int32; every position is a target (synthetic
    data has no padding — real data shifts by one and masks the tail)."""
    x = prepare_embedding(
        ids, cfg["vocab"], cfg["d_model"], cfg["max_len"],
        cfg["residual_dropout"], name="emb",
    )
    for i in range(cfg["n_layers"]):
        x = lm_block(x, cfg, name=f"layer_{i}")
    x = layers.layer_norm(x, begin_norm_axis=x.ndim - 1)
    with name_scope("project"):
        logits = _proj(x, cfg["vocab"], shard_out=True, name="logits", bias=False)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    n_tok = float(np.prod(labels.shape))
    return jnp.mean(nll), n_tok, logits


BASE_CFG = dict(
    vocab=32000,
    d_model=512,
    d_inner=2048,
    num_heads=8,
    n_layers=6,
    max_len=8192,
    attn_dropout=0.0,
    relu_dropout=0.0,
    residual_dropout=0.0,
)


def get_model(
    seq_len: int = 1024, learning_rate: float = 1e-3, ring_mesh=None, **overrides
) -> ModelSpec:
    """``ring_mesh``: a Mesh with a ``seq`` axis → attention runs as ring
    attention over it (sequence-parallel exact attention; batch tokens must
    be fed sharded [data, seq])."""
    cfg = dict(BASE_CFG)
    cfg.update({k: v for k, v in overrides.items() if k in cfg})
    cfg["max_len"] = max(cfg["max_len"], seq_len)
    if ring_mesh is not None:
        cfg["ring_mesh"] = ring_mesh

    model = pt.build(functools.partial(lm_forward, cfg=cfg), name="transformer_lm")

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        ids = rng.randint(1, cfg["vocab"], size=(batch_size, seq_len)).astype(np.int32)
        labels = rng.randint(1, cfg["vocab"], size=(batch_size, seq_len)).astype(np.int32)
        return ids, labels

    return ModelSpec(
        name="transformer_lm",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Adam(learning_rate=learning_rate),
        unit="tokens/sec",
        examples_per_row=seq_len,
        extra={"cfg": cfg, "seq_len": seq_len},
    )

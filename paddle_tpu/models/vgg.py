"""VGG-16 with batch norm (cifar10 / flowers configs).

Reference: ``benchmark/fluid/models/vgg.py`` — five img_conv_group blocks
(all convs BN+dropout, 3×3 SAME), two dropout+fc(512)+BN head layers, final
fc softmax; Adam(lr=1e-3).
"""

from __future__ import annotations

import functools

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, nets
from paddle_tpu.models import ModelSpec


def vgg16_bn_drop(input):
    def conv_block(ipt, num_filter, groups, dropouts):
        return nets.img_conv_group(
            ipt,
            conv_num_filter=[num_filter] * groups,
            pool_size=2,
            pool_stride=2,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(conv5, dropout_prob=0.5)
    fc1 = layers.fc(drop, size=512)
    bn = layers.batch_norm(fc1[:, None, None, :], act="relu")[:, 0, 0, :]
    drop2 = layers.dropout(bn, dropout_prob=0.5)
    fc2 = layers.fc(drop2, size=512)
    return fc2


def _forward(images, labels, *, class_dim):
    feat = vgg16_bn_drop(images)
    logits = layers.fc(feat, size=class_dim)
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(logits, labels)
    return avg_loss, acc, logits


def get_model(
    dataset: str = "cifar10",
    class_dim: int = None,
    image_size: int = None,
    learning_rate: float = 1e-3,
    **_unused,
) -> ModelSpec:
    if dataset == "cifar10":
        class_dim = class_dim or 10
        image_size = image_size or 32
    else:
        class_dim = class_dim or 102
        image_size = image_size or 224

    model = pt.build(functools.partial(_forward, class_dim=class_dim), name=f"vgg16_{dataset}")

    def synth_batch(batch_size: int, rng: np.random.RandomState):
        images = rng.rand(batch_size, image_size, image_size, 3).astype(np.float32)
        labels = rng.randint(0, class_dim, size=(batch_size,)).astype(np.int32)
        return images, labels

    return ModelSpec(
        name="vgg16",
        model=model,
        synth_batch=synth_batch,
        optimizer=lambda: pt.optimizer.Adam(learning_rate=learning_rate),
        unit="images/sec",
        extra={"class_dim": class_dim, "image_size": image_size},
    )

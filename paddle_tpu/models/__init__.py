"""Model zoo — the ``benchmark/fluid/models`` configs rebuilt TPU-first.

Reference: ``benchmark/fluid/models/{mnist,resnet,se_resnext,vgg,
machine_translation,stacked_dynamic_lstm}.py`` and
``benchmark/fluid/fluid_benchmark.py:310`` (model registry / get_model
protocol). Each module here exposes ``get_model(**cfg) -> ModelSpec`` where
the spec carries a built :class:`paddle_tpu.framework.Model` whose forward
returns ``(loss, metric_or_logits, ...)``, plus a synthetic-batch generator
mirroring the reference's fake-data path
(``fluid_benchmark.py:148-162`` fill-constant feeds).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.framework import Model

__all__ = ["ModelSpec", "get_model", "MODELS"]


@dataclasses.dataclass
class ModelSpec:
    """A runnable benchmark config (get_model protocol)."""

    name: str
    model: Model
    # synth_batch(batch_size, rng) -> tuple of numpy arrays fed to model.apply
    synth_batch: Callable[[int, np.random.RandomState], Tuple[np.ndarray, ...]]
    optimizer: Callable[[], Any]
    unit: str = "examples/sec"
    # elements counted per batch row for throughput (e.g. tokens per sentence)
    examples_per_row: int = 1
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


def get_model(name: str, **cfg) -> ModelSpec:
    """Look up and instantiate a benchmark model by reference name."""
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; available: {sorted(MODELS)}")
    return MODELS[name](**cfg)


def _mnist(**cfg):
    from paddle_tpu.models import mnist

    return mnist.get_model(**cfg)


def _resnet(**cfg):
    from paddle_tpu.models import resnet

    return resnet.get_model(**cfg)


def _se_resnext(**cfg):
    from paddle_tpu.models import se_resnext

    return se_resnext.get_model(**cfg)


def _vgg(**cfg):
    from paddle_tpu.models import vgg

    return vgg.get_model(**cfg)


def _transformer(**cfg):
    from paddle_tpu.models import transformer

    return transformer.get_model(**cfg)


def _stacked_dynamic_lstm(**cfg):
    from paddle_tpu.models import stacked_lstm

    return stacked_lstm.get_model(**cfg)


def _machine_translation(**cfg):
    from paddle_tpu.models import machine_translation

    return machine_translation.get_model(**cfg)


def _transformer_lm(**cfg):
    from paddle_tpu.models import transformer_lm

    return transformer_lm.get_model(**cfg)


MODELS: Dict[str, Callable[..., ModelSpec]] = {
    "mnist": _mnist,
    "resnet": _resnet,
    "se_resnext": _se_resnext,
    "vgg": _vgg,
    "transformer": _transformer,
    "transformer_lm": _transformer_lm,
    "stacked_dynamic_lstm": _stacked_dynamic_lstm,
    "machine_translation": _machine_translation,
}

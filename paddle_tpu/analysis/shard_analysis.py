"""Static sharding-layout analyzer: PartitionSpec propagation with zero FLOPs.

PR 16 made tensor-parallel replica groups the unit of serving dispatch,
but the invariants that keep a :class:`~paddle_tpu.serving.shardgroup.
GroupLayout` fast and correct were only checked dynamically — after
params were placed and devices burned. This pass checks them from the
program alone (the reference framework verified ``ProgramDesc`` before
execution; GSPMD/GDP argue sharding decisions should be validated and
costed statically): a ``jax.eval_shape`` param tree + a rule table + a
mesh *shape* (a plain ``{axis: size}`` dict — no devices are touched) in,
typed :class:`~paddle_tpu.analysis.diagnostics.Diagnostic`\\ s out.

Diagnostic codes (stable; tests and the CI gate match on them):

* ``shard-dead-rule`` (error) — a rule matches no parameter: stale after
  a rename, or a layout written for a different model family. Rules in
  ``GroupLayout.optional`` (e.g. the swiglu gate projections on a relu
  model) are exempt.
* ``shard-rank-mismatch`` (error) — a matched spec names more dims than
  the parameter has rank (the same condition ``spec_for(ndim=...)``
  raises at placement time, reported here as a finding so one run lists
  every offender).
* ``shard-silent-degrade`` (warning) — the axis exists but does not
  divide the dim, so ``degrade_spec`` silently replicates it; the message
  carries the per-device HBM cost of the degrade. Mirrors the runtime
  ``sharding.degraded_total`` counter exactly.
* ``shard-unknown-axis`` (warning) — a spec names a mesh axis the target
  mesh does not have (a training-layout axis leaking into a serving
  mesh); placement degrades it by contract, but the rule cannot ever
  shard on this mesh.
* ``shard-conflict`` (error, :func:`compare_layouts`) — two layouts
  (e.g. training vs serving) give the same parameter different effective
  specs: every transition re-lays the weights out across the mesh.
* ``shard-kv-geometry`` (error) — the KV-page spec or shape disagrees
  with ``PagedKVCache.geometry()``: a sharded page-id/page-offset dim
  breaks the pages-are-global invariant that refcounts, the radix prefix
  cache, CoW and disagg handoff all lean on.

:func:`tp_comm_report` emits the static communication estimate for the
tp forward pass: every row-parallel boundary (Megatron column→row pair)
costs one all-reduce of the full activation row, ``2·(tp-1)/tp`` of the
payload over the wire per device for a ring.

Wired into ``python -m paddle_tpu.analysis`` (the ``shard`` pass),
``DecodeEngine`` group-mode init (:func:`lint_group_layout_or_raise`
runs before any param is placed), and ``tools/analysis_gate.py`` in CI.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from paddle_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic
from paddle_tpu.parallel.sharding import (
    MISSING_AXIS,
    NON_DIVISIBLE,
    ShardingRules,
    degraded_dims,
    mesh_axis_sizes,
)

__all__ = [
    "CommBoundary",
    "CommReport",
    "analyze_layout",
    "analyze_model",
    "compare_layouts",
    "eval_param_shapes",
    "lint_group_layout_or_raise",
    "tp_comm_report",
]

# KV page arrays are [L, num_pages, H_kv, page_size, dh]; page ids are
# global across a replica group, so only the head dim may shard
KV_PAGES_DIM = 1
KV_HEAD_DIM = 2
KV_OFFSET_DIM = 3

AxisSizes = Mapping[str, int]
# a layout: a GroupLayout-like object (``.rules`` + ``.optional``) or a
# bare rule table
LayoutLike = Union[ShardingRules, Any]


def _shape_of(v: Any) -> Tuple[int, ...]:
    """Accept ShapeDtypeStructs, arrays, or plain shape tuples."""
    shape = getattr(v, "shape", v)
    return tuple(int(s) for s in shape)


def _dtype_bytes(v: Any, default: int = 4) -> int:
    dtype = getattr(v, "dtype", None)
    if dtype is None:
        return default
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return default


def _rules_of(layout: LayoutLike) -> Tuple[ShardingRules, Tuple[str, ...]]:
    rules = getattr(layout, "rules", layout)
    optional = tuple(getattr(layout, "optional", ()))
    return tuple(rules), optional


def _first_match(name: str, rules: ShardingRules):
    for idx, (pattern, spec) in enumerate(rules):
        if fnmatch.fnmatchcase(name, pattern):
            return idx, pattern, spec
    return None


def _spec_dims(spec, rank: int) -> Tuple[Optional[str], ...]:
    dims = tuple(spec) + (None,) * max(0, rank - len(spec))
    return dims[:rank]


def _effective_spec(
    name: str, shape: Tuple[int, ...], layout: LayoutLike, axis_sizes: AxisSizes
) -> Tuple[Optional[str], ...]:
    """The spec a param actually gets: first-match rule, padded to rank,
    degraded exactly as ``degrade_spec`` would. Replicated on no match."""
    rules, _ = _rules_of(layout)
    hit = _first_match(name, rules)
    if hit is None:
        return (None,) * len(shape)
    _, _, spec = hit
    dims = list(_spec_dims(spec, len(shape)))
    for i, _axis, _reason in degraded_dims(axis_sizes, spec, shape):
        if i < len(dims):
            dims[i] = None
    return tuple(dims)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


def _degrade_cost_bytes(
    shape: Tuple[int, ...], spec, axis_sizes: AxisSizes, dim: int, dtype_bytes: int
) -> int:
    """Extra per-device HBM of replicating ``dim`` instead of sharding it:
    the param's actual per-device bytes (after every degrade) minus what
    they would be had this one dim sharded as asked."""
    total = int(np.prod(shape)) * dtype_bytes if shape else dtype_bytes
    dims = _spec_dims(spec, len(shape))
    dropped = {i for i, _, _ in degraded_dims(axis_sizes, spec, shape)}
    shard_factor = 1
    for i, axis in enumerate(dims):
        if axis is not None and i not in dropped:
            shard_factor *= axis_sizes.get(axis, 1)
    actual = total // max(1, shard_factor)
    n = axis_sizes.get(dims[dim], 1)
    return actual - actual // max(1, n)


# ---------------------------------------------------------------------------
# core pass: one layout over one param tree
# ---------------------------------------------------------------------------


def analyze_layout(
    params: Mapping[str, Any],
    layout: LayoutLike,
    axis_sizes: AxisSizes,
    *,
    kv_page_shape: Optional[Tuple[int, ...]] = None,
    kv_geometry: Optional[Mapping[str, int]] = None,
    where: str = "layout",
) -> List[Diagnostic]:
    """Propagate the layout's PartitionSpecs over a param tree without
    touching devices and report every invariant violation as a
    :class:`Diagnostic`. ``params`` maps name → shape-like (eval_shape
    structs, arrays, or plain tuples); ``axis_sizes`` is the mesh shape
    (``{"tp": 4}``)."""
    rules, optional = _rules_of(layout)
    diags: List[Diagnostic] = []
    matched: set = set()
    for name in sorted(params):
        shape = _shape_of(params[name])
        dtype_bytes = _dtype_bytes(params[name])
        hit = _first_match(name, rules)
        if hit is None:
            continue
        idx, pattern, spec = hit
        matched.add(idx)
        if len(spec) > len(shape):
            diags.append(Diagnostic(
                "shard-rank-mismatch",
                f"rule {pattern!r} names {len(spec)} dims but param {name!r} "
                f"has rank {len(shape)} {shape} — a layout written for a "
                "different parameter shape (placement would raise here)",
                where=name,
            ))
            continue
        for dim, axis, reason in degraded_dims(axis_sizes, spec, shape):
            if reason == MISSING_AXIS:
                diags.append(Diagnostic(
                    "shard-unknown-axis",
                    f"rule {pattern!r} shards dim {dim} of {name!r} over "
                    f"axis {axis!r}, which this mesh "
                    f"({dict(axis_sizes)}) does not have — the rule can "
                    "never shard here and degrades to replicated",
                    severity=WARNING, where=name,
                ))
            else:  # NON_DIVISIBLE: the silent degrade, costed in HBM
                n = axis_sizes[axis]
                cost = _degrade_cost_bytes(shape, spec, axis_sizes, dim,
                                           dtype_bytes)
                diags.append(Diagnostic(
                    "shard-silent-degrade",
                    f"dim {dim} (size {shape[dim]}) of {name!r} is not "
                    f"divisible by mesh axis {axis!r} (size {n}); "
                    "degrade_spec silently replicates it, costing "
                    f"{_fmt_bytes(cost)} extra HBM per device",
                    severity=WARNING, where=name,
                ))
    for idx, (pattern, spec) in enumerate(rules):
        if idx in matched or pattern in optional:
            continue
        diags.append(Diagnostic(
            "shard-dead-rule",
            f"rule {pattern!r} -> {spec} matches no parameter — stale "
            "after a rename, or a layout for a different model family "
            "(mark variant-only families in GroupLayout.optional)",
            where=f"{where}:rule[{idx}]",
        ))
    if kv_page_shape is not None:
        diags.extend(_analyze_kv_pages(layout, kv_page_shape, kv_geometry,
                                       axis_sizes))
    return diags


def _kv_spec_dims(layout: LayoutLike, rank: int) -> Tuple[Optional[str], ...]:
    kv_rule = getattr(layout, "kv_rule", None)
    if kv_rule is not None:
        return _spec_dims(kv_rule, rank)
    tp_axis = getattr(layout, "tp_axis", "tp")
    dims = [None] * rank
    if rank > KV_HEAD_DIM:
        dims[KV_HEAD_DIM] = tp_axis
    return tuple(dims)


def _analyze_kv_pages(
    layout: LayoutLike,
    shape: Tuple[int, ...],
    geometry: Optional[Mapping[str, int]],
    axis_sizes: AxisSizes,
) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    dims = _kv_spec_dims(layout, len(shape))
    if geometry:
        for dim, key in ((KV_PAGES_DIM, "num_pages"),
                         (KV_OFFSET_DIM, "page_size")):
            want = geometry.get(key)
            if want is not None and len(shape) > dim and shape[dim] != want:
                diags.append(Diagnostic(
                    "shard-kv-geometry",
                    f"KV page array dim {dim} is {shape[dim]} but "
                    f"PagedKVCache.geometry()[{key!r}] is {want} — the page "
                    "tables would index pages that do not exist",
                    where="kv_pages",
                ))
    for dim in (KV_PAGES_DIM, KV_OFFSET_DIM):
        if len(dims) > dim and dims[dim] is not None:
            diags.append(Diagnostic(
                "shard-kv-geometry",
                f"KV page spec shards dim {dim} "
                f"({'page ids' if dim == KV_PAGES_DIM else 'page offsets'}) "
                f"over axis {dims[dim]!r}: page ids are global across a "
                "replica group — sharding them breaks refcounts, the radix "
                "prefix cache, CoW and disagg handoff; only the head dim "
                f"({KV_HEAD_DIM}) may shard",
                where="kv_pages",
            ))
    from jax.sharding import PartitionSpec as P

    for dim, axis, reason in degraded_dims(axis_sizes, P(*dims), shape):
        if reason == NON_DIVISIBLE and dim == KV_HEAD_DIM:
            diags.append(Diagnostic(
                "shard-silent-degrade",
                f"KV head count {shape[dim]} is not divisible by axis "
                f"{axis!r} (size {axis_sizes[axis]}); the whole page cache "
                "replicates per device — the tp memory win is silently lost",
                severity=WARNING, where="kv_pages",
            ))
    return diags


# ---------------------------------------------------------------------------
# cross-layout conflicts (training vs serving, tp=2 vs tp=4 rule tables, ...)
# ---------------------------------------------------------------------------


def compare_layouts(
    layouts: Mapping[str, LayoutLike],
    params: Mapping[str, Any],
    axis_sizes: AxisSizes,
) -> List[Diagnostic]:
    """Effective-spec conflicts for the same param across named layouts.
    Any difference means every transition between the two contexts (e.g.
    checkpoint restore from training into serving) re-lays the parameter
    out across the mesh — legitimate sometimes, but never silently."""
    diags: List[Diagnostic] = []
    for name in sorted(params):
        shape = _shape_of(params[name])
        effective = {
            label: _effective_spec(name, shape, layout, axis_sizes)
            for label, layout in layouts.items()
        }
        if len(set(effective.values())) > 1:
            detail = ", ".join(
                f"{label}={spec}" for label, spec in sorted(effective.items()))
            diags.append(Diagnostic(
                "shard-conflict",
                f"param {name!r} gets conflicting effective specs across "
                f"layouts: {detail} — every transition between them is a "
                "full cross-mesh resharding of this parameter",
                where=name,
            ))
    return diags


# ---------------------------------------------------------------------------
# static communication estimate for the tp forward pass
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CommBoundary:
    """One column→row boundary: the all-reduce after a row-parallel
    matmul. ``payload_bytes`` is the full activation row per token;
    ``wire_bytes`` the per-device ring traffic (``2·(tp-1)/tp`` of it)."""

    param: str
    out_features: int
    payload_bytes: int
    wire_bytes: int


@dataclasses.dataclass(frozen=True)
class CommReport:
    """Per-token communication of one tp forward pass, statically derived
    from the rule table: every effective row-parallel 2-d weight is one
    all-reduce boundary."""

    tp_axis: str
    tp: int
    dtype_bytes: int
    boundaries: Tuple[CommBoundary, ...]

    @property
    def total_payload_bytes(self) -> int:
        return sum(b.payload_bytes for b in self.boundaries)

    @property
    def total_wire_bytes(self) -> int:
        return sum(b.wire_bytes for b in self.boundaries)

    def format(self) -> str:
        lines = [
            f"tp comm report: axis {self.tp_axis!r} degree {self.tp}, "
            f"{self.dtype_bytes}B/elem, per token:",
            f"  {'boundary (row-parallel weight)':<44}"
            f"{'payload':>10}{'wire/device':>14}",
        ]
        for b in self.boundaries:
            lines.append(
                f"  {b.param:<44}{_fmt_bytes(b.payload_bytes):>10}"
                f"{_fmt_bytes(b.wire_bytes):>14}")
        lines.append(
            f"  total: {len(self.boundaries)} all-reduce(s), "
            f"{_fmt_bytes(self.total_payload_bytes)} payload, "
            f"{_fmt_bytes(self.total_wire_bytes)} wire/device")
        return "\n".join(lines)


def tp_comm_report(
    params: Mapping[str, Any],
    layout: LayoutLike,
    axis_sizes: AxisSizes,
    *,
    dtype_bytes: int = 4,
) -> CommReport:
    """Estimate the forward-pass all-reduce traffic a layout implies.
    Column-parallel matmuls keep their outputs sharded (no comm); each
    row-parallel weight ``[in, out]`` with the tp axis on dim 0 ends a
    Megatron pair and all-reduces its ``[*, out]`` activation."""
    tp_axis = getattr(layout, "tp_axis", "tp")
    tp = int(axis_sizes.get(tp_axis, 1))
    boundaries: List[CommBoundary] = []
    for name in sorted(params):
        shape = _shape_of(params[name])
        if len(shape) != 2:
            continue
        spec = _effective_spec(name, shape, layout, axis_sizes)
        if spec[0] == tp_axis:
            payload = shape[1] * dtype_bytes
            wire = int(payload * 2 * (tp - 1) / tp) if tp > 1 else 0
            boundaries.append(CommBoundary(name, shape[1], payload, wire))
    return CommReport(tp_axis, tp, dtype_bytes, tuple(boundaries))


# ---------------------------------------------------------------------------
# conveniences: eval_shape param trees, whole-model analysis, engine hook
# ---------------------------------------------------------------------------


def eval_param_shapes(model: str = "transformer_lm", **cfg):
    """``(param_shapes, model_cfg)`` for a registered model via
    ``jax.eval_shape`` over its ``init`` — zero FLOPs, zero device memory,
    exact names/shapes/dtypes."""
    import jax

    from paddle_tpu import models

    spec = models.get_model(model, **cfg)
    rng = np.random.RandomState(0)
    batch = spec.synth_batch(1, rng)
    shapes = jax.eval_shape(
        lambda r, *b: spec.model.init(r, *b).params,
        jax.random.PRNGKey(0), *batch)
    return shapes, dict(spec.extra.get("cfg", {}))


def analyze_model(
    model: str = "transformer_lm",
    *,
    tp: int = 1,
    layout: Optional[LayoutLike] = None,
    page_size: int = 16,
    num_pages: int = 64,
    **cfg,
) -> Tuple[List[Diagnostic], CommReport]:
    """One-call analysis of a registered model under a layout at a given
    tp degree, KV-page checks included — what the CLI ``shard`` pass and
    ``tools/analysis_gate.py`` run."""
    if layout is None:
        from paddle_tpu.serving.shardgroup import default_layout

        layout = default_layout()
    shapes, model_cfg = eval_param_shapes(model, **cfg)
    axis_sizes = {getattr(layout, "tp_axis", "tp"): int(tp)}
    kv_shape = None
    kv_geometry = None
    if model == "transformer_lm":
        from paddle_tpu.models.transformer_lm import paged_cache_shape

        kv_shape = tuple(paged_cache_shape(model_cfg, num_pages, page_size))
        kv_geometry = {"num_pages": num_pages, "page_size": page_size}
    diags = analyze_layout(
        shapes, layout, axis_sizes, kv_page_shape=kv_shape,
        kv_geometry=kv_geometry, where=f"{model}@tp={tp}")
    report = tp_comm_report(shapes, layout, axis_sizes)
    return diags, report


def lint_group_layout_or_raise(
    params: Mapping[str, Any],
    layout: LayoutLike,
    mesh,
    *,
    kv_page_shape: Optional[Tuple[int, ...]] = None,
    kv_geometry: Optional[Mapping[str, int]] = None,
    where: str = "group layout",
) -> List[Diagnostic]:
    """The serving init hook: analyze a layout against the actual params
    about to be placed on a replica group's mesh. Error findings raise
    ``EnforceError`` BEFORE any device_put burns HBM on a bad layout;
    warnings are logged once each. Returns every diagnostic."""
    from paddle_tpu.core import logging as ptlog
    from paddle_tpu.core.enforce import enforce

    diags = analyze_layout(
        params, layout, mesh_axis_sizes(mesh),
        kv_page_shape=kv_page_shape, kv_geometry=kv_geometry, where=where)
    errors = [d for d in diags if d.severity == ERROR]
    for d in diags:
        if d.severity != ERROR:
            ptlog.warn_once(("shard-analysis", where, d.code, d.where),
                            "shard analysis [%s]: %s", d.code, str(d))
    enforce(
        not errors,
        f"{where}: static shard analysis found {len(errors)} error(s) — "
        "refusing to place params on the group:\n"
        + "\n".join(str(d) for d in errors),
    )
    return diags

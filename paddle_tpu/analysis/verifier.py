"""IR verifier for the native serving program.

The reference framework validated graphs before execution — ProgramDesc
checks on load, ``PADDLE_ENFORCE`` inside every OpDesc InferShape, and the
``ir::Graph`` pass infrastructure asserting graph invariants between
passes. The native line IR (``paddle_tpu/native/passes.py`` ←
``native/export.py`` → ``csrc/predictor.cc``) had no equivalent: a buggy
pass produced a program that failed deep inside the C++ interpreter (or
worse, computed garbage). This module is the missing layer:

* **structural checks** — well-formed lines, op arity, known prims/attrs;
* **SSA invariants** — single definition per id, def-before-use, no
  dangling uses, every ``output`` defined;
* **per-prim shape/dtype inference** — re-deriving every op's result shape
  the same way ``csrc/ops.cc`` computes it, so a rewrite that silently
  changes an operand (the classic CSE/remap bug class) is caught at
  verify time with the offending line, not at predict time.

``PassManager.run`` calls :func:`verify_or_raise` after every pass when
verification is enabled (on by default under pytest — the TVM-style
verify-between-passes discipline), and ``native/export.py`` verifies the
final program before writing ``program.txt``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

from paddle_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic, format_diagnostics, has_errors
from paddle_tpu.core.enforce import EnforceError

__all__ = [
    "Diagnostic",
    "VerificationError",
    "verify_text",
    "verify_program",
    "verify_or_raise",
]

# storage dtype tags (csrc/predictor.cc parse_dtype) -> payload bytes/elem
_DTYPE_BYTES = {"f32": 4, "bf16": 2, "i32": 4, "i64": 8, "i8": 1}

_UNARY = {
    "exp", "log", "neg", "abs", "sign", "floor", "rsqrt", "sqrt", "tanh",
    "logistic", "sin", "cos", "erf", "ceil", "expm1", "log1p", "not",
    "is_finite", "round", "round_away",
}
_BINARY = {
    "add", "sub", "mul", "div", "max", "min", "pow", "eq", "lt", "gt", "ge",
    "le", "and", "or", "rem", "atan2", "ne",
}
_IDENTITY = {"copy", "convert_element_type", "stop_gradient"}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_or", "reduce_and"}
_CUMULATIVE = {"cumsum", "cumprod", "cummax", "cummin"}


class VerificationError(EnforceError):
    """The program violates an IR invariant; carries the diagnostics."""

    def __init__(self, message: str, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        detail = format_diagnostics(
            [d for d in self.diagnostics if d.severity == ERROR], limit=20
        )
        super().__init__(f"{message}\n{detail}" if detail else message)


class _Invalid(Exception):
    """Internal: a shape/attr rule failed for one op."""

    def __init__(self, code: str, message: str):
        self.code = code
        self.message = message


@dataclasses.dataclass
class _Val:
    shape: Optional[Tuple[int, ...]]  # None = unknown (upstream error)
    dtype: str
    line_no: int


@dataclasses.dataclass
class _OpRec:
    prim: str
    out: int
    ins: List[int]
    attrs: Dict[str, List[int]]
    fval: Optional[float]
    line_no: int
    raw: str


def _numel(shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def _parse_attrs(token: str) -> Tuple[Dict[str, List[int]], Optional[float], List[str]]:
    """Parse the ``k=v;k=v`` attr token (csv ints; ``fval`` is float).
    Returns (attrs, fval, malformed-chunks)."""
    attrs: Dict[str, List[int]] = {}
    fval: Optional[float] = None
    bad: List[str] = []
    if token == "-":
        return attrs, fval, bad
    for chunk in token.split(";"):
        if "=" not in chunk:
            if chunk:
                bad.append(chunk)
            continue
        key, val = chunk.split("=", 1)
        if key == "fval":
            try:
                fval = float(val)
            except ValueError:
                bad.append(chunk)
            continue
        try:
            attrs[key] = [int(v) for v in val.split(",") if v != ""]
        except ValueError:
            bad.append(chunk)
    return attrs, fval, bad


# ---- per-prim shape rules -------------------------------------------------
# Each rule mirrors the corresponding evaluator in csrc/ops.cc: the verifier
# accepts exactly what the interpreter executes.


def _attr(op: _OpRec, key: str, length: Optional[int] = None) -> List[int]:
    if key not in op.attrs:
        raise _Invalid("missing-attr", f"op '{op.prim}' requires attr '{key}'")
    val = op.attrs[key]
    if length is not None and len(val) != length:
        raise _Invalid(
            "bad-attr",
            f"op '{op.prim}' attr '{key}' must have {length} values, got {len(val)}",
        )
    return val


def _arity(op: _OpRec, lo: int, hi: Optional[int] = None) -> None:
    hi = lo if hi is None else hi
    if not (lo <= len(op.ins) <= hi):
        want = str(lo) if lo == hi else f"{lo}..{hi}"
        raise _Invalid(
            "bad-arity", f"op '{op.prim}' expects {want} inputs, got {len(op.ins)}"
        )


def _broadcast2(op: _OpRec, a: Tuple[int, ...], b: Tuple[int, ...]) -> Tuple[int, ...]:
    """csrc/ops.cc binary_impl: equal shapes, either side numel==1, or equal
    rank with size-1 dims broadcasting. NOT full numpy trailing-dim rules."""
    if a == b:
        return a
    if _numel(b) == 1:
        return a
    if _numel(a) == 1:
        return b
    if len(a) != len(b):
        raise _Invalid(
            "shape-mismatch",
            f"op '{op.prim}' rank mismatch: {a} vs {b} (the native interpreter "
            "broadcasts size-1 dims at equal rank only)",
        )
    out = []
    for da, db in zip(a, b):
        if da != db and da != 1 and db != 1:
            raise _Invalid(
                "shape-mismatch", f"op '{op.prim}' incompatible shapes {a} vs {b}"
            )
        out.append(max(da, db))
    return tuple(out)


def _check_axis(op: _OpRec, axis: int, rank: int, what: str = "axis") -> int:
    if not (0 <= axis < rank):
        raise _Invalid(
            "bad-attr", f"op '{op.prim}' {what} {axis} out of range for rank {rank}"
        )
    return axis


def _infer_shape(op: _OpRec, ins: List[Tuple[int, ...]]) -> Tuple[int, ...]:
    p = op.prim
    if p in _UNARY or p in {"to_bf16", "to_int", "integer_pow"} or p in _IDENTITY:
        _arity(op, 1)
        if p == "integer_pow":
            _attr(op, "y", 1)
        return ins[0]
    if p in _BINARY:
        _arity(op, 2)
        return _broadcast2(op, ins[0], ins[1])
    if p == "clamp":  # lax.clamp(min, x, max): max(x, min) then min(., max)
        _arity(op, 3)
        return _broadcast2(op, _broadcast2(op, ins[1], ins[0]), ins[2])
    if p in ("reshape", "squeeze"):
        _arity(op, 1)
        shape = tuple(_attr(op, "shape"))
        if _numel(shape) != _numel(ins[0]):
            raise _Invalid(
                "shape-mismatch",
                f"op '{p}' cannot reshape {ins[0]} ({_numel(ins[0])} elements) "
                f"to {shape} ({_numel(shape)} elements)",
            )
        return shape
    if p == "transpose":
        _arity(op, 1)
        perm = _attr(op, "perm", len(ins[0]))
        if sorted(perm) != list(range(len(ins[0]))):
            raise _Invalid(
                "bad-attr", f"op 'transpose' perm {perm} is not a permutation "
                f"of rank {len(ins[0])}"
            )
        return tuple(ins[0][d] for d in perm)
    if p == "broadcast_in_dim":
        _arity(op, 1)
        out = tuple(_attr(op, "shape"))
        dims = _attr(op, "dims", len(ins[0]))
        if any(not (0 <= d < len(out)) for d in dims) or list(dims) != sorted(set(dims)):
            raise _Invalid(
                "bad-attr",
                f"op 'broadcast_in_dim' dims {dims} must be strictly increasing "
                f"and < rank {len(out)}",
            )
        for src_d, out_d in enumerate(dims):
            if ins[0][src_d] not in (1, out[out_d]):
                raise _Invalid(
                    "shape-mismatch",
                    f"op 'broadcast_in_dim' input dim {src_d} (={ins[0][src_d]}) "
                    f"does not broadcast to output dim {out_d} (={out[out_d]})",
                )
        return out
    if p in _REDUCE:
        _arity(op, 1)
        axes = _attr(op, "axes")
        if len(set(axes)) != len(axes):
            raise _Invalid("bad-attr", f"op '{p}' repeated axes {axes}")
        for a in axes:
            _check_axis(op, a, len(ins[0]))
        return tuple(d for i, d in enumerate(ins[0]) if i not in set(axes))
    if p in _CUMULATIVE:
        _arity(op, 1)
        _check_axis(op, _attr(op, "axis", 1)[0], len(ins[0]))
        _attr(op, "reverse", 1)
        return ins[0]
    if p in ("argmax", "argmin"):
        _arity(op, 1)
        axis = _check_axis(op, _attr(op, "axis", 1)[0], len(ins[0]))
        return tuple(d for i, d in enumerate(ins[0]) if i != axis)
    if p == "dot_general":
        return _infer_dot_general(op, ins)
    if p == "conv":
        return _infer_conv(op, ins)
    if p in ("reduce_window_max", "reduce_window_sum"):
        return _infer_reduce_window(op, ins)
    if p == "slice":
        _arity(op, 1)
        rank = len(ins[0])
        start = _attr(op, "start", rank)
        limit = _attr(op, "limit", rank)
        stride = _attr(op, "stride", rank)
        out = []
        for d, (s, l, st, n) in enumerate(zip(start, limit, stride, ins[0])):
            if st <= 0 or not (0 <= s <= l <= n):
                raise _Invalid(
                    "bad-attr",
                    f"op 'slice' dim {d}: start={s} limit={l} stride={st} "
                    f"invalid for size {n}",
                )
            out.append(-(-(l - s) // st))
        return tuple(out)
    if p == "pad":
        _arity(op, 1, 2)
        if len(op.ins) == 1 and op.fval is None:
            raise _Invalid("missing-attr", "op 'pad' needs a value operand or fval=")
        if len(op.ins) == 2 and _numel(ins[1]) != 1:
            raise _Invalid(
                "shape-mismatch", f"op 'pad' value operand must be scalar, got {ins[1]}"
            )
        rank = len(ins[0])
        lo = _attr(op, "lo", rank)
        hi = _attr(op, "hi", rank)
        inter = _attr(op, "interior", rank)
        out = []
        for d, (l, h, i, n) in enumerate(zip(lo, hi, inter, ins[0])):
            if i < 0:
                raise _Invalid("bad-attr", f"op 'pad' negative interior at dim {d}")
            size = n + l + h + max(n - 1, 0) * i
            if size < 0:
                raise _Invalid(
                    "shape-mismatch", f"op 'pad' dim {d} pads to negative size {size}"
                )
            out.append(size)
        return tuple(out)
    if p == "select_n":
        _arity(op, 2, 64)
        cases = ins[1:]
        if any(c != cases[0] for c in cases):
            raise _Invalid(
                "shape-mismatch", f"op 'select_n' case shapes differ: {ins[1:]}"
            )
        if _numel(ins[0]) not in (1, _numel(cases[0])):
            raise _Invalid(
                "shape-mismatch",
                f"op 'select_n' predicate shape {ins[0]} matches neither a "
                f"scalar nor the case shape {cases[0]}",
            )
        return cases[0]
    if p == "gather":
        return _infer_gather(op, ins)
    if p == "concatenate":
        _arity(op, 1, 1 << 30)
        dim = _attr(op, "dim", 1)[0]
        rank = len(ins[0])
        _check_axis(op, dim, rank, "dim")
        for i, s in enumerate(ins[1:], start=1):
            if len(s) != rank or any(
                a != b for d, (a, b) in enumerate(zip(ins[0], s)) if d != dim
            ):
                raise _Invalid(
                    "shape-mismatch",
                    f"op 'concatenate' operand {i} shape {s} incompatible with "
                    f"{ins[0]} along dim {dim}",
                )
        return tuple(
            sum(s[d] for s in ins) if d == dim else ins[0][d] for d in range(rank)
        )
    if p == "rev":
        _arity(op, 1)
        for d in _attr(op, "dims"):
            _check_axis(op, d, len(ins[0]), "dim")
        return ins[0]
    if p == "dynamic_slice":
        rank = len(ins[0])
        _arity(op, 1 + rank)
        sizes = _attr(op, "sizes", rank)
        for d, (sz, n) in enumerate(zip(sizes, ins[0])):
            if not (0 < sz <= n):
                raise _Invalid(
                    "bad-attr", f"op 'dynamic_slice' size {sz} invalid for dim "
                    f"{d} of {ins[0]}"
                )
        for i, s in enumerate(ins[1:], start=1):
            if _numel(s) != 1:
                raise _Invalid(
                    "shape-mismatch",
                    f"op 'dynamic_slice' start operand {i} must be scalar, got {s}",
                )
        return tuple(sizes)
    if p == "dynamic_update_slice":
        rank = len(ins[0])
        _arity(op, 2 + rank)
        if len(ins[1]) != rank or any(u > n for u, n in zip(ins[1], ins[0])):
            raise _Invalid(
                "shape-mismatch",
                f"op 'dynamic_update_slice' update {ins[1]} does not fit in "
                f"operand {ins[0]}",
            )
        for i, s in enumerate(ins[2:], start=2):
            if _numel(s) != 1:
                raise _Invalid(
                    "shape-mismatch",
                    f"op 'dynamic_update_slice' start operand {i} must be "
                    f"scalar, got {s}",
                )
        return ins[0]
    raise _Invalid(
        "unknown-prim",
        f"primitive '{p}' is not in the native interpreter's op set "
        "(csrc/predictor.cc run_instr)",
    )


def _infer_dot_general(op: _OpRec, ins: List[Tuple[int, ...]]) -> Tuple[int, ...]:
    _arity(op, 2)
    lhs, rhs = ins
    lc, rc = _attr(op, "lc"), _attr(op, "rc")
    lb, rb = _attr(op, "lb"), _attr(op, "rb")
    if len(lc) != len(rc) or len(lb) != len(rb):
        raise _Invalid(
            "bad-attr",
            f"op 'dot_general' contraction/batch dim counts differ: "
            f"lc={lc} rc={rc} lb={lb} rb={rb}",
        )
    for dims, shape, what in ((lc, lhs, "lc"), (rc, rhs, "rc"), (lb, lhs, "lb"), (rb, rhs, "rb")):
        for d in dims:
            _check_axis(op, d, len(shape), what)
    if set(lb) & set(lc) or set(rb) & set(rc):
        raise _Invalid("bad-attr", "op 'dot_general' batch and contraction dims overlap")
    for dl, dr in zip(lc, rc):
        if lhs[dl] != rhs[dr]:
            raise _Invalid(
                "shape-mismatch",
                f"op 'dot_general' contraction size mismatch: lhs dim {dl} "
                f"(={lhs[dl]}) vs rhs dim {dr} (={rhs[dr]})",
            )
    for dl, dr in zip(lb, rb):
        if lhs[dl] != rhs[dr]:
            raise _Invalid(
                "shape-mismatch",
                f"op 'dot_general' batch size mismatch: lhs dim {dl} "
                f"(={lhs[dl]}) vs rhs dim {dr} (={rhs[dr]})",
            )
    lhs_free = [d for d in range(len(lhs)) if d not in set(lc) | set(lb)]
    rhs_free = [d for d in range(len(rhs)) if d not in set(rc) | set(rb)]
    return (
        tuple(lhs[d] for d in lb)
        + tuple(lhs[d] for d in lhs_free)
        + tuple(rhs[d] for d in rhs_free)
    )


def _infer_conv(op: _OpRec, ins: List[Tuple[int, ...]]) -> Tuple[int, ...]:
    # NHWC x HWIO (export canonicalizes layouts); optional fused addend
    _arity(op, 2, 3)
    x, w = ins[0], ins[1]
    if len(x) != 4 or len(w) != 4:
        raise _Invalid(
            "shape-mismatch", f"op 'conv' wants rank-4 NHWC x HWIO, got {x} x {w}"
        )
    strides = _attr(op, "strides", 2)
    pad_lo = _attr(op, "pad_lo", 2)
    pad_hi = _attr(op, "pad_hi", 2)
    groups = _attr(op, "groups", 1)[0]
    n, h, wid, c = x
    kh, kw, ci, co = w
    if groups < 1 or ci * groups != c or co % groups:
        raise _Invalid(
            "shape-mismatch",
            f"op 'conv' channel mismatch: input C={c}, filter I={ci}, O={co}, "
            f"groups={groups}",
        )
    out_sp = []
    for d, (k, s, pl, ph, size) in enumerate(
        zip((kh, kw), strides, pad_lo, pad_hi, (h, wid))
    ):
        if s <= 0 or size + pl + ph < k:
            raise _Invalid(
                "shape-mismatch",
                f"op 'conv' spatial dim {d}: size {size} + pads ({pl},{ph}) "
                f"< window {k} (stride {s})",
            )
        out_sp.append((size + pl + ph - k) // s + 1)
    out = (n, out_sp[0], out_sp[1], co)
    if len(ins) == 3:  # fused residual addend (fuse-conv-epilogue)
        if _broadcast2(op, out, ins[2]) != out:
            raise _Invalid(
                "shape-mismatch",
                f"op 'conv' fused addend shape {ins[2]} does not broadcast "
                f"into conv output {out}",
            )
    return out


def _infer_reduce_window(op: _OpRec, ins: List[Tuple[int, ...]]) -> Tuple[int, ...]:
    _arity(op, 1)
    x = ins[0]
    if len(x) != 4:
        raise _Invalid("shape-mismatch", f"op '{op.prim}' wants rank-4 NHWC, got {x}")
    window = _attr(op, "window", 4)
    strides = _attr(op, "strides", 4)
    pad_lo = _attr(op, "pad_lo", 4)
    pad_hi = _attr(op, "pad_hi", 4)
    out = []
    for d, (k, s, pl, ph, size) in enumerate(zip(window, strides, pad_lo, pad_hi, x)):
        if s <= 0 or k <= 0 or size + pl + ph < k:
            raise _Invalid(
                "shape-mismatch",
                f"op '{op.prim}' dim {d}: size {size} + pads ({pl},{ph}) < "
                f"window {k} (stride {s})",
            )
        out.append((size + pl + ph - k) // s + 1)
    return tuple(out)


def _infer_gather(op: _OpRec, ins: List[Tuple[int, ...]]) -> Tuple[int, ...]:
    # XLA gather shape rule over the attrs the exporter emits
    _arity(op, 2)
    operand, indices = ins
    offset_dims = _attr(op, "offset_dims")
    collapsed = _attr(op, "collapsed_dims")
    start_map = _attr(op, "start_index_map")
    slice_sizes = _attr(op, "slice_sizes", len(operand))
    _attr(op, "fill_oob", 1)
    if not indices:
        raise _Invalid("shape-mismatch", "op 'gather' indices must have rank >= 1")
    if indices[-1] != len(start_map):
        raise _Invalid(
            "shape-mismatch",
            f"op 'gather' trailing index dim {indices[-1]} != "
            f"len(start_index_map) {len(start_map)}",
        )
    for d in collapsed:
        _check_axis(op, d, len(operand), "collapsed dim")
        if slice_sizes[d] != 1:
            raise _Invalid(
                "bad-attr", f"op 'gather' collapsed dim {d} has slice size "
                f"{slice_sizes[d]} != 1"
            )
    for d in start_map:
        _check_axis(op, d, len(operand), "start_index_map dim")
    for d, (sz, n) in enumerate(zip(slice_sizes, operand)):
        if not (0 <= sz <= n):
            raise _Invalid(
                "bad-attr", f"op 'gather' slice size {sz} invalid for operand "
                f"dim {d} (={n})"
            )
    batch = list(indices[:-1])
    offsets = [slice_sizes[d] for d in range(len(operand)) if d not in set(collapsed)]
    out_rank = len(batch) + len(offset_dims)
    if len(offsets) != len(offset_dims):
        raise _Invalid(
            "bad-attr",
            f"op 'gather' offset_dims {offset_dims} inconsistent with "
            f"{len(offsets)} non-collapsed slice dims",
        )
    out: List[Optional[int]] = [None] * out_rank
    for pos, d in enumerate(offset_dims):
        if not (0 <= d < out_rank) or out[d] is not None:
            raise _Invalid("bad-attr", f"op 'gather' bad offset_dims {offset_dims}")
        out[d] = offsets[pos]
    it = iter(batch)
    for d in range(out_rank):
        if out[d] is None:
            out[d] = next(it)
    return tuple(out)  # type: ignore[arg-type]


# ---- the verifier ---------------------------------------------------------


def verify_text(text: str, weights: bytes = b"") -> List[Diagnostic]:
    """Verify a serialized native program. Returns diagnostics (empty =
    clean). Never raises on malformed input — every problem becomes a
    structured :class:`Diagnostic` pointing at the offending line."""
    diags: List[Diagnostic] = []
    lines = text.splitlines()

    def diag(code, msg, line_no, raw="", severity=ERROR):
        diags.append(Diagnostic(code, msg, severity=severity,
                                where=f"program:{line_no}", source=raw))

    # -- line-level parse (tolerant: records what it can, reports the rest)
    records: List[Tuple[int, str, object]] = []  # (line_no, kind, payload)
    header_seen = False
    for ln, raw in enumerate(lines, start=1):
        s = raw.strip()
        if not s:
            continue
        if s.startswith("#"):
            if not header_seen:
                header_seen = True
                if "native program" not in s:
                    diag("unknown-header", f"unrecognized header {s!r}", ln, raw,
                         severity=WARNING)
            continue
        parts = s.split()
        kind = parts[0]
        try:
            if kind == "input":
                vid, nd = int(parts[1]), int(parts[2])
                dims = [int(d) for d in parts[3:3 + nd]]
                if len(dims) != nd or len(parts) > 3 + nd:
                    raise ValueError(f"input line declares {nd} dims")
                records.append((ln, "input", (vid, tuple(dims))))
            elif kind == "const":
                vid, off, nd = int(parts[1]), int(parts[2]), int(parts[3])
                dims = [int(d) for d in parts[4:4 + nd]]
                if len(dims) != nd:
                    raise ValueError(f"const line declares {nd} dims")
                rest = parts[4 + nd:]
                if len(rest) > 1:
                    raise ValueError("trailing tokens after dtype tag")
                dtag = rest[0] if rest else "f32"  # v1 lines have no tag
                records.append((ln, "const", (vid, off, tuple(dims), dtag)))
            elif kind == "op":
                prim, out, nin = parts[1], int(parts[2]), int(parts[3])
                ids = parts[4:4 + nin]
                if len(ids) != nin:
                    raise ValueError(
                        f"op declares {nin} inputs but carries {len(ids)}"
                    )
                if len(parts) != 5 + nin:
                    raise ValueError(
                        "op line must end with exactly one attrs token"
                    )
                attrs, fval, bad = _parse_attrs(parts[4 + nin])
                for chunk in bad:
                    diag("bad-attr", f"malformed attr chunk {chunk!r}", ln, raw)
                records.append(
                    (ln, "op",
                     _OpRec(prim, out, [int(i) for i in ids], attrs, fval, ln, raw))
                )
            elif kind == "output":
                if len(parts) != 2:
                    raise ValueError("output line must be 'output <id>'")
                records.append((ln, "output", int(parts[1])))
            else:
                raise ValueError(f"unknown line kind {kind!r}")
        except (ValueError, IndexError) as e:
            diag("malformed-line", str(e), ln, raw)

    # -- SSA + shape/dtype inference in one ordered walk
    env: Dict[int, _Val] = {}
    defined_at: Dict[int, int] = {}
    all_defs = {
        payload[0] if kind in ("input", "const") else payload.out: ln
        for ln, kind, payload in records
        if kind in ("input", "const", "op")
    }
    n_outputs = 0

    def define(vid: int, val: _Val, ln: int, raw: str) -> None:
        if vid in defined_at:
            diag("redefined",
                 f"id {vid} already defined at program:{defined_at[vid]} "
                 "(single-definition SSA violated)", ln, raw)
            return
        defined_at[vid] = ln
        env[vid] = val

    def resolve(vid: int, ln: int, raw: str, what: str) -> Optional[_Val]:
        if vid in env:
            return env[vid]
        if vid in all_defs:
            diag("use-before-def",
                 f"{what} uses id {vid} before its definition at "
                 f"program:{all_defs[vid]}", ln, raw)
        else:
            diag("undefined-use", f"{what} uses id {vid}, which is never defined",
                 ln, raw)
        return None

    for ln, kind, payload in records:
        if kind == "input":
            vid, shape = payload
            define(vid, _Val(shape, "f32", ln), ln, lines[ln - 1])
        elif kind == "const":
            vid, off, shape, dtag = payload
            if dtag not in _DTYPE_BYTES:
                diag("bad-dtype",
                     f"const id {vid} has storage dtype {dtag!r}; the native "
                     f"runtime supports {sorted(_DTYPE_BYTES)}", ln, lines[ln - 1])
                define(vid, _Val(shape, "f32", ln), ln, lines[ln - 1])
                continue
            if weights:
                need = off + _numel(shape) * _DTYPE_BYTES[dtag]
                if off < 0 or need > len(weights):
                    diag("const-out-of-range",
                         f"const id {vid} reads bytes [{off}, {need}) but "
                         f"weights.bin holds {len(weights)}", ln, lines[ln - 1])
            define(vid, _Val(shape, dtag, ln), ln, lines[ln - 1])
        elif kind == "op":
            op: _OpRec = payload
            in_vals = [resolve(i, ln, op.raw, f"op '{op.prim}'") for i in op.ins]
            if op.out in op.ins:
                diag("self-reference", f"op '{op.prim}' result id {op.out} is "
                     "also one of its inputs", ln, op.raw)
            shape: Optional[Tuple[int, ...]] = None
            if all(v is not None and v.shape is not None for v in in_vals):
                try:
                    shape = _infer_shape(op, [v.shape for v in in_vals])  # type: ignore[union-attr]
                except _Invalid as e:
                    diag(e.code, e.message, ln, op.raw)
            dtype = "bf16" if op.prim == "to_bf16" else (
                "i32" if op.prim == "to_int" else "f32")
            define(op.out, _Val(shape, dtype, ln), ln, op.raw)
        else:  # output
            n_outputs += 1
            resolve(payload, ln, lines[ln - 1], "output")

    if n_outputs == 0:
        diags.append(Diagnostic(
            "no-outputs", "program has no output lines; it computes nothing",
            where="program"))
    return diags


def verify_program(prog) -> List[Diagnostic]:
    """Verify a parsed :class:`paddle_tpu.native.passes.Program`."""
    return verify_text(prog.serialize(), weights=prog.weights)


def verify_or_raise(prog_or_text: Union[str, object], weights: bytes = b"",
                    where: str = "") -> None:
    """Raise :class:`VerificationError` when the program has error-severity
    diagnostics (warnings are tolerated)."""
    if isinstance(prog_or_text, str):
        diags = verify_text(prog_or_text, weights=weights)
    else:
        diags = verify_program(prog_or_text)
    if has_errors(diags):
        ctx = f" ({where})" if where else ""
        raise VerificationError(
            f"native program failed IR verification{ctx}", diags
        )

"""Retrace lint: AST checks for the compile-once discipline.

The serving stack's throughput rests on one invariant: the hot jitted
programs (``paged_decode_step``, the train step, the handoff gather)
compile ONCE and are reused forever (``decode_step_cache_size() == 1``
is an acceptance gate). The killers are all the same textual shape — a
jitted/pjitted function whose closure or arguments capture a
Python-dynamic value, so a "constant" silently freezes at trace time or
every new value triggers a fresh trace. This pass catches them at review
time. Rules:

* ``retrace-config-read`` — ``config.flags()`` / ``os.getenv`` /
  ``os.environ[...]`` inside traced code: the read runs once at trace
  time and the program bakes that value in forever (flipping the flag at
  runtime silently does nothing);
* ``retrace-dynamic-len`` — ``len()`` of a closure/attribute capture
  inside traced code (``len()`` of a traced *argument* is shape-static
  and fine): the length freezes at trace time, and when the captured
  list grows the program is silently wrong — or, hashed as a static, a
  new length means a full retrace per size;
* ``retrace-jit-in-loop`` — a ``jax.jit``/``pjit`` call lexically inside
  a ``for``/``while`` body: a fresh wrapper per iteration has an empty
  executable cache, so every iteration recompiles (the executor's
  LRU-eviction comment documents the same trap for fresh closures);
* ``retrace-dict-order`` — ``in_shardings``/``out_shardings``/
  ``donate_argnums``/``static_argnums`` built from ``.keys()`` /
  ``.values()`` / ``.items()`` without ``sorted(...)``: two processes
  (or two runs) disagreeing on insertion order donate or shard
  *different arguments* — wrap the iteration in ``sorted``;
* ``retrace-missing-static`` — a directly ``@jax.jit``-decorated
  function branching on a bare parameter (``if flag:`` / ``while n:`` /
  ``range(n)``) that ``static_argnums``/``static_argnames`` does not
  cover: a tracer cannot take a Python branch — mark it static (and know
  each distinct value compiles its own program). ``is``/``is not``
  comparisons are exempt (``if rng is not None`` is trace-safe).

Traced code means: a function decorated with ``jax.jit``/``pjit`` (bare,
called, or via ``functools.partial(jax.jit, ...)``), or a function whose
NAME is wrapped by a ``jax.jit``/``pjit`` call anywhere in the same
module (including through ``functools.partial`` / ``jax.grad`` /
``jax.vmap`` / ``jax.checkpoint``), plus everything lexically nested
inside one. Cross-module wrapping is invisible to a per-file AST pass —
the usual precision/recall trade (the concurrency lint documents the
same one); the runtime ``decode_step_cache_size`` gate has no such blind
spot.

Wired into ``python -m paddle_tpu.analysis`` (the ``retrace`` pass) and
the whole-tree-clean test in ``tests/test_retrace_lint.py``. Suppress a
finding with ``# lint: allow`` on the offending line.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Set

from paddle_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic
from paddle_tpu.analysis.source_lint import _dotted, default_roots

__all__ = ["lint_retrace", "lint_file", "default_roots"]

_SUPPRESS = "# lint: allow"

# call chains that wrap a function for tracing (last dotted segment)
_JIT_NAMES = ("jit", "pjit")
# transform wrappers to unwrap when hunting for the jitted function name:
# jax.jit(functools.partial(step, ...)) / jax.jit(jax.grad(loss))
_UNWRAP_NAMES = ("partial", "grad", "value_and_grad", "vmap", "checkpoint",
                 "remat")
# jit kwargs whose value must not depend on dict iteration order
_ORDER_KWARGS = ("in_shardings", "out_shardings", "donate_argnums",
                 "donate_argnames", "static_argnums", "static_argnames")
# trace-frozen environment reads
_ENV_READS = ("os.getenv", "os.environ.get")


def _is_jit_chain(node: ast.AST) -> bool:
    chain = _dotted(node)
    return bool(chain) and chain.rsplit(".", 1)[-1] in _JIT_NAMES


def _wrapped_name(node: ast.AST) -> Optional[str]:
    """The function NAME a jit target ultimately wraps: unwraps nested
    partial/grad/vmap/... calls down to a bare Name."""
    while isinstance(node, ast.Call):
        chain = _dotted(node.func) or ""
        if chain.rsplit(".", 1)[-1] not in _UNWRAP_NAMES:
            return None
        if not node.args:
            return None
        node = node.args[0]
    return node.id if isinstance(node, ast.Name) else None


def _jit_decoration(node) -> Optional[ast.Call]:
    """If the def is jit-decorated, the decorator Call (or a synthetic
    marker for the bare ``@jax.jit`` form); else None."""
    for dec in node.decorator_list:
        if _is_jit_chain(dec):
            return ast.Call(func=dec, args=[], keywords=[])  # bare @jax.jit
        if isinstance(dec, ast.Call):
            if _is_jit_chain(dec.func):
                return dec
            # @functools.partial(jax.jit, static_argnums=...)
            chain = _dotted(dec.func) or ""
            if chain.rsplit(".", 1)[-1] == "partial" and dec.args \
                    and _is_jit_chain(dec.args[0]):
                return dec
    return None


def _static_params(node, dec: ast.Call) -> Set[str]:
    """Parameter names the decorator marks static (literal
    static_argnums/static_argnames only; dynamic expressions disable the
    missing-static check rather than guess)."""
    params = [a.arg for a in node.args.posonlyargs + node.args.args] \
        if hasattr(node.args, "posonlyargs") else [a.arg for a in node.args.args]
    static: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    static.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if 0 <= n.value < len(params):
                        static.add(params[n.value])
    return static


class _JitIndex(ast.NodeVisitor):
    """Pre-pass: names of functions wrapped by a jit/pjit call anywhere
    in the module."""

    def __init__(self) -> None:
        self.jitted: Set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_chain(node.func) and node.args:
            target = node.args[0]
            name = target.id if isinstance(target, ast.Name) \
                else _wrapped_name(target)
            if name:
                self.jitted.add(name)
        self.generic_visit(node)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: List[str], jitted: Set[str]):
        self.path = path
        self.lines = source_lines
        self.jitted = jitted
        self.diags: List[Diagnostic] = []
        self._loop_depth = 0
        self._traced = False          # inside a jit-wrapped function body
        self._fn_locals: Set[str] = set()   # params + assigned names
        self._static: Set[str] = set()      # decorator-declared static params
        self._params: Set[str] = set()

    def _diag(self, code: str, message: str, node: ast.AST,
              severity: str = ERROR) -> None:
        line_no = getattr(node, "lineno", 0)
        src = self.lines[line_no - 1] if 0 < line_no <= len(self.lines) else ""
        if _SUPPRESS in src:
            return
        self.diags.append(Diagnostic(
            code, message, severity=severity,
            where=f"{self.path}:{line_no}", source=src,
        ))

    # -- lexical context ---------------------------------------------------

    def visit_For(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = visit_For
    visit_AsyncFor = visit_For

    def _collect_locals(self, node) -> Set[str]:
        names: Set[str] = set()
        a = node.args
        for arg in (getattr(a, "posonlyargs", []) + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            names.add(arg.arg)
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                names.add(n.id)
        return names

    def _visit_fn(self, node) -> None:
        dec = _jit_decoration(node)
        traced = self._traced or dec is not None \
            or getattr(node, "name", None) in self.jitted
        saved = (self._traced, self._fn_locals, self._static, self._params,
                 self._loop_depth)
        # a def's body runs when CALLED, not where it appears: loop depth
        # does not propagate in (the autotune make_fn pattern is fine)
        self._loop_depth = 0
        if traced and not self._traced:
            self._fn_locals = self._collect_locals(node)
            self._params = {a.arg for a in getattr(node.args, "posonlyargs", [])
                            + node.args.args + node.args.kwonlyargs}
            self._static = _static_params(node, dec) if dec is not None else set()
            self._traced = True
            if dec is not None:
                self._check_python_branches(node)
        elif traced:
            # nested def inside traced code: locals accumulate
            self._fn_locals = self._fn_locals | self._collect_locals(node)
        self.generic_visit(node)
        (self._traced, self._fn_locals, self._static, self._params,
         self._loop_depth) = saved

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node) -> None:
        saved = self._loop_depth
        self._loop_depth = 0
        self.generic_visit(node)
        self._loop_depth = saved

    # -- rule: missing static_argnums (decorated defs only) ----------------

    def _check_python_branches(self, node) -> None:
        dynamic = self._params - self._static
        for n in ast.walk(node):
            test = None
            if isinstance(n, (ast.If, ast.While)):
                test = n.test
            elif isinstance(n, ast.Call) and _dotted(n.func) == "range" \
                    and n.args:
                test = n.args[0]
            if test is None:
                continue
            for name in self._bare_branch_names(test):
                if name in dynamic:
                    self._diag(
                        "retrace-missing-static",
                        f"parameter {name!r} takes a Python branch inside a "
                        "jitted function but is not in static_argnums/"
                        "static_argnames — a tracer cannot branch; mark it "
                        "static (each distinct value compiles its own "
                        "program) or lift the branch out of the jit",
                        n if hasattr(n, "lineno") else node,
                        severity=WARNING,
                    )

    @staticmethod
    def _bare_branch_names(test: ast.AST) -> Set[str]:
        """Bare parameter Names a Python branch would force to a bool —
        `x`, `not x`, `x and y`, `x == c`. Identity tests (`x is None`)
        and attribute/subscript reads (`x.ndim == 2`, shape-static) are
        trace-safe and exempt."""
        out: Set[str] = set()
        stack = [test]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name):
                out.add(n.id)
            elif isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
                stack.append(n.operand)
            elif isinstance(n, ast.BoolOp):
                stack.extend(n.values)
            elif isinstance(n, ast.Compare):
                if all(not isinstance(op, (ast.Is, ast.IsNot))
                       for op in n.ops):
                    stack.append(n.left)
                    stack.extend(n.comparators)
        return out

    # -- rules on calls ----------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_chain(node.func):
            if self._loop_depth:
                self._diag(
                    "retrace-jit-in-loop",
                    "jax.jit/pjit called inside a loop body: each iteration "
                    "builds a fresh wrapper with an empty executable cache, "
                    "so every call recompiles — hoist the jit out of the "
                    "loop (or cache it keyed on the static config)",
                    node,
                )
            self._check_order_kwargs(node)
        if self._traced:
            self._check_traced_call(node)
        self.generic_visit(node)

    def _check_order_kwargs(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg not in _ORDER_KWARGS:
                continue
            has_iter = any(
                isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("keys", "values", "items")
                for n in ast.walk(kw.value))
            has_sorted = any(
                isinstance(n, ast.Call) and _dotted(n.func) == "sorted"
                for n in ast.walk(kw.value))
            if has_iter and not has_sorted:
                self._diag(
                    "retrace-dict-order",
                    f"{kw.arg}= built from dict .keys()/.values()/.items() "
                    "without sorted(): insertion order decides which "
                    "arguments are donated/sharded, and two processes (or a "
                    "code motion) that disagree silently donate DIFFERENT "
                    "buffers — iterate in sorted() order",
                    node,
                )

    def _check_traced_call(self, node: ast.Call) -> None:
        chain = _dotted(node.func) or ""
        last = chain.rsplit(".", 1)[-1] if chain else ""
        if chain in _ENV_READS or last == "flags":
            self._diag(
                "retrace-config-read",
                f"{chain or last}() inside traced code is read ONCE at "
                "trace time and baked into the compiled program — flipping "
                "it at runtime silently does nothing; read the flag outside "
                "the jit and pass it in (static arg or closure rebuilt on "
                "change)",
                node,
            )
        elif chain == "len" and node.args:
            target = node.args[0]
            capture = None
            if isinstance(target, ast.Name) \
                    and target.id not in self._fn_locals:
                capture = target.id
            elif isinstance(target, ast.Attribute):
                capture = _dotted(target) or target.attr
            if capture is not None:
                self._diag(
                    "retrace-dynamic-len",
                    f"len({capture}) inside traced code measures a "
                    "closure/attribute capture: the length freezes at trace "
                    "time, and when the captured container changes the "
                    "program is silently stale (or retraces per size) — "
                    "pass the data in as a traced argument",
                    node,
                    severity=WARNING,
                )

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self._traced and _dotted(node.value) == "os.environ":
            self._diag(
                "retrace-config-read",
                "os.environ[...] inside traced code is frozen at trace "
                "time — read it outside the jit and pass it in",
                node,
            )
        self.generic_visit(node)


def lint_file(path: str, text: Optional[str] = None) -> List[Diagnostic]:
    """Retrace-lint one Python file."""
    if text is None:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [Diagnostic("syntax-error", str(e),
                           where=f"{path}:{e.lineno or 0}")]
    index = _JitIndex()
    index.visit(tree)
    linter = _Linter(path, text.splitlines(), index.jitted)
    linter.visit(tree)
    return linter.diags


def lint_retrace(paths: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint a set of files/directories (default: the paddle_tpu package)."""
    targets: List[str] = []
    for p in (list(paths) if paths else default_roots()):
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                targets.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        else:
            targets.append(p)
    diags: List[Diagnostic] = []
    for path in targets:
        diags.extend(lint_file(path))
    return diags

"""CLI: ``python -m paddle_tpu.analysis``.

One aggregated exit code over a registry of passes:

* ``source`` — repo-invariant AST lint (:mod:`.source_lint`);
* ``concurrency`` — locking-discipline AST lint (:mod:`.concurrency_lint`);
* ``retrace`` — compile-once retrace lint (:mod:`.retrace_lint`);
* ``shard`` — static sharding-layout analysis of the shipped
  ``default_layout()`` over ``transformer_lm`` at tp ∈ {1, 2, 4}
  (:mod:`.shard_analysis`; needs jax, so it is skipped when explicit
  paths are given — it analyzes the model, not files).

``--only PASS`` (repeatable) restricts the run; ``--verify-program DIR``
additionally verifies an exported native program directory
(``program.txt`` + ``weights.bin``). Exit status 1 when ANY selected
pass produced an error-severity diagnostic — one aggregated gate, not
per-pass ad-hoc codes.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable, Dict, List, Optional, Sequence

from paddle_tpu.analysis.diagnostics import Diagnostic, format_diagnostics, has_errors

_SHARD_TPS = (1, 2, 4)


def _run_source(paths: Optional[Sequence[str]]) -> List[Diagnostic]:
    from paddle_tpu.analysis.source_lint import lint_source

    return list(lint_source(paths or None))


def _run_concurrency(paths: Optional[Sequence[str]]) -> List[Diagnostic]:
    from paddle_tpu.analysis.concurrency_lint import lint_concurrency

    return list(lint_concurrency(paths or None))


def _run_retrace(paths: Optional[Sequence[str]]) -> List[Diagnostic]:
    from paddle_tpu.analysis.retrace_lint import lint_retrace

    return list(lint_retrace(paths or None))


def _run_shard(paths: Optional[Sequence[str]]) -> List[Diagnostic]:
    # model-based, not path-based: analyze the shipped default layout at
    # the tp degrees the serving stack actually runs
    from paddle_tpu.analysis.shard_analysis import analyze_model

    diags: List[Diagnostic] = []
    for tp in _SHARD_TPS:
        found, _report = analyze_model(tp=tp)
        diags.extend(found)
    return diags


# name -> (runner, path_based). Path-based passes lint the given files;
# the shard pass analyzes the model and only runs on whole-repo checks.
PASSES: Dict[str, tuple] = {
    "source": (_run_source, True),
    "concurrency": (_run_concurrency, True),
    "retrace": (_run_retrace, True),
    "shard": (_run_shard, False),
}


def _verify_program_dir(path: str) -> List[Diagnostic]:
    from paddle_tpu.analysis.verifier import verify_text

    prog_path = os.path.join(path, "program.txt") if os.path.isdir(path) else path
    with open(prog_path, "r", encoding="utf-8") as f:
        text = f.read()
    weights = b""
    wpath = os.path.join(os.path.dirname(prog_path), "weights.bin")
    if os.path.exists(wpath):
        with open(wpath, "rb") as f:
            weights = f.read()
    return verify_text(text, weights=weights)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="paddle_tpu static analysis: "
        + ", ".join(PASSES) + " + program verifier",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the paddle_tpu package)",
    )
    ap.add_argument(
        "--only", action="append", choices=sorted(PASSES), default=None,
        metavar="PASS",
        help="run only this pass (repeatable): " + ", ".join(sorted(PASSES)),
    )
    ap.add_argument(
        "--verify-program", metavar="DIR", default=None,
        help="also verify an exported native program (directory containing "
        "program.txt, or the program.txt path itself)",
    )
    ap.add_argument(
        "--no-source-lint", action="store_true",
        help="skip all lint passes (e.g. with --verify-program alone)",
    )
    args = ap.parse_args(argv)

    selected = list(args.only) if args.only else list(PASSES)
    if args.no_source_lint and not args.only:
        selected = []

    diags: List[Diagnostic] = []
    by_pass: Dict[str, int] = {}
    for name in selected:
        runner, path_based = PASSES[name]
        if not path_based and args.paths and not args.only:
            continue  # model-based pass on a file-list invocation
        found = runner(args.paths or None)
        by_pass[name] = len(found)
        diags.extend(found)
    if args.verify_program:
        found = _verify_program_dir(args.verify_program)
        by_pass["verify-program"] = len(found)
        diags.extend(found)

    if diags:
        print(format_diagnostics(diags))
    n_err = sum(1 for d in diags if d.severity == "error")
    n_warn = len(diags) - n_err
    detail = ", ".join(f"{k}={v}" for k, v in by_pass.items())
    print(f"paddle_tpu.analysis: {n_err} error(s), {n_warn} warning(s)"
          + (f" [{detail}]" if detail else ""))
    return 1 if has_errors(diags) else 0


if __name__ == "__main__":
    sys.exit(main())

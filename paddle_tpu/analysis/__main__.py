"""CLI: ``python -m paddle_tpu.analysis``.

Default action lints Python sources (the whole ``paddle_tpu`` package when
no paths are given) with both the general source lint and the concurrency
lint. ``--verify-program DIR`` additionally verifies an exported native
program directory (``program.txt`` + ``weights.bin``). Exit status 1 when
any error-severity diagnostic was produced.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from paddle_tpu.analysis.concurrency_lint import lint_concurrency
from paddle_tpu.analysis.diagnostics import Diagnostic, format_diagnostics, has_errors
from paddle_tpu.analysis.source_lint import lint_source
from paddle_tpu.analysis.verifier import verify_text


def _verify_program_dir(path: str) -> List[Diagnostic]:
    prog_path = os.path.join(path, "program.txt") if os.path.isdir(path) else path
    with open(prog_path, "r", encoding="utf-8") as f:
        text = f.read()
    weights = b""
    wpath = os.path.join(os.path.dirname(prog_path), "weights.bin")
    if os.path.exists(wpath):
        with open(wpath, "rb") as f:
            weights = f.read()
    return verify_text(text, weights=weights)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="paddle_tpu static analysis: source lint + program verifier",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to source-lint (default: the paddle_tpu package)",
    )
    ap.add_argument(
        "--verify-program", metavar="DIR", default=None,
        help="also verify an exported native program (directory containing "
        "program.txt, or the program.txt path itself)",
    )
    ap.add_argument(
        "--no-source-lint", action="store_true",
        help="skip the source lint (e.g. with --verify-program alone)",
    )
    args = ap.parse_args(argv)

    diags: List[Diagnostic] = []
    if not args.no_source_lint:
        diags.extend(lint_source(args.paths or None))
        diags.extend(lint_concurrency(args.paths or None))
    if args.verify_program:
        diags.extend(_verify_program_dir(args.verify_program))

    if diags:
        print(format_diagnostics(diags))
    n_err = sum(1 for d in diags if d.severity == "error")
    n_warn = len(diags) - n_err
    print(f"paddle_tpu.analysis: {n_err} error(s), {n_warn} warning(s)")
    return 1 if has_errors(diags) else 0


if __name__ == "__main__":
    sys.exit(main())

"""Shared diagnostic record for the static-analysis subsystem.

All three analyzers (IR verifier, model linter, source lint) report through
one structured record so callers — tests, the CLI, the serving warm-up
hook — can filter by severity/code and print uniformly. The reference
framework's analogue is the enforce-message convention of
``PADDLE_ENFORCE`` plus the ``inference/analysis`` Argument/analysis-pass
reporting; here diagnostics are first-class values instead of exception
strings so a whole program can be checked in one run.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional

__all__ = ["Diagnostic", "format_diagnostics", "has_errors", "ERROR", "WARNING"]

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass
class Diagnostic:
    """One finding.

    ``code`` is a stable kebab-case identifier (tests match on it),
    ``where`` locates the finding (``program.txt:12``, ``file.py:34``, a
    parameter name), and ``source`` carries the offending line when there
    is one.
    """

    code: str
    message: str
    severity: str = ERROR
    where: str = ""
    source: str = ""

    def __str__(self) -> str:
        loc = f"{self.where}: " if self.where else ""
        src = f"\n    | {self.source.strip()}" if self.source else ""
        return f"{loc}{self.severity}[{self.code}] {self.message}{src}"


def has_errors(diags: Iterable[Diagnostic]) -> bool:
    return any(d.severity == ERROR for d in diags)


def format_diagnostics(diags: Iterable[Diagnostic], limit: Optional[int] = None) -> str:
    diags = list(diags)
    shown: List[str] = [str(d) for d in (diags[:limit] if limit else diags)]
    if limit and len(diags) > limit:
        shown.append(f"... and {len(diags) - limit} more")
    return "\n".join(shown)

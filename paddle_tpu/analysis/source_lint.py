"""Repo source lint: AST-based checks for paddle_tpu's own invariants.

PR 1 shipped a seed breakage this linter would have caught: a raw ``from
jax import shard_map`` import that only worked on new jax releases until
``core/compat.py`` grew a shim. Generic linters cannot know the repo's
rules; this one encodes them:

* ``compat-import`` — version-sensitive jax symbols (``shard_map``) must
  be imported via ``paddle_tpu.core.compat``, never straight from jax;
* ``unguarded-export-import`` — ``jax.export`` imports must sit inside a
  ``try/except ImportError`` (older jax does not re-export it);
* ``traced-wallclock`` / ``traced-py-rng`` — traced model/op code (the
  ``ops``/``layers``/``models`` trees and ``nets.py``) must not call
  wall-clock functions or Python/global-numpy RNGs: under ``jax.jit`` the
  value is frozen at trace time and silently reused forever after;
* ``bare-assert`` — user-facing (public) functions must raise
  ``paddle_tpu.core.enforce.enforce()`` instead of ``assert``: asserts
  vanish under ``python -O`` and carry no structured context;
* ``metric-name`` — metric names at ``inc_counter``/``set_gauge``/
  ``observe`` call sites must be dotted ``subsystem.snake_case``
  (``trainer.steps_total``): the observability exporter groups families
  by subsystem prefix and a flat or CamelCase name silently lands
  outside every dashboard query;
* ``span-name`` — span/event names at ``record_event``/``start_span``/
  ``start_trace``/``record_span`` call sites follow the same dotted
  lowercase convention (``serving.execute``): the merged Chrome-trace
  export and ``phase_totals`` group timeline rows by that prefix, and a
  free-form name fragments the timeline. The fleet-observability
  families (``serving.fleet.*``, ``flight_recorder.*``) ride the same
  rule — the ``/fleet`` and ``/trace/<id>`` views group by it;
* ``fleet-metric-kind`` — ``serving.fleet.*`` families are *recomputed*
  on every ``FleetView.rollup()`` and must be published with
  ``set_gauge``: an ``inc_counter``/``observe`` there accumulates across
  rollup calls and silently double-counts the fleet.

Runnable as ``python -m paddle_tpu.analysis`` and over the whole tree in
``tests/test_source_lint.py`` (so the gate rides tier-1). Suppress a
finding with a ``# lint: allow`` comment on the offending line.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic

__all__ = ["lint_source", "lint_file", "default_roots"]

_SUPPRESS = "# lint: allow"

# dirs (relative to the package) whose code runs under jax tracing
_TRACED_DIRS = ("ops", "layers", "models")
_TRACED_FILES = ("nets.py",)

# dotted call chains that freeze a trace-time value into the program
_WALLCLOCK_CALLS = {
    "time.time", "time.time_ns", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.date.today",
}
# np.random.<fn> constructors that are fine (explicitly-seeded generators
# passed around as values, not hidden global state)
_NP_RANDOM_OK = {"RandomState", "default_rng", "Generator", "SeedSequence",
                 "PCG64", "Philox", "MT19937", "BitGenerator"}

# metric-registry entry points whose first argument is a metric name
_METRIC_FNS = ("inc_counter", "set_gauge", "observe")
# dotted subsystem.snake_case with at least one dot: "trainer.steps_total"
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
# an f-string name must open with a literal "subsystem." prefix and its
# literal head must stay inside the legal alphabet (no "name:{var}" keys —
# variable parts belong in labels=, not baked into the family name)
_METRIC_PREFIX_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z0-9_.]*$")

# span/event entry points whose first argument is a timeline name; the
# naming convention matches metrics (dotted lowercase) so the Chrome-trace
# export and phase_totals() group rows by subsystem prefix
_SPAN_FNS = ("record_event", "start_span", "start_trace", "record_span")

# fleet rollup families are recomputed (not accumulated) every
# FleetView.rollup() — only set_gauge may publish them
_FLEET_PREFIX = "serving.fleet."
_FLEET_GAUGE_ONLY_FNS = ("inc_counter", "observe")


def default_roots() -> List[str]:
    """The package tree this lint governs."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_traced_path(path: str) -> bool:
    norm = os.path.normpath(path).split(os.sep)
    if "paddle_tpu" in norm:
        rel = norm[norm.index("paddle_tpu") + 1:]
    else:
        rel = norm[-2:]
    if rel and rel[0] in _TRACED_DIRS:
        return True
    return bool(rel) and rel[-1] in _TRACED_FILES


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: List[str], traced: bool,
                 is_compat_module: bool):
        self.path = path
        self.lines = source_lines
        self.traced = traced
        self.is_compat_module = is_compat_module
        self.diags: List[Diagnostic] = []
        # lexical context stacks
        self._try_depth = 0          # inside a try: with an except clause
        self._scope: List[str] = []  # enclosing class/function names

    # -- helpers -----------------------------------------------------------

    def _diag(self, code: str, message: str, node: ast.AST,
              severity: str = ERROR) -> None:
        line_no = getattr(node, "lineno", 0)
        src = self.lines[line_no - 1] if 0 < line_no <= len(self.lines) else ""
        if _SUPPRESS in src:
            return
        self.diags.append(Diagnostic(
            code, message, severity=severity,
            where=f"{self.path}:{line_no}", source=src,
        ))

    def _public_context(self) -> bool:
        """True when every enclosing def/class is public (dunders count as
        public: __init__/__call__ are user entry points; a single leading
        underscore marks internal)."""
        if not self._scope:
            return True  # module level
        for name in self._scope:
            if name.startswith("_") and not (
                name.startswith("__") and name.endswith("__")
            ):
                return False
        return True

    # -- scope/ancestor tracking ------------------------------------------

    def visit_Try(self, node: ast.Try) -> None:
        catches_import_error = any(
            h.type is None
            or (isinstance(h.type, ast.Name) and h.type.id in
                ("ImportError", "ModuleNotFoundError", "Exception"))
            or (isinstance(h.type, ast.Tuple) and any(
                isinstance(e, ast.Name) and e.id in
                ("ImportError", "ModuleNotFoundError", "Exception")
                for e in h.type.elts))
            for h in node.handlers
        )
        if catches_import_error:
            self._try_depth += 1
            for stmt in node.body:
                self.visit(stmt)
            self._try_depth -= 1
            for part in (node.handlers, node.orelse, node.finalbody):
                for stmt in part:
                    self.visit(stmt)
        else:
            self.generic_visit(node)

    def _visit_scoped(self, node) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_scoped
    visit_AsyncFunctionDef = _visit_scoped
    visit_ClassDef = _visit_scoped

    # -- rule: compat-sensitive imports -----------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        names = [a.name for a in node.names]
        if not self.is_compat_module:
            if (mod == "jax" and "shard_map" in names) or mod.startswith(
                "jax.experimental.shard_map"
            ):
                self._diag(
                    "compat-import",
                    "shard_map moved between jax releases; import it from "
                    "paddle_tpu.core.compat, which shims both spellings",
                    node,
                )
        if (mod == "jax.export" or (mod == "jax" and "export" in names)) \
                and not self._try_depth:
            self._diag(
                "unguarded-export-import",
                "jax.export is absent on older jax; wrap the import in "
                "try/except ImportError (see paddle_tpu/io.py)",
                node,
            )
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.startswith("jax.experimental.shard_map") \
                    and not self.is_compat_module:
                self._diag(
                    "compat-import",
                    "import shard_map via paddle_tpu.core.compat",
                    node,
                )
            if alias.name == "jax.export" and not self._try_depth:
                self._diag(
                    "unguarded-export-import",
                    "jax.export is absent on older jax; wrap the import in "
                    "try/except ImportError (see paddle_tpu/io.py)",
                    node,
                )
        self.generic_visit(node)

    # -- rules on expressions ---------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if not self.is_compat_module:
            chain = _dotted(node)
            if chain in ("jax.shard_map", "jax.experimental.shard_map"):
                self._diag(
                    "compat-import",
                    "use paddle_tpu.core.compat.shard_map, not a raw jax "
                    "attribute path",
                    node,
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.traced:
            chain = _dotted(node.func)
            if chain in _WALLCLOCK_CALLS:
                self._diag(
                    "traced-wallclock",
                    f"{chain}() inside traced model/op code is frozen at "
                    "trace time and silently reused on every later call; "
                    "thread times in as inputs instead",
                    node,
                )
            elif chain and chain.startswith("random."):
                self._diag(
                    "traced-py-rng",
                    f"{chain}() uses Python's global RNG inside traced code; "
                    "use jax.random with an explicit key "
                    "(framework.next_rng_key)",
                    node,
                )
            elif chain and (
                chain.startswith("np.random.") or chain.startswith("numpy.random.")
            ):
                fn = chain.rsplit(".", 1)[-1]
                if fn not in _NP_RANDOM_OK:
                    self._diag(
                        "traced-py-rng",
                        f"{chain}() draws from numpy's hidden global RNG "
                        "inside traced code; pass an explicit "
                        "np.random.RandomState / jax key instead",
                        node,
                    )
        self._check_metric_name(node)
        self._check_span_name(node)
        self.generic_visit(node)

    def _check_metric_name(self, node: ast.Call) -> None:
        """metric-name: inc_counter/set_gauge/observe with a literal name
        must use dotted subsystem.snake_case. Non-literal names (variables,
        attribute reads) are out of scope; an f-string must open with a
        literal ``subsystem.`` prefix so the family is still groupable."""
        chain = _dotted(node.func)
        if not chain or chain.rsplit(".", 1)[-1] not in _METRIC_FNS:
            return
        if not node.args:
            return
        arg0 = node.args[0]
        if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
            if not _METRIC_NAME_RE.match(arg0.value):
                self._diag(
                    "metric-name",
                    f"metric name {arg0.value!r} is not dotted "
                    "subsystem.snake_case (e.g. 'trainer.steps_total'); "
                    "un-prefixed names land outside every dashboard query",
                    node,
                )
            elif (arg0.value.startswith(_FLEET_PREFIX)
                  and chain.rsplit(".", 1)[-1] in _FLEET_GAUGE_ONLY_FNS):
                self._diag(
                    "fleet-metric-kind",
                    f"{arg0.value!r} is a fleet rollup family: it is "
                    "recomputed on every FleetView.rollup(), so it must be "
                    "published with set_gauge — a counter/histogram here "
                    "double-counts the fleet on every rollup call",
                    node,
                )
        elif isinstance(arg0, ast.JoinedStr):
            head = arg0.values[0] if arg0.values else None
            if not (isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    and _METRIC_PREFIX_RE.match(head.value)):
                self._diag(
                    "metric-name",
                    "f-string metric name must start with a literal "
                    "'subsystem.' prefix (prefer a fixed name plus labels= "
                    "for the variable part)",
                    node,
                )

    def _check_span_name(self, node: ast.Call) -> None:
        """span-name: record_event/start_span/start_trace/record_span with a
        literal name must use dotted lowercase (``serving.execute``). Same
        f-string rule as metrics: the literal head must carry a dotted
        prefix so the timeline row still groups by subsystem."""
        chain = _dotted(node.func)
        if not chain or chain.rsplit(".", 1)[-1] not in _SPAN_FNS:
            return
        if not node.args:
            return
        arg0 = node.args[0]
        if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
            if not _METRIC_NAME_RE.match(arg0.value):
                self._diag(
                    "span-name",
                    f"span name {arg0.value!r} is not dotted lowercase "
                    "(e.g. 'serving.execute'); free-form names fragment the "
                    "merged trace timeline and phase_totals() grouping",
                    node,
                )
        elif isinstance(arg0, ast.JoinedStr):
            head = arg0.values[0] if arg0.values else None
            if not (isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    and _METRIC_PREFIX_RE.match(head.value)):
                self._diag(
                    "span-name",
                    "f-string span name must start with a literal "
                    "'subsystem.' prefix (put the variable part in span "
                    "attributes, not the name)",
                    node,
                )

    def visit_Assert(self, node: ast.Assert) -> None:
        if self._public_context():
            self._diag(
                "bare-assert",
                "bare assert on a user-facing path: it vanishes under "
                "python -O and reports no context — use "
                "paddle_tpu.core.enforce.enforce()",
                node,
            )
        self.generic_visit(node)


def lint_file(path: str, text: Optional[str] = None,
              traced: Optional[bool] = None) -> List[Diagnostic]:
    """Lint one Python file. ``traced`` overrides the path-based detection
    of traced model/op code (tests use this on fixture files)."""
    if text is None:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [Diagnostic("syntax-error", str(e), where=f"{path}:{e.lineno or 0}")]
    if traced is None:
        traced = _is_traced_path(path)
    is_compat = os.path.normpath(path).endswith(os.path.join("core", "compat.py"))
    linter = _Linter(path, text.splitlines(), traced, is_compat)
    linter.visit(tree)
    return linter.diags


def lint_source(paths: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint a set of files/directories (default: the paddle_tpu package)."""
    targets: List[str] = []
    for p in (list(paths) if paths else default_roots()):
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                targets.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        else:
            targets.append(p)
    diags: List[Diagnostic] = []
    for path in targets:
        diags.extend(lint_file(path))
    return diags

"""Model linter: abstract-trace a :class:`framework.Model` and report
structural problems before they cost device time.

The reference framework caught these classes of bug operationally —
``PADDLE_ENFORCE`` inside InferShape, duplicate-variable checks when
appending to a ``BlockDesc``, regularizer plumbing in the optimizer — but
always one bug per run, at run time. Here the whole model is traced once
through ``jax.eval_shape`` (zero FLOPs, zero device memory) and every
finding comes back as a structured :class:`Diagnostic`:

* ``param-collision`` — two ``create_parameter`` calls resolve to the same
  full name (explicit ``ParamAttr.name`` reuse inside one scope);
* ``init-apply-mismatch`` — ``apply`` requests a parameter ``init`` never
  created, or with a different shape;
* ``unused-param`` — a parameter exists in the variable set but no apply
  path reads it (checkpoint/config drift; sees through scan-over-layers
  via the frame's read ledger);
* ``sharding-rank`` — a ``ParamAttr.sharding`` spec whose rank disagrees
  with the parameter shape (would fail at mesh-partition time);
* ``float64-leak`` — a parameter/state/output declared float64: on TPU
  this silently downcasts (x64 off) or catastrophically deoptimizes
  (x64 on);
* ``stale-state`` — a state entry created in init but never updated by a
  training-mode apply (a moving statistic that never moves);
* ``cross-scope-state`` — an ``update_state`` that only resolved through
  the bare-name fallback (see ``framework.update_state``);
* ``regularizer-non-trainable`` — weight decay attached to a frozen
  parameter: it would silently do nothing.

Used directly (``lint_model``), from the CLI (``python -m
paddle_tpu.analysis --model``-style fixtures in tests), and as the serving
warm-up hook (``serving.engine.ServingEngine`` lints the model it is about
to compile and logs findings).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic
from paddle_tpu.core.enforce import EnforceError

__all__ = ["lint_model"]


def _sds(x):
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    arr = np.asarray(x)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def _split_variables(variables) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    if hasattr(variables, "params"):
        return dict(variables.params), dict(getattr(variables, "state", {}) or {})
    if isinstance(variables, tuple) and len(variables) == 2:
        return dict(variables[0]), dict(variables[1] or {})
    return dict(variables), {}


def _is_f64(dtype) -> bool:
    try:
        return np.dtype(dtype) == np.float64
    except TypeError:
        return str(dtype) in ("float64", "f64")


def lint_model(
    model,
    example_inputs: Sequence,
    variables=None,
    rng: int = 0,
    train: bool = True,
) -> List[Diagnostic]:
    """Abstractly trace ``model`` over ``example_inputs`` and return
    diagnostics. ``example_inputs`` may be arrays or
    ``jax.ShapeDtypeStruct``s — nothing is ever computed. When
    ``variables`` is omitted, ``model.init`` is traced too (enabling the
    init-vs-apply checks); otherwise the provided variable set is linted
    against a single apply trace."""
    import jax

    from paddle_tpu.framework import Model, build

    if not isinstance(model, Model):
        model = build(model)
    diags: List[Diagnostic] = []
    key_struct = _sds(jax.random.PRNGKey(rng))
    abstract_inputs = tuple(_sds(x) for x in example_inputs)

    init_info = None
    if variables is None:
        try:
            variables = jax.eval_shape(
                lambda k, *xs: model.init(k, *xs), key_struct, *abstract_inputs
            )
        except EnforceError as e:
            code = (
                "param-collision"
                if "duplicate parameter" in str(e)
                else "init-error"
            )
            diags.append(Diagnostic(code, str(e), where=f"{model.name}.init"))
            return diags
        init_info = dict(model.param_info)
    else:
        variables = jax.tree_util.tree_map(_sds, variables)
    params, state = _split_variables(variables)

    try:
        out_struct = jax.eval_shape(
            lambda k, v, *xs: model.apply(v, *xs, rng=k, is_train=train),
            key_struct, variables, *abstract_inputs,
        )
    except EnforceError as e:
        diags.append(
            Diagnostic("init-apply-mismatch", str(e), where=f"{model.name}.apply")
        )
        return diags

    apply_info = dict(model._last_param_info)
    reads = set(model._last_param_reads)
    updated = set(model._last_state_updates)
    cross_scope = set(model._last_cross_scope_updates)

    # -- structural: params present but never read by this apply trace
    for name in sorted(set(params) - reads):
        diags.append(Diagnostic(
            "unused-param",
            f"parameter {name!r} exists in the variable set but no apply "
            "path reads it (checkpoint/config drift, or a branch this trace "
            "did not take)",
            severity=WARNING, where=name,
        ))

    # -- per-param metadata checks (init metadata wins: it records every
    # parameter; apply-only tracing still covers what was read)
    info = dict(apply_info)
    if init_info:
        info.update(init_info)
    for name, pi in sorted(info.items()):
        if pi.sharding is not None and len(pi.sharding) != len(pi.shape):
            diags.append(Diagnostic(
                "sharding-rank",
                f"parameter {name!r} has sharding spec {pi.sharding} of rank "
                f"{len(pi.sharding)} but shape {pi.shape} of rank "
                f"{len(pi.shape)} — pjit partitioning would reject it",
                where=name,
            ))
        if _is_f64(pi.dtype):
            diags.append(Diagnostic(
                "float64-leak",
                f"parameter {name!r} is declared float64; TPU-native code is "
                "f32/bf16 — with x64 disabled this silently downcasts",
                where=name,
            ))
        if pi.regularizer is not None and not pi.trainable:
            diags.append(Diagnostic(
                "regularizer-non-trainable",
                f"parameter {name!r} is non-trainable but carries a "
                "regularizer; the optimizer will never apply it",
                severity=WARNING, where=name,
            ))

    # -- state checks
    if train:
        for name in sorted(set(state) - updated):
            diags.append(Diagnostic(
                "stale-state",
                f"state entry {name!r} was created but never updated by a "
                "training-mode apply — a moving statistic that never moves",
                severity=WARNING, where=name,
            ))
    for scoped, bare in sorted(cross_scope):
        diags.append(Diagnostic(
            "cross-scope-state",
            f"update_state({bare!r}) inside scope {scoped.rsplit('/', 1)[0]!r} "
            "resolved through the bare-name fallback; address state within "
            "the name_scope that created it",
            severity=WARNING, where=scoped,
        ))

    # -- dtype promotion leaks on state and outputs
    for name, s in sorted(state.items()):
        if _is_f64(getattr(s, "dtype", None)):
            diags.append(Diagnostic(
                "float64-leak", f"state entry {name!r} is float64", where=name
            ))
    out_leaves = jax.tree_util.tree_leaves(out_struct[0])
    for i, leaf in enumerate(out_leaves):
        if _is_f64(getattr(leaf, "dtype", None)):
            diags.append(Diagnostic(
                "float64-leak",
                f"model output {i} has dtype float64 — a python-float/x64 "
                "promotion leaked into the traced program",
                where=f"{model.name}.apply output {i}",
            ))
    return diags

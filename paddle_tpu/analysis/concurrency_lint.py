"""Concurrency lint: AST checks for the locking discipline that
``paddle_tpu.core.locks`` enforces at runtime.

PR 11 and PR 12 each shipped a fix for a *pre-existing* deadlock found by
accident (``DecodeEngine.close`` hang; ``WeightedFairScheduler.recv``
parking while holding un-fired expiry callbacks). Both bugs had the same
textual shape — work invoked while a lock is held, or a wait that can
park forever — which a repo-specific static pass catches at review time.
Rules:

* ``raw-threading-lock`` — ``threading.Lock()`` / ``RLock()`` /
  ``Condition()`` constructed anywhere in the package outside
  ``core/locks.py`` itself: threaded subsystems must use the named,
  instrumented ``core.locks`` wrappers so the lock-order detector and the
  held-locks registry see every lock;
* ``wait-without-timeout`` — zero-argument ``.wait()`` or ``.join()``:
  an unbounded park cannot be woken by shutdown paths that race the
  waiter (the PR 11 close-hang shape). Pass a timeout and re-check in a
  loop;
* ``wait-without-predicate-loop`` — ``cond.wait(...)`` on a Condition
  not lexically inside a ``while``: stolen wakeups and ``notify_all``
  broadcasts make a bare wait return with the predicate still false;
* ``callback-under-lock`` — invoking a user callback / subscriber
  (``on_*`` / ``*callback*`` names) inside a ``with <lock>:`` body: the
  exact PR 12 bug shape (callback re-enters the lock, or blocks while
  every other thread needs it). Collect under the lock, fire after
  release — the pattern ``MetricRegistry._notify`` already follows;
* ``blocking-io-under-lock`` — filesystem / sleep / subprocess / socket
  calls inside a ``with <lock>:`` body: every thread contending that
  lock now waits on the disk or the network.

Lock-ish context expressions are recognized by name (last dotted segment
containing ``lock``/``cond``/``mutex``) — naming a lock something else
hides it from the lexical rules, which is the usual precision/recall
trade for AST lint; the runtime order-graph has no such blind spot.

Wired into ``python -m paddle_tpu.analysis`` and the whole-tree-clean
test in ``tests/test_concurrency_lint.py`` (so the gate rides tier-1).
Suppress a finding with ``# lint: allow`` on the offending line.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Set

from paddle_tpu.analysis.diagnostics import ERROR, Diagnostic
from paddle_tpu.analysis.source_lint import _dotted, default_roots

__all__ = ["lint_concurrency", "lint_file", "default_roots"]

_SUPPRESS = "# lint: allow"

_RAW_PRIMITIVES = ("Lock", "RLock", "Condition")

# last-segment names that mark a with-context as "holding a lock"
_LOCKISH = ("lock", "cond", "mutex")

# dotted call chains that block on the filesystem / network / clock
_BLOCKING_CALLS = {
    "open", "time.sleep",
    "os.fsync", "os.replace", "os.rename", "os.remove", "os.unlink",
    "os.makedirs", "os.rmdir", "os.listdir", "os.stat",
}
_BLOCKING_PREFIXES = ("subprocess.", "shutil.", "socket.", "urllib.",
                      "requests.")


def _is_lockish(expr: ast.AST) -> bool:
    """Does this with-context expression look like a lock acquisition?
    Matches names/attributes whose last segment contains lock/cond/mutex
    (``self._lock``, ``cache_lock``, ``self._cond``) and direct
    ``.acquire()``-style helpers on such names."""
    chain = _dotted(expr)
    if chain is None and isinstance(expr, ast.Call):
        chain = _dotted(expr.func)
    if not chain:
        return False
    last = chain.rsplit(".", 1)[-1].lower()
    return any(k in last for k in _LOCKISH)


def _is_locks_module(path: str) -> bool:
    return os.path.normpath(path).endswith(os.path.join("core", "locks.py"))


class _CondNames(ast.NodeVisitor):
    """Pre-pass: names assigned from ``Condition(...)`` constructors (raw
    or ``core.locks``), so ``wait-without-predicate-loop`` does not fire
    on ``Event.wait`` / ``Thread.join`` / queue waits."""

    def __init__(self) -> None:
        self.names: Set[str] = set()

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            chain = _dotted(node.value.func) or ""
            if chain.rsplit(".", 1)[-1] == "Condition":
                for tgt in node.targets:
                    chain_t = _dotted(tgt)
                    if chain_t:
                        self.names.add(chain_t.rsplit(".", 1)[-1])
        self.generic_visit(node)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: List[str],
                 cond_names: Set[str]):
        self.path = path
        self.lines = source_lines
        self.cond_names = cond_names
        self.diags: List[Diagnostic] = []
        self._while_depth = 0
        self._lock_depth = 0  # lexically inside a `with <lockish>:` body

    def _diag(self, code: str, message: str, node: ast.AST,
              severity: str = ERROR) -> None:
        line_no = getattr(node, "lineno", 0)
        src = self.lines[line_no - 1] if 0 < line_no <= len(self.lines) else ""
        if _SUPPRESS in src:
            return
        self.diags.append(Diagnostic(
            code, message, severity=severity,
            where=f"{self.path}:{line_no}", source=src,
        ))

    # -- lexical context ---------------------------------------------------

    def visit_While(self, node: ast.While) -> None:
        self._while_depth += 1
        self.generic_visit(node)
        self._while_depth -= 1

    def visit_With(self, node: ast.With) -> None:
        lockish = any(_is_lockish(item.context_expr) for item in node.items)
        for item in node.items:
            self.visit(item)
        if lockish:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self._lock_depth -= 1

    # functions defined inside a with-block run LATER, not under the lock
    def _visit_fn(self, node) -> None:
        saved = self._lock_depth
        self._lock_depth = 0
        self.generic_visit(node)
        self._lock_depth = saved

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn
    visit_Lambda = _visit_fn

    # -- rules -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        chain = _dotted(node.func) or ""
        last = chain.rsplit(".", 1)[-1] if chain else ""

        # raw-threading-lock: threading.Lock/RLock/Condition constructors
        if chain in tuple(f"threading.{p}" for p in _RAW_PRIMITIVES):
            self._diag(
                "raw-threading-lock",
                f"{chain}() bypasses the lock-order detector and the "
                "held-locks registry; use the named core.locks wrapper "
                f"(locks.{chain.rsplit('.', 1)[-1]}(name='subsystem.role'))",
                node,
            )

        # wait-without-timeout: zero-arg .wait() / .join()
        if last in ("wait", "join") and not node.args and not node.keywords \
                and isinstance(node.func, ast.Attribute):
            self._diag(
                "wait-without-timeout",
                f".{last}() with no timeout parks forever if the notifier "
                "races shutdown (the DecodeEngine.close hang shape); pass "
                "a timeout and re-check the predicate in a loop",
                node,
            )

        # wait-without-predicate-loop: cond.wait(...) outside a while
        if last == "wait" and isinstance(node.func, ast.Attribute):
            recv = _dotted(node.func.value)
            recv_last = recv.rsplit(".", 1)[-1] if recv else ""
            if recv_last in self.cond_names and not self._while_depth:
                self._diag(
                    "wait-without-predicate-loop",
                    f"{recv}.wait() outside a while-predicate loop: "
                    "notify_all broadcasts and stolen wakeups return with "
                    "the predicate still false — use "
                    "`while not pred: cond.wait(timeout)`",
                    node,
                )

        # rules that only apply inside a `with <lock>:` body
        if self._lock_depth:
            if last.startswith("on_") or "callback" in last.lower():
                self._diag(
                    "callback-under-lock",
                    f"{chain or last}(...) invoked while holding a lock — "
                    "the WeightedFairScheduler.recv deadlock shape (PR 12): "
                    "the callback can re-enter the lock or block every "
                    "other thread; collect under the lock, fire after "
                    "release",
                    node,
                )
            elif chain in _BLOCKING_CALLS or any(
                    chain.startswith(p) for p in _BLOCKING_PREFIXES):
                self._diag(
                    "blocking-io-under-lock",
                    f"{chain}(...) inside a `with lock:` body serializes "
                    "every contending thread behind the disk/network; move "
                    "the I/O outside the critical section",
                    node,
                )
        self.generic_visit(node)


def lint_file(path: str, text: Optional[str] = None) -> List[Diagnostic]:
    """Lint one Python file for concurrency-discipline violations."""
    if text is None:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    if _is_locks_module(path):
        return []  # the wrapper module itself owns the raw primitives
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [Diagnostic("syntax-error", str(e),
                           where=f"{path}:{e.lineno or 0}")]
    pre = _CondNames()
    pre.visit(tree)
    linter = _Linter(path, text.splitlines(), pre.names)
    linter.visit(tree)
    return linter.diags


def lint_concurrency(paths: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint a set of files/directories (default: the paddle_tpu package)."""
    targets: List[str] = []
    for p in (list(paths) if paths else default_roots()):
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                targets.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        else:
            targets.append(p)
    diags: List[Diagnostic] = []
    for path in targets:
        diags.extend(lint_file(path))
    return diags

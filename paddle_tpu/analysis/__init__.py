"""paddle_tpu.analysis — static analysis for the native IR and models.

Three layers, one diagnostics vocabulary (:mod:`.diagnostics`):

* :mod:`.verifier` — SSA + shape/dtype verification of native ``Program``
  text (wired into ``PassManager.run`` and ``native.export``);
* :mod:`.model_lint` — abstract-traces a ``framework.Model`` via
  ``jax.eval_shape`` and reports structural problems (lazy import: pulls
  in jax);
* :mod:`.source_lint` — AST lint of the repo's own Python sources for
  repo-specific invariants (stdlib only);
* :mod:`.concurrency_lint` — AST lint for the locking discipline that
  ``core.locks`` enforces at runtime (raw primitives, unbounded waits,
  callbacks/blocking I/O under a lock);
* :mod:`.retrace_lint` — AST lint for the compile-once discipline
  (trace-frozen config reads, dynamic-closure ``len()``, jit-in-loop,
  dict-order-dependent donate/shardings, missing ``static_argnums``);
* :mod:`.shard_analysis` — zero-FLOP sharding-layout analyzer: propagates
  a ``GroupLayout``'s PartitionSpecs over an ``eval_shape`` param tree
  and reports dead rules, silent degrades (with HBM cost), cross-layout
  conflicts, KV-geometry violations, and a static tp comm report (lazy
  import: pulls in jax).

CLI: ``python -m paddle_tpu.analysis [paths...] [--only PASS]
[--verify-program DIR]`` — aggregated exit code over all passes.
"""

from __future__ import annotations

from paddle_tpu.analysis.diagnostics import (
    ERROR,
    WARNING,
    Diagnostic,
    format_diagnostics,
    has_errors,
)
from paddle_tpu.analysis.concurrency_lint import lint_concurrency
from paddle_tpu.analysis.retrace_lint import lint_retrace
from paddle_tpu.analysis.source_lint import lint_file, lint_source
from paddle_tpu.analysis.verifier import (
    VerificationError,
    verify_or_raise,
    verify_program,
    verify_text,
)

__all__ = [
    "Diagnostic",
    "ERROR",
    "WARNING",
    "analyze_layout",
    "analyze_model",
    "compare_layouts",
    "format_diagnostics",
    "has_errors",
    "lint_concurrency",
    "lint_file",
    "lint_group_layout_or_raise",
    "lint_model",
    "lint_retrace",
    "lint_source",
    "tp_comm_report",
    "VerificationError",
    "verify_or_raise",
    "verify_program",
    "verify_text",
]

# jax-importing entry points, loaded lazily so the verifier path (used
# inside PassManager) stays stdlib-light.
_LAZY = {
    "lint_model": "paddle_tpu.analysis.model_lint",
    "analyze_layout": "paddle_tpu.analysis.shard_analysis",
    "analyze_model": "paddle_tpu.analysis.shard_analysis",
    "compare_layouts": "paddle_tpu.analysis.shard_analysis",
    "lint_group_layout_or_raise": "paddle_tpu.analysis.shard_analysis",
    "tp_comm_report": "paddle_tpu.analysis.shard_analysis",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is not None:
        import importlib

        return getattr(importlib.import_module(mod), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""paddle_tpu.analysis — static analysis for the native IR and models.

Three layers, one diagnostics vocabulary (:mod:`.diagnostics`):

* :mod:`.verifier` — SSA + shape/dtype verification of native ``Program``
  text (wired into ``PassManager.run`` and ``native.export``);
* :mod:`.model_lint` — abstract-traces a ``framework.Model`` via
  ``jax.eval_shape`` and reports structural problems (lazy import: pulls
  in jax);
* :mod:`.source_lint` — AST lint of the repo's own Python sources for
  repo-specific invariants (stdlib only);
* :mod:`.concurrency_lint` — AST lint for the locking discipline that
  ``core.locks`` enforces at runtime (raw primitives, unbounded waits,
  callbacks/blocking I/O under a lock).

CLI: ``python -m paddle_tpu.analysis [paths...] [--verify-program DIR]``.
"""

from __future__ import annotations

from paddle_tpu.analysis.diagnostics import (
    ERROR,
    WARNING,
    Diagnostic,
    format_diagnostics,
    has_errors,
)
from paddle_tpu.analysis.concurrency_lint import lint_concurrency
from paddle_tpu.analysis.source_lint import lint_file, lint_source
from paddle_tpu.analysis.verifier import (
    VerificationError,
    verify_or_raise,
    verify_program,
    verify_text,
)

__all__ = [
    "Diagnostic",
    "ERROR",
    "WARNING",
    "format_diagnostics",
    "has_errors",
    "lint_concurrency",
    "lint_file",
    "lint_model",
    "lint_source",
    "VerificationError",
    "verify_or_raise",
    "verify_program",
    "verify_text",
]


def __getattr__(name):
    # lint_model imports jax; load it only when asked for so that the
    # verifier path (used inside PassManager) stays stdlib-light.
    if name == "lint_model":
        from paddle_tpu.analysis.model_lint import lint_model

        return lint_model
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Distributed topology wiring — the DistributeTranspiler successor.

Reference: ``python/paddle/fluid/transpiler/distribute_transpiler.py:142``
(transpile(trainer_id, pservers, trainers, sync_mode) rewriting programs into
send/recv + listen_and_serv) and the NCCL2 mode (``:193`` config with
trainers/trainer_id for multi-node allreduce), wired from env vars
(``trainer.py:229-295`` PADDLE_TRAINING_ROLE/PADDLE_PSERVER_IPS/
PADDLE_TRAINERS/PADDLE_TRAINER_ID).

TPU-native: there are no pserver programs — dense training uses compiled
collectives over the mesh (the nccl2 path is the surviving analogue). The
"transpilation" left is process bootstrap + mesh construction: initialize the
JAX coordination service from the same PADDLE_* env contract and build a
multi-host mesh whose data axis spans processes (DCN) while model/seq axes
stay intra-slice (ICI).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

import jax

from paddle_tpu.core import logging as ptlog
from paddle_tpu.core.enforce import enforce
from paddle_tpu.parallel import mesh as mesh_mod

__all__ = ["DistributedRole", "DistributeTranspiler", "parse_cluster_env"]


@dataclass
class DistributedRole:
    """Parsed cluster wiring (the env contract of trainer.py:229-295)."""

    trainer_id: int = 0
    num_trainers: int = 1
    coordinator: Optional[str] = None
    role: str = "TRAINER"

    @property
    def is_chief(self) -> bool:
        return self.trainer_id == 0


def parse_cluster_env(env: Optional[Dict[str, str]] = None) -> DistributedRole:
    """Read the PADDLE_* env contract. PSERVER roles are rejected: the dense
    TPU path has no parameter server (SURVEY.md north star)."""
    env = dict(os.environ if env is None else env)
    role = env.get("PADDLE_TRAINING_ROLE", "TRAINER").upper()
    enforce(
        role != "PSERVER",
        "parameter-server mode is not part of the TPU framework: dense "
        "training uses mesh collectives (update_method='collective')",
    )
    coordinator = env.get("PADDLE_COORDINATOR_ADDR")
    if coordinator is None:
        # reference nccl2 mode used PADDLE_TRAINER_ENDPOINTS with trainer 0
        # as the id broadcaster (gen_nccl_id); process 0 is the coordinator
        endpoints = env.get("PADDLE_TRAINER_ENDPOINTS", "")
        if endpoints:
            coordinator = endpoints.split(",")[0].strip()
    return DistributedRole(
        trainer_id=int(env.get("PADDLE_TRAINER_ID", "0")),
        num_trainers=int(env.get("PADDLE_TRAINERS", env.get("PADDLE_TRAINERS_NUM", "1"))),
        coordinator=coordinator,
        role=role,
    )


class DistributeTranspiler:
    """API-parity shell for the reference transpiler, producing a mesh
    instead of rewritten programs.

    Usage (replaces transpile(...) + get_trainer_program()):

        t = DistributeTranspiler()
        t.transpile(trainer_id=..., trainers=N)     # bootstraps processes
        mesh = t.trainer_mesh(model_axis=4)         # DCN×ICI mesh
    """

    def __init__(self):
        self.role: Optional[DistributedRole] = None
        self._initialized = False

    def transpile(
        self,
        trainer_id: Optional[int] = None,
        pservers: Optional[str] = None,
        trainers: Optional[int] = None,
        sync_mode: bool = True,
        startup_program=None,
    ) -> "DistributeTranspiler":
        enforce(pservers is None, "pserver mode unsupported (dense/collective only)")
        enforce(sync_mode, "async SGD unsupported: collectives are synchronous")
        role = parse_cluster_env()
        if trainer_id is not None:
            role.trainer_id = trainer_id
        if trainers is not None:
            role.num_trainers = trainers
        self.role = role
        if role.num_trainers > 1 and not self._initialized:
            mesh_mod.initialize_distributed(
                coordinator_address=role.coordinator,
                num_processes=role.num_trainers,
                process_id=role.trainer_id,
            )
            self._initialized = True
        ptlog.vlog(
            0,
            "distribute transpile: trainer %d/%d (coordinator %s)",
            role.trainer_id,
            role.num_trainers,
            role.coordinator,
        )
        return self

    def trainer_mesh(self, model_axis: int = 1, seq_axis: int = 1, **extra_axes: int):
        """Global mesh: data axis spans all processes' chips (collectives on
        the data axis cross DCN; model/seq collectives stay on ICI because
        those axes subdivide each process's local devices)."""
        axes = {mesh_mod.DATA_AXIS: -1}
        if model_axis > 1:
            axes[mesh_mod.MODEL_AXIS] = model_axis
        if seq_axis > 1:
            axes[mesh_mod.SEQ_AXIS] = seq_axis
        axes.update(extra_axes)
        return mesh_mod.make_mesh(axes)

    def get_trainer_program(self):
        """API-parity stub: there is no rewritten program — the train step is
        jit-compiled with mesh shardings; returns None."""
        return None

    def get_pserver_program(self, *_a, **_k):
        raise NotImplementedError(
            "no parameter server in the TPU framework (dense path; "
            "reference listen_and_serv_op.cc:305 has no TPU analogue)"
        )

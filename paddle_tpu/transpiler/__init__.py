"""Program-to-program rewrites, TPU-style.

Reference: ``python/paddle/fluid/transpiler/`` — DistributeTranspiler
(``distribute_transpiler.py:142``), memory_optimize
(``memory_optimization_transpiler.py:384``), InferenceTranspiler
(``inference_transpiler.py``) — plus ``paddle/contrib/float16/
float16_transpiler.py``.

TPU-native: the rewrites operate on (a) traced functions — rematerialization
policies wrap the model fn before jit; (b) parameter pytrees — BN folding and
dtype conversion transform the weights; (c) process topology — the
distributed transpiler wires the multi-host mesh. There is no mutable
ProgramDesc to rewrite; XLA already does liveness, in-place reuse, and
fusion (the bulk of memory_optimize and InferenceTranspiler).
"""

from paddle_tpu.transpiler import amp  # noqa: F401
from paddle_tpu.transpiler import memory  # noqa: F401
from paddle_tpu.transpiler import inference  # noqa: F401
from paddle_tpu.transpiler import distributed  # noqa: F401
from paddle_tpu.transpiler.amp import (  # noqa: F401
    DynamicLossScale,
    amp_minimize,
    cast_params,
)
from paddle_tpu.transpiler.distributed import DistributeTranspiler  # noqa: F401
from paddle_tpu.transpiler.inference import inference_optimize, fuse_batch_norm  # noqa: F401
from paddle_tpu.transpiler.memory import memory_optimize, release_memory  # noqa: F401

__all__ = [
    "amp",
    "memory",
    "inference",
    "distributed",
    "DynamicLossScale",
    "amp_minimize",
    "cast_params",
    "DistributeTranspiler",
    "inference_optimize",
    "fuse_batch_norm",
    "memory_optimize",
    "release_memory",
]

"""Automatic mixed precision: dtype casting + loss scaling.

Reference: ``paddle/contrib/float16/float16_transpiler.py`` (rewrite an
inference program to fp16) — extended here to full mixed-precision training,
which the reference lacked. TPU-first recipe: params/optimizer state fp32,
matmul/conv compute bf16 (MXU-native, no loss scaling needed), fp16 only for
export parity; dynamic loss scaling provided for fp16-style training.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtypes as dt
from paddle_tpu.framework import Model, Variables
from paddle_tpu.optimizer import Optimizer, OptState, StepOutput

__all__ = ["cast_params", "DynamicLossScale", "amp_minimize"]


def cast_params(tree, dtype="bfloat16"):
    """Cast floating leaves of a param/state pytree (float16_transpiler
    parity: its pass rewrote persistable var dtypes + inserted cast ops)."""
    target = dt.convert(dtype)

    def cast(leaf):
        if dt.is_floating(leaf.dtype):
            return leaf.astype(target)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


class DynamicLossScale(NamedTuple):
    """Dynamic loss-scaling state (the standard fp16 recipe; no reference
    counterpart — Fluid fp16 was inference-only)."""

    scale: jax.Array  # current multiplier
    good_steps: jax.Array  # consecutive finite steps

    @staticmethod
    def create(initial: float = 2.0 ** 15) -> "DynamicLossScale":
        return DynamicLossScale(
            scale=jnp.asarray(initial, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
        )

    def update(self, grads_finite, growth_interval: int = 2000, factor: float = 2.0):
        grown = jnp.where(
            self.good_steps + 1 >= growth_interval, self.scale * factor, self.scale
        )
        new_scale = jnp.where(grads_finite, grown, self.scale / factor)
        new_scale = jnp.clip(new_scale, 1.0, 2.0 ** 24)
        new_good = jnp.where(
            grads_finite & (self.good_steps + 1 < growth_interval),
            self.good_steps + 1,
            0,
        )
        return DynamicLossScale(scale=new_scale, good_steps=new_good)


class AmpStepOutput(NamedTuple):
    variables: Variables
    opt_state: OptState
    loss: jax.Array
    loss_scale: DynamicLossScale
    grads_finite: jax.Array


def amp_minimize(
    optimizer: Optimizer,
    model: Model,
    loss_index: int = 0,
    compute_dtype="bfloat16",
    use_loss_scaling: bool = False,
) -> Callable:
    """Mixed-precision train step builder.

    Returns ``step_fn(variables, opt_state, loss_scale, *batch, rng=None)
    -> AmpStepOutput``. Forward runs with params cast to ``compute_dtype``;
    gradients/updates stay fp32 (master weights). With ``use_loss_scaling``
    (fp16 recipe) the loss is multiplied by the dynamic scale, gradients are
    unscaled, and non-finite-gradient steps are skipped while the scale
    backs off.
    """
    param_info = model.param_info

    def step_fn(
        variables: Variables,
        opt_state: OptState,
        loss_scale: Optional[DynamicLossScale],
        *batch,
        rng=None,
    ) -> AmpStepOutput:
        params, state = variables.params, variables.state
        scale_val = loss_scale.scale if use_loss_scaling else jnp.float32(1.0)

        def loss_fn(p):
            p_half = cast_params(p, compute_dtype)
            out, new_state = model.apply(
                Variables(p_half, state), *batch, rng=rng, is_train=True
            )
            loss = out[loss_index] if isinstance(out, (tuple, list)) else out
            loss = jnp.mean(loss.astype(jnp.float32))
            return loss * scale_val, (new_state, loss)

        grads, (new_state, loss) = jax.grad(loss_fn, has_aux=True)(params)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) / scale_val, grads
        )
        finite = jnp.asarray(True)
        for g in jax.tree_util.tree_leaves(grads):
            finite = finite & jnp.all(jnp.isfinite(g))

        info = param_info or model.param_info
        new_params, new_opt = optimizer.apply_gradients(params, grads, opt_state, info)
        if use_loss_scaling:
            # skip the update when gradients overflowed
            new_params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(finite, n, o), new_params, params
            )
            new_opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(finite, n, o), new_opt, opt_state
            )
            loss_scale = loss_scale.update(finite)
        return AmpStepOutput(
            Variables(new_params, new_state), new_opt, loss, loss_scale, finite
        )

    return step_fn

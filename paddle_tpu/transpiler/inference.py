"""Inference-time optimization: BN folding and eval-mode program capture.

Reference: ``python/paddle/fluid/transpiler/inference_transpiler.py`` —
fuse batch_norm into the preceding conv/fc (its ``_fuse_bn`` rewrites the
program and adjusts weights), plus relu/conv fusions which XLA performs
automatically on TPU. Here the only work left is the WEIGHT transform: fold
BN's (scale, bias, moving_mean, moving_var) into the adjacent conv kernel and
bias; dropout stripping is ``is_train=False``; op fusion is XLA's job.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import logging as ptlog
from paddle_tpu.framework import Model, Variables

__all__ = ["fuse_batch_norm", "find_conv_bn_pairs", "inference_optimize"]


def find_conv_bn_pairs(variables: Variables) -> List[Tuple[str, str]]:
    """Detect (conv_scope, bn_scope) pairs by the layer naming convention:
    a ``.../conv2d*/w`` parameter whose sibling scope ``.../batch_norm*``
    holds scale/bias params and moving stats, with matching channel count.
    Mirrors the pattern matching of ``inference_transpiler.py`` _fuse_bn
    (there done on the op graph; here on the name hierarchy)."""
    params, state = variables.params, variables.state
    conv_scopes = {}
    for name in params:
        m = re.match(r"^(.*conv2d[^/]*)/w$", name)
        if m:
            conv_scopes[m.group(1)] = params[name]
    bn_scopes = set()
    for name in state:
        m = re.match(r"^(.*batch_norm[^/]*)/moving_mean$", name)
        if m:
            bn_scopes.add(m.group(1))

    pairs = []
    for conv_scope, w in conv_scopes.items():
        # sibling bn scope: same parent, batch_norm block created right after
        parent = conv_scope.rsplit("/", 1)[0] if "/" in conv_scope else ""
        suffix = re.search(r"_(\d+)$", conv_scope.rsplit("/", 1)[-1])
        candidates = [
            b
            for b in bn_scopes
            if (b.rsplit("/", 1)[0] if "/" in b else "") == parent
        ]
        if suffix:
            candidates = [b for b in candidates if b.endswith(f"_{suffix.group(1)}")]
        else:
            candidates = [b for b in candidates if not re.search(r"_\d+$", b)]
        for b in candidates:
            if state[f"{b}/moving_mean"].shape[0] == w.shape[-1]:
                pairs.append((conv_scope, b))
    return pairs


def fuse_batch_norm(
    variables: Variables,
    pairs: Optional[List[Tuple[str, str]]] = None,
    epsilon: float = 1e-5,
) -> Variables:
    """Fold BN into conv weights: ``w' = w * gamma/sqrt(var+eps)`` per output
    channel, ``b' = beta - gamma*mean/sqrt(var+eps)`` (+ folded old bias).
    BN scale/bias become identity (1, 0) so the SAME program computes the
    fused result — the reference rewrites the op list instead
    (``inference_transpiler.py`` _fuse_bn); with XLA the arithmetic
    identity-BN folds away at compile time, so only the weights need
    transforming."""
    params = dict(variables.params)
    state = dict(variables.state)
    pairs = pairs if pairs is not None else find_conv_bn_pairs(variables)
    for conv_scope, bn_scope in pairs:
        w_name = f"{conv_scope}/w"
        gamma = params[f"{bn_scope}/scale"]
        beta = params[f"{bn_scope}/bias"]
        mean = state[f"{bn_scope}/moving_mean"]
        var = state[f"{bn_scope}/moving_variance"]
        inv_std = 1.0 / jnp.sqrt(var + epsilon)
        factor = gamma * inv_std  # [C_out]
        params[w_name] = params[w_name] * factor  # HWIO: broadcast over C_out
        b_name = f"{conv_scope}/b"
        old_b = params.get(b_name, jnp.zeros_like(beta))
        fused_b = (old_b - mean) * factor + beta
        # the fused bias lands in the conv bias if one exists, else in the
        # (now otherwise-identity) BN bias; BN becomes a no-op either way
        if b_name in params:
            params[b_name] = fused_b
            params[f"{bn_scope}/bias"] = jnp.zeros_like(beta)
        else:
            params[f"{bn_scope}/bias"] = fused_b
        params[f"{bn_scope}/scale"] = jnp.ones_like(gamma)
        state[f"{bn_scope}/moving_mean"] = jnp.zeros_like(mean)
        # var + epsilon must equal exactly 1 so the residual 1/sqrt is identity
        state[f"{bn_scope}/moving_variance"] = jnp.full_like(var, 1.0 - epsilon)
    ptlog.vlog(1, "fuse_batch_norm folded %d conv+bn pairs", len(pairs))
    return Variables(params=params, state=state)


def inference_optimize(
    model: Model,
    variables: Variables,
    fuse_bn: bool = True,
    epsilon: float = 1e-5,
):
    """Produce (predict_fn, optimized_variables) for deployment: eval mode
    (dropout stripped, BN uses moving stats), BN folded into conv weights.
    The ``program.inference_optimize()`` + InferenceTranspiler pipeline of
    the reference collapsed into a weight transform + is_train=False trace."""
    opt_vars = fuse_batch_norm(variables, epsilon=epsilon) if fuse_bn else variables

    def predict_fn(params_state: Variables, *batch):
        out, _ = model.apply(params_state, *batch, is_train=False)
        return out

    return predict_fn, opt_vars

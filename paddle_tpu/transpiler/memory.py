"""Memory optimization: rematerialization policies.

Reference: ``python/paddle/fluid/transpiler/memory_optimization_transpiler.py``
(``memory_optimize`` at :384 — CFG liveness + in-place var reuse;
``release_memory`` inserts delete_var ops). On TPU, XLA buffer assignment
already performs liveness analysis and in-place reuse, and the Executor's
eager GC has no analogue (no per-op scope). What remains profitable is
trading FLOPs for HBM via rematerialization — ``jax.checkpoint`` — which
subsumes the reference's var-reuse pass for activation memory.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

__all__ = ["memory_optimize", "release_memory", "POLICIES"]

# named remat policies (jax.checkpoint policies): what to KEEP (not recompute)
POLICIES = {
    # keep nothing: recompute everything in backward — min memory, max FLOPs
    "full_remat": None,
    # keep matmul/conv outputs (cheap to store, expensive to recompute):
    # the usual sweet spot for transformer blocks
    "save_dots": jax.checkpoint_policies.dots_saveable,
    "save_dots_with_no_batch_dims": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}
# To keep only activations tagged with jax.ad_checkpoint.checkpoint_name,
# pass the policy callable directly:
# memory_optimize(m, policy=jax.checkpoint_policies.save_only_these_names("x"))


def memory_optimize(
    fn_or_model,
    policy: Union[str, Callable, None] = "full_remat",
    prevent_cse: bool = True,
):
    """Wrap a traced function (or a Model's apply) in ``jax.checkpoint``
    with a named policy — the ``fluid.memory_optimize(program)`` API shape,
    re-targeted at activation rematerialization.

    Apply to the loss/model function BEFORE jit: under ``jax.grad`` the
    wrapped region's activations are recomputed in the backward pass instead
    of being kept live, the TPU replacement for the reference's var-reuse
    pass (its buffers are already reused by XLA).
    """
    if isinstance(policy, str):
        if policy not in POLICIES:
            raise KeyError(f"unknown remat policy {policy!r}; known: {sorted(POLICIES)}")
        policy = POLICIES[policy]

    def wrap(fn: Callable) -> Callable:
        return jax.checkpoint(fn, policy=policy, prevent_cse=prevent_cse)

    from paddle_tpu.framework import Model

    if isinstance(fn_or_model, Model):
        return _RematModel(fn_or_model, policy, prevent_cse)
    return wrap(fn_or_model)


class _RematModel:
    """Model wrapper whose apply() runs under jax.checkpoint.

    The checkpoint boundary must see params/state as EXPLICIT arguments —
    wrapping the raw layer fn would capture them via the framework's
    thread-local frame, and closed-over tracers don't get gradients through
    a remat boundary."""

    def __init__(self, inner, policy, prevent_cse: bool):
        self._inner = inner
        self._policy = policy
        self._prevent_cse = prevent_cse
        self.name = inner.name + "_remat"

    @property
    def param_info(self):
        return self._inner.param_info

    def init(self, rng=None, *args, **kwargs):
        return self._inner.init(rng, *args, **kwargs)

    def apply(self, variables, *args, rng=None, is_train: bool = False, **kwargs):
        from paddle_tpu.framework import Variables

        if isinstance(variables, Variables):
            params, state = variables.params, variables.state
        elif isinstance(variables, tuple) and len(variables) == 2:
            params, state = variables
        else:
            params, state = variables, {}

        def fn(p, s, r, *a):
            return self._inner.apply(
                Variables(p, s), *a, rng=r, is_train=is_train, **kwargs
            )

        wrapped = jax.checkpoint(fn, policy=self._policy, prevent_cse=self._prevent_cse)
        return wrapped(params, state, rng, *args)


def release_memory(*_args, **_kwargs) -> None:
    """No-op (API parity with ``fluid.release_memory``): the reference
    inserted delete_var ops to free dead tensors mid-program; XLA frees
    buffers at their last use automatically."""
    return None

"""Merged Chrome/Perfetto trace export.

The reference's ``DeviceTracer::GenProfile`` folded host annotations and
CUPTI kernel records into one timeline protobuf that ``tools/timeline.py``
converted for chrome://tracing. Here the merge happens directly into Chrome
Trace Event Format JSON, combining four sources on one timebase
(``time.perf_counter()`` microseconds):

* tracing spans (``ph:"X"``, with trace_id/span_id/parent_id in ``args``)
* host profiler spans from ``core.profiler`` (``ph:"X"``, cat ``host``)
* runlog events (``ph:"i"`` instants; epoch timestamps converted via the
  import-time clock offset)
* device HBM samples (``ph:"C"`` counter tracks per device)
* roofline achieved-rate samples from the kernel cost ledger
  (``ph:"C"`` counter tracks ``roofline.achieved_gflops_per_s`` /
  ``roofline.achieved_gbytes_per_s``, one series per kernel)

``validate_chrome_trace`` is the strict schema parser the smoke gate and
tests run over the artifact — same posture as
``observability.exporter.parse_text_exposition``: unknown phases, missing
required keys, or non-numeric timestamps fail loudly rather than rendering
as an empty timeline.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from paddle_tpu.core import profiler as prof
from paddle_tpu.tracing import context as _ctx
from paddle_tpu.tracing import memory as _mem

__all__ = ["chrome_trace_doc", "export_chrome_trace", "validate_chrome_trace"]

# Stable synthetic tids for the non-thread tracks. Host thread tracks are
# numbered from _FIRST_THREAD_TID up; the roofline track draws from that
# range through the same tid allocator (keyed by a sentinel raw tid that
# no real thread id can collide with).
_RUNLOG_TID = 0
_DEVICE_TID = 1
_FIRST_THREAD_TID = 2
_ROOFLINE_RAW_TID = -1


def chrome_trace_doc(
    runlog_path: Optional[str] = None,
    include_profiler: bool = True,
    include_device: bool = True,
    include_roofline: bool = True,
) -> dict:
    """Build the merged trace document. ``runlog_path`` defaults to the
    installed runlog's file (if any)."""
    pid = os.getpid()
    events: List[dict] = []
    tid_map: Dict[int, int] = {}
    thread_names: Dict[int, str] = {}

    def chrome_tid(raw_tid: int, name: str) -> int:
        if raw_tid not in tid_map:
            tid_map[raw_tid] = _FIRST_THREAD_TID + len(tid_map)
            thread_names[tid_map[raw_tid]] = name
        return tid_map[raw_tid]

    for span in _ctx.spans():
        if span.t1_us is None:
            continue
        args = {
            "trace_id": span.context.trace_id,
            "span_id": span.context.span_id,
            "parent_id": span.context.parent_id,
        }
        for k, v in span.attrs.items():
            args[k] = v if isinstance(v, (int, float, str, bool, type(None))) else repr(v)
        events.append({
            "name": span.name, "ph": "X", "cat": "tracing",
            "ts": span.t0_us, "dur": max(0.0, span.t1_us - span.t0_us),
            "pid": pid, "tid": chrome_tid(span.tid, span.thread_name),
            "args": args,
        })

    if include_profiler:
        prof_threads = prof.thread_names()
        for name, start_us, dur_us, raw_tid in prof.spans():
            events.append({
                "name": name, "ph": "X", "cat": "host",
                "ts": start_us, "dur": dur_us,
                "pid": pid,
                "tid": chrome_tid(raw_tid, prof_threads.get(raw_tid, f"thread-{raw_tid}")),
            })

    if runlog_path is None:
        from paddle_tpu.observability import runlog as _runlog

        log = _runlog.get_runlog()
        runlog_path = log.path if log is not None else None
    if runlog_path and os.path.exists(runlog_path):
        from paddle_tpu.observability import runlog as _runlog

        for ev in _runlog.read_runlog(runlog_path):
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            events.append({
                "name": str(ev.get("kind", "event")), "ph": "i", "cat": "runlog",
                "ts": _ctx.epoch_s_to_pc_us(float(ts)), "s": "p",
                "pid": pid, "tid": _RUNLOG_TID,
                "args": {k: v for k, v in ev.items() if k != "ts"},
            })

    if include_device:
        for t_us, dev_label, in_use in _mem.memory_history():
            events.append({
                "name": "device.hbm.bytes_in_use", "ph": "C", "cat": "device",
                "ts": t_us, "pid": pid, "tid": _DEVICE_TID,
                "args": {dev_label: in_use},
            })

    if include_roofline:
        from paddle_tpu.observability import roofline as _roofline

        samples = _roofline.history()
        if samples:
            tid = chrome_tid(_ROOFLINE_RAW_TID, "roofline")
            for t_us, kernel, flops_per_s, bytes_per_s in samples:
                events.append({
                    "name": "roofline.achieved_gflops_per_s", "ph": "C",
                    "cat": "roofline", "ts": t_us, "pid": pid, "tid": tid,
                    "args": {kernel: flops_per_s / 1e9},
                })
                events.append({
                    "name": "roofline.achieved_gbytes_per_s", "ph": "C",
                    "cat": "roofline", "ts": t_us, "pid": pid, "tid": tid,
                    "args": {kernel: bytes_per_s / 1e9},
                })

    meta_tracks = dict(thread_names)
    meta_tracks[_RUNLOG_TID] = "runlog"
    meta_tracks[_DEVICE_TID] = "device.hbm"
    for tid, name in sorted(meta_tracks.items()):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name},
        })

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"producer": "paddle_tpu.tracing"},
    }


def export_chrome_trace(
    path: str,
    runlog_path: Optional[str] = None,
    include_profiler: bool = True,
    include_device: bool = True,
    include_roofline: bool = True,
) -> str:
    """Write the merged trace atomically (tmp + rename, same contract as
    ``profiler.export_chrome_trace``) and return ``path``."""
    doc = chrome_trace_doc(
        runlog_path=runlog_path,
        include_profiler=include_profiler,
        include_device=include_device,
        include_roofline=include_roofline,
    )
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.rename(tmp, path)
    return path


_KNOWN_PHASES = ("X", "i", "C", "M")


def validate_chrome_trace(doc) -> Dict[str, int]:
    """Strictly validate a Chrome Trace Event Format document. Returns
    per-phase event counts on success; raises ``ValueError`` listing every
    violation otherwise. Accepts a dict (JSON-object form) or a JSON
    string."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    problems: List[str] = []
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        raise ValueError("chrome trace: document must be an object with a "
                         "'traceEvents' array")
    counts: Dict[str, int] = {}
    for i, ev in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: event is not an object")
            continue
        ph = ev.get("ph")
        if ph not in _KNOWN_PHASES:
            problems.append(f"{where}: unknown phase {ph!r}")
            continue
        counts[ph] = counts.get(ph, 0) + 1
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing/empty 'name'")
        if not isinstance(ev.get("pid"), int):
            problems.append(f"{where}: 'pid' must be an int")
        if not isinstance(ev.get("tid"), int):
            problems.append(f"{where}: 'tid' must be an int")
        if ph in ("X", "i", "C"):
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"{where}: '{ph}' event needs numeric 'ts'")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: 'X' event needs numeric 'dur' >= 0")
        if ph == "i":
            if ev.get("s") not in ("g", "p", "t"):
                problems.append(f"{where}: 'i' event needs scope 's' in g/p/t")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not args or not all(
                isinstance(v, (int, float)) for v in args.values()
            ):
                problems.append(
                    f"{where}: 'C' event needs non-empty numeric 'args'")
        if ph == "M":
            args = ev.get("args")
            if not isinstance(args, dict) or not isinstance(args.get("name"), str):
                problems.append(f"{where}: 'M' event needs args.name")
    if problems:
        raise ValueError(
            "invalid chrome trace (%d problem%s):\n  %s" % (
                len(problems), "s" if len(problems) != 1 else "",
                "\n  ".join(problems[:50]),
            )
        )
    return counts

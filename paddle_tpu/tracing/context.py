"""Span contexts and the in-process span store.

The reference framework correlated host and device activity with CUPTI
inside ``DeviceTracer`` (platform/device_tracer.h): RAII annotations on the
host side, kernel records on the device side, merged into one timeline
protobuf keyed by correlation id. The TPU port has no CUPTI; causality is
carried explicitly instead. A :class:`SpanContext` — trace_id/span_id/
parent_id, encodable as a W3C ``traceparent`` string — is attached to every
serving request at enqueue and to every training step at fetch, and each
pipeline stage opens a child span against it. The resulting span records
land in a bounded in-memory store that :mod:`paddle_tpu.tracing.export`
merges with profiler spans, runlog events, and device-memory samples into
one Chrome-trace document.

Two timestamp APIs cover the two shapes of instrumentation:

* ``start_span``/``start_trace`` — context managers for code the span
  encloses lexically (the trainer's step phases).
* ``record_span`` — explicit ``time.perf_counter()`` start/end for spans
  whose lifetime crosses threads (a serving request's queue wait is
  measured by the batcher thread against a timestamp taken by the
  submitter).

All span times share the profiler's timebase (``time.perf_counter()``
microseconds) so host spans from both systems line up in one export.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from paddle_tpu.core import locks
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.config import flags
from paddle_tpu.core.enforce import enforce

__all__ = [
    "SpanContext",
    "Span",
    "start_span",
    "start_trace",
    "record_span",
    "current_context",
    "spans",
    "spans_for_trace",
    "active_spans",
    "phase_totals",
    "validate_trace",
    "reset_tracing",
    "enable_tracing",
    "disable_tracing",
    "tracing_enabled",
    "pc_us_to_epoch_s",
    "epoch_s_to_pc_us",
]

# One-time offset between the span timebase (perf_counter) and wall-clock
# epoch seconds (the runlog timebase). Computed once at import so every
# conversion in a process is consistent; drift between the two clocks over
# a run is far below span-duration resolution.
_PC_TO_EPOCH_S = time.time() - time.perf_counter()


def pc_us_to_epoch_s(us: float) -> float:
    """perf_counter microseconds -> wall-clock epoch seconds."""
    return us / 1e6 + _PC_TO_EPOCH_S


def epoch_s_to_pc_us(ts: float) -> float:
    """wall-clock epoch seconds -> perf_counter microseconds."""
    return (ts - _PC_TO_EPOCH_S) * 1e6


_TRACEPARENT_VERSION = "00"


class SpanContext:
    """Identity of one span: which trace it belongs to, its own id, and its
    parent's id. Immutable; propagation creates children."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str] = None):
        enforce(
            len(trace_id) == 32 and _is_hex(trace_id),
            f"trace_id must be 32 lowercase hex chars, got {trace_id!r}",
        )
        enforce(
            len(span_id) == 16 and _is_hex(span_id),
            f"span_id must be 16 lowercase hex chars, got {span_id!r}",
        )
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def new_trace(cls) -> "SpanContext":
        """A fresh root context (no parent)."""
        return cls(os.urandom(16).hex(), os.urandom(8).hex())

    def child(self) -> "SpanContext":
        """A new context in the same trace, parented to this span."""
        return SpanContext(self.trace_id, os.urandom(8).hex(), self.span_id)

    def to_traceparent(self) -> str:
        """W3C trace-context ``traceparent`` header value
        (``00-<trace_id>-<span_id>-01``; sampled flag always set — the
        store is bounded, sampling-out happens by eviction, not at the
        source)."""
        return f"{_TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: str) -> "SpanContext":
        parts = header.strip().split("-")
        enforce(
            len(parts) == 4,
            f"malformed traceparent {header!r}: want version-traceid-spanid-flags",
        )
        version, trace_id, span_id, traceflags = parts
        enforce(
            len(version) == 2 and _is_hex(version) and version != "ff",
            f"malformed traceparent version {version!r}",
        )
        enforce(
            len(traceflags) == 2 and _is_hex(traceflags),
            f"malformed traceparent flags {traceflags!r}",
        )
        enforce(
            trace_id != "0" * 32 and span_id != "0" * 16,
            f"traceparent {header!r} has an all-zero id (invalid per spec)",
        )
        return cls(trace_id, span_id)

    def __repr__(self):
        return (
            f"SpanContext(trace_id={self.trace_id!r}, span_id={self.span_id!r}, "
            f"parent_id={self.parent_id!r})"
        )

    def __eq__(self, other):
        return (
            isinstance(other, SpanContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
            and self.parent_id == other.parent_id
        )

    def __hash__(self):
        return hash((self.trace_id, self.span_id, self.parent_id))


def _is_hex(s: str) -> bool:
    return all(c in "0123456789abcdef" for c in s)


class Span:
    """One finished-or-open span record. Mutable while open (``set`` adds
    attributes, ``cancel`` discards it); frozen in the store once closed."""

    __slots__ = ("name", "context", "t0_us", "t1_us", "attrs", "tid",
                 "thread_name", "_cancelled")

    def __init__(self, name: str, context: SpanContext, t0_us: float,
                 attrs: Optional[dict] = None):
        self.name = name
        self.context = context
        self.t0_us = t0_us
        self.t1_us: Optional[float] = None
        self.attrs = dict(attrs) if attrs else {}
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name
        self._cancelled = False

    @property
    def duration_s(self) -> Optional[float]:
        if self.t1_us is None:
            return None
        return (self.t1_us - self.t0_us) / 1e6

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def cancel(self) -> None:
        """Discard this span on exit (e.g. the data-wait that hit
        end-of-epoch instead of yielding a batch)."""
        self._cancelled = True

    def __repr__(self):
        dur = f"{self.duration_s * 1e3:.3f}ms" if self.t1_us is not None else "open"
        return f"Span({self.name!r}, {dur}, {self.context.trace_id[:8]}…)"


# --------------------------------------------------------------------------
# Store + thread-local span stack
# --------------------------------------------------------------------------

_lock = locks.Lock("tracing.spans")
_store: "deque[Span]" = deque(maxlen=max(1, int(flags().trace_max_spans)))
_enabled = True
_tls = threading.local()
# Open spans across ALL threads, keyed by id(span) — the watchdog dumps this
# on a stall to show what every thread was inside when it wedged.
_open: Dict[int, Span] = {}


def tracing_enabled() -> bool:
    return _enabled


def enable_tracing() -> None:
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def reset_tracing() -> None:
    """Clear the span store (open spans in flight are unaffected — they
    simply land in the fresh store when they close)."""
    with _lock:
        _store.clear()


def _stack() -> List[Span]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_context() -> Optional[SpanContext]:
    """The SpanContext of this thread's innermost open span, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1].context if st else None


def _resolve_parent(parent) -> Optional[SpanContext]:
    if parent is None:
        return current_context()
    if isinstance(parent, Span):
        return parent.context
    enforce(
        isinstance(parent, SpanContext),
        f"parent must be a Span or SpanContext, got {type(parent).__name__}",
    )
    return parent


def _commit(span: Span) -> None:
    with _lock:
        if len(_store) == _store.maxlen:
            prof.inc_counter("tracing.spans_evicted")
        _store.append(span)


class _SpanScope:
    """Context manager returned by start_span/start_trace."""

    __slots__ = ("_span",)

    def __init__(self, span: Span):
        self._span = span

    def __enter__(self) -> Span:
        _stack().append(self._span)
        _open[id(self._span)] = self._span
        return self._span

    def __exit__(self, exc_type, exc, tb):
        span = self._span
        st = _stack()
        # Tolerate exotic unwind orders (generators finalized late): remove
        # this span wherever it sits rather than blindly popping the top.
        for i in range(len(st) - 1, -1, -1):
            if st[i] is span:
                del st[i]
                break
        _open.pop(id(span), None)
        span.t1_us = time.perf_counter() * 1e6
        if exc_type is not None:
            span.attrs.setdefault("status", "error")
            span.attrs.setdefault("exception", exc_type.__name__)
        if _enabled and not span._cancelled:
            _commit(span)
        return False


def start_span(name: str, parent=None, **attrs) -> _SpanScope:
    """Open a span as a child of ``parent`` (a Span or SpanContext), or of
    this thread's current span, or as a new root if neither exists. Usable
    as ``with start_span("trainer.h2d") as sp: ...``."""
    pctx = _resolve_parent(parent)
    ctx = pctx.child() if pctx is not None else SpanContext.new_trace()
    return _SpanScope(Span(name, ctx, time.perf_counter() * 1e6, attrs))


def start_trace(name: str, **attrs) -> _SpanScope:
    """Open a new ROOT span (fresh trace_id) regardless of any span already
    open on this thread — one trace per training step / per request."""
    return _SpanScope(Span(name, SpanContext.new_trace(), time.perf_counter() * 1e6, attrs))


def record_span(
    name: str,
    t0: float,
    t1: float,
    parent=None,
    context: Optional[SpanContext] = None,
    **attrs,
) -> Optional[SpanContext]:
    """Record an already-measured span. ``t0``/``t1`` are
    ``time.perf_counter()`` seconds. With ``context=`` the span is recorded
    under that exact identity (used for a request's root span, whose context
    was minted at submit time); otherwise a child of ``parent`` (or of the
    current thread span) is minted. Returns the span's context, or None when
    tracing is disabled."""
    if not _enabled:
        return None
    enforce(t1 >= t0, f"record_span({name!r}): t1 < t0 ({t1} < {t0})")
    if context is not None:
        ctx = context
    else:
        pctx = _resolve_parent(parent)
        ctx = pctx.child() if pctx is not None else SpanContext.new_trace()
    span = Span(name, ctx, t0 * 1e6, attrs)
    span.t1_us = t1 * 1e6
    _commit(span)
    return ctx


def spans() -> List[Span]:
    """Snapshot of the span store (oldest first)."""
    with _lock:
        return list(_store)


def spans_for_trace(trace_id: str) -> List[Span]:
    """All stored spans of one trace, start-time ordered."""
    with _lock:
        got = [s for s in _store if s.context.trace_id == trace_id]
    got.sort(key=lambda s: s.t0_us)
    return got


def active_spans() -> List[Span]:
    """Currently-open spans across all threads (stall diagnostics)."""
    return list(_open.values())


def phase_totals(names: Iterable[str]) -> Dict[str, float]:
    """Total seconds spent in each named span across the store — the
    per-phase breakdown bench.py reports (data_wait/h2d/compile/step)."""
    want = set(names)
    totals = {n: 0.0 for n in want}
    with _lock:
        for s in _store:
            if s.name in want and s.t1_us is not None:
                totals[s.name] += (s.t1_us - s.t0_us) / 1e6
    return totals


# Child spans may overshoot their parent by measurement skew: the parent's
# endpoints and the child's are captured by different perf_counter() calls,
# sometimes on different threads. Tolerate a small slack before calling a
# tree malformed.
_CONTAINMENT_SLACK_US = 500.0


def validate_trace(trace_spans: List[Span],
                   multi_engine: bool = False) -> List[str]:
    """Structural checks over one trace's spans. Returns a list of problem
    strings — empty means the trace reconstructs end-to-end: exactly one
    root, every parent_id resolves, every span closed and monotonic
    (t1 >= t0), and children sit inside their parent's interval.

    With ``multi_engine=True`` (fleet traces: handoff, migration, crash
    replay) the containment check is skipped for parent/child pairs whose
    ``engine`` attrs differ: a migrated request's pre-adoption spans ran
    on a different engine, before the adopting engine's root interval
    opened — cross-engine edges carry causality, not wall-clock
    containment. Identity checks (one trace id, one root, no orphaned
    parent_ids, closed + monotonic spans) still apply in full."""
    problems: List[str] = []
    if not trace_spans:
        return ["trace has no spans"]
    tids = {s.context.trace_id for s in trace_spans}
    if len(tids) != 1:
        problems.append(f"spans from {len(tids)} different traces: {sorted(tids)}")
    by_id = {s.context.span_id: s for s in trace_spans}
    roots = [s for s in trace_spans if s.context.parent_id is None]
    if len(roots) != 1:
        problems.append(
            f"want exactly 1 root span, got {len(roots)}: "
            f"{[s.name for s in roots]}"
        )
    for s in trace_spans:
        if s.t1_us is None:
            problems.append(f"span {s.name!r} never closed")
            continue
        if s.t1_us < s.t0_us:
            problems.append(f"span {s.name!r} not monotonic: t1 < t0")
        pid = s.context.parent_id
        if pid is None:
            continue
        parent = by_id.get(pid)
        if parent is None:
            problems.append(f"span {s.name!r} has unresolved parent_id {pid}")
            continue
        if parent.t1_us is None:
            continue
        if multi_engine and s.attrs.get("engine") != parent.attrs.get("engine"):
            continue
        if (s.t0_us < parent.t0_us - _CONTAINMENT_SLACK_US
                or s.t1_us > parent.t1_us + _CONTAINMENT_SLACK_US):
            problems.append(
                f"span {s.name!r} [{s.t0_us:.0f},{s.t1_us:.0f}] escapes parent "
                f"{parent.name!r} [{parent.t0_us:.0f},{parent.t1_us:.0f}]"
            )
    return problems

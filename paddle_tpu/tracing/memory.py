"""Per-device HBM telemetry: live/peak gauges + compiled-executable peaks.

The reference surfaced GPU memory through ``FLAGS_benchmark`` prints in the
executor (executor.cc:399-401) and CUPTI counters; on TPU the equivalents
are PJRT's per-device ``memory_stats()`` (live/peak/limit HBM bytes) and
XLA's per-executable ``memory_analysis()`` (what one compiled program will
need at peak). Both are sampled here into ``device.hbm.*`` gauge families
so an impending OOM is visible on the ``/metrics`` scrape *before* the
allocator raises, and a bounded history of samples feeds counter tracks in
the merged Chrome-trace export.

CPU backends (tests, laptops) return no ``memory_stats()``; the sampler
falls back to summing ``nbytes`` over ``jax.live_arrays()`` per device and
tracks its own running peak, so the gauge families exist — with honest
``source`` labels — on every platform.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from paddle_tpu.core import locks
from paddle_tpu.core import profiler as prof

__all__ = [
    "device_label",
    "sample_device_memory",
    "record_executable_memory",
    "memory_history",
    "reset_memory_telemetry",
]

_lock = locks.Lock("tracing.memory")
# live-arrays fallback needs its own running peak — PJRT tracks the real
# one only when memory_stats() exists
_live_peak: Dict[str, int] = {}
# bounded (t_pc_us, device_label, bytes_in_use) history for the trace
# export's counter track
_history: "deque[tuple]" = deque(maxlen=4096)


def device_label(dev) -> str:
    """Stable metric label for one jax device, e.g. ``tpu:0``."""
    return f"{dev.platform}:{dev.id}"


def _live_bytes_by_device(devices) -> Dict[str, int]:
    """Fallback accounting: sum nbytes of every live jax array per device."""
    import jax

    want = {device_label(d): 0 for d in devices}
    try:
        arrays = jax.live_arrays()
    except Exception:
        return want
    for a in arrays:
        try:
            for d in a.devices():
                lbl = device_label(d)
                if lbl in want:
                    # sharded arrays: attribute an even split per device
                    want[lbl] += int(a.nbytes) // max(1, len(a.devices()))
        except Exception:
            continue
    return want


def sample_device_memory(devices=None) -> List[dict]:
    """Sample live/peak/limit HBM bytes for each device into the
    ``device.hbm.*`` gauge families (labeled ``device=...``). Returns the
    per-device samples. Called per training step and by the smoke gate."""
    import jax

    devices = list(devices) if devices is not None else jax.local_devices()
    now_us = time.perf_counter() * 1e6
    fallback: Optional[Dict[str, int]] = None
    samples = []
    for dev in devices:
        lbl = device_label(dev)
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            in_use = int(stats.get("bytes_in_use", 0))
            peak = int(stats.get("peak_bytes_in_use", in_use))
            limit = stats.get("bytes_limit")
            source = "memory_stats"
        else:
            if fallback is None:
                fallback = _live_bytes_by_device(devices)
            in_use = fallback.get(lbl, 0)
            with _lock:
                peak = max(_live_peak.get(lbl, 0), in_use)
                _live_peak[lbl] = peak
            limit = None
            source = "live_arrays"
        labels = {"device": lbl}
        prof.set_gauge("device.hbm.bytes_in_use", float(in_use), labels=labels)
        prof.set_gauge("device.hbm.peak_bytes_in_use", float(peak), labels=labels)
        if limit is not None:
            prof.set_gauge("device.hbm.bytes_limit", float(limit), labels=labels)
        with _lock:
            _history.append((now_us, lbl, in_use))
        samples.append({
            "device": lbl,
            "bytes_in_use": in_use,
            "peak_bytes_in_use": peak,
            "bytes_limit": limit,
            "source": source,
        })
    return samples


def record_executable_memory(compiled, target: str) -> Optional[dict]:
    """Record one compiled executable's memory footprint from XLA's
    ``memory_analysis()`` into ``device.hbm.executable_*`` gauges (labeled
    ``target=...``). On backends that report no peak (CPU), the peak is
    reconstructed as argument + output + temp sizes. Returns the breakdown,
    or None when the executable exposes no analysis."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None

    def _get(attr):
        v = getattr(mem, attr, None)
        try:
            return int(v) if v is not None else None
        except (TypeError, ValueError):
            return None

    arg = _get("argument_size_in_bytes") or 0
    out = _get("output_size_in_bytes") or 0
    tmp = _get("temp_size_in_bytes") or 0
    gen = _get("generated_code_size_in_bytes") or 0
    peak = _get("peak_memory_in_bytes")
    if not peak:
        peak = arg + out + tmp
    labels = {"target": target}
    prof.set_gauge("device.hbm.executable_peak_bytes", float(peak), labels=labels)
    prof.set_gauge("device.hbm.executable_temp_bytes", float(tmp), labels=labels)
    prof.set_gauge("device.hbm.executable_argument_bytes", float(arg), labels=labels)
    prof.set_gauge("device.hbm.executable_output_bytes", float(out), labels=labels)
    return {
        "target": target,
        "peak_bytes": peak,
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "generated_code_bytes": gen,
    }


def memory_history() -> List[tuple]:
    """Snapshot of (t_pc_us, device_label, bytes_in_use) samples for the
    merged trace export's per-device counter track."""
    with _lock:
        return list(_history)


def reset_memory_telemetry() -> None:
    with _lock:
        _live_peak.clear()
        _history.clear()

"""Straggler detection over per-replica / per-step durations.

On a TPU pod one slow participant sets the pace for everyone: a serving
replica with a flaky host drags every batch routed to it, a device whose
steps degrade throttles the whole data-parallel step (GDP, arxiv
1910.01578, builds its placement decisions on exactly this per-device
timing attribution). The detector consumes the same durations the tracing
spans measure and flags two shapes of skew:

* **spatial** — several keys report the same kind of duration (one per
  serving replica): a key whose recent mean exceeds the median of all key
  means by ``ratio`` is a straggler relative to its peers.
* **temporal** — only one key reports (a single-host trainer's step time):
  an observation exceeding the key's own recent median by ``ratio`` is a
  straggler relative to its past.

Flags are exported three ways so every consumer sees them: a
``tracing.straggler.flags_total`` counter and ``tracing.straggler.skew_ratio``
gauge (labeled group/key), a runlog ``straggler`` event (which carries the
active trace ids when flagged inside a span), and a ``warn_once`` log line
per (group, key).
"""

from __future__ import annotations

import statistics
import threading
from collections import deque
from typing import Dict, Optional

from paddle_tpu.core import logging as ptlog
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.config import flags
from paddle_tpu.core.enforce import enforce
from paddle_tpu.observability import runlog

__all__ = ["StragglerDetector"]


class StragglerDetector:
    """Sliding-window skew detector. ``record(key, seconds)`` returns True
    when that observation was flagged. Thread-safe — serving worker threads
    record concurrently."""

    def __init__(
        self,
        group: str,
        ratio: Optional[float] = None,
        window: int = 32,
        min_samples: int = 5,
    ):
        enforce(window >= 2, f"window must be >= 2, got {window}")
        enforce(min_samples >= 2, f"min_samples must be >= 2, got {min_samples}")
        self.group = group
        self.ratio = float(ratio if ratio is not None else flags().straggler_ratio)
        enforce(self.ratio > 1.0, f"straggler ratio must be > 1.0, got {self.ratio}")
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.flagged: Dict[str, int] = {}  # key -> flag count
        self._lock = threading.Lock()
        self._series: Dict[str, deque] = {}

    def record(self, key: str, seconds: float) -> bool:
        """Record one duration for ``key``; returns True if it was flagged
        as a straggler."""
        if seconds < 0:
            return False
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = deque(maxlen=self.window)
            series.append(float(seconds))
            skew, mode = self._skew_locked(key, float(seconds))
        if skew is None or skew <= self.ratio:
            return False
        self._flag(key, seconds, skew, mode)
        return True

    def _skew_locked(self, key: str, latest: float):
        """Skew ratio for the latest observation of ``key``, or (None, _)
        when there is not enough signal yet."""
        peers = {
            k: s for k, s in self._series.items() if len(s) >= self.min_samples
        }
        if len(peers) >= 2 and key in peers:
            # spatial: this key's recent mean against the median of all
            # keys' means — median (not mean) so one straggler cannot drag
            # the baseline up and hide itself.
            means = {k: sum(s) / len(s) for k, s in peers.items()}
            baseline = statistics.median(means.values())
            if baseline <= 0:
                return None, "spatial"
            return means[key] / baseline, "spatial"
        series = self._series[key]
        if len(series) < self.min_samples:
            return None, "temporal"
        # temporal: the latest observation against this key's own recent
        # median (excluding the latest, so a spike cannot inflate its own
        # baseline).
        history = list(series)[:-1]
        baseline = statistics.median(history)
        if baseline <= 0:
            return None, "temporal"
        return latest / baseline, "temporal"

    def _flag(self, key: str, seconds: float, skew: float, mode: str) -> None:
        with self._lock:
            self.flagged[key] = self.flagged.get(key, 0) + 1
        labels = {"group": self.group, "key": key}
        prof.inc_counter("tracing.straggler.flags_total", labels=labels)
        prof.set_gauge("tracing.straggler.skew_ratio", round(skew, 4), labels=labels)
        runlog.emit(
            "straggler",
            group=self.group,
            key=key,
            mode=mode,
            seconds=round(seconds, 6),
            skew_ratio=round(skew, 4),
            threshold=self.ratio,
        )
        ptlog.warn_once(
            f"straggler[{self.group}/{key}]",
            "straggler detected: %s %s took %.4fs — %.2fx the %s baseline "
            "(threshold %.2fx)",
            self.group, key, seconds, skew, mode, self.ratio,
        )

    def snapshot(self) -> Dict[str, dict]:
        """Per-key window stats (count/mean/max) plus flag counts."""
        with self._lock:
            out = {}
            for k, s in self._series.items():
                vals = list(s)
                out[k] = {
                    "count": len(vals),
                    "mean_s": sum(vals) / len(vals) if vals else 0.0,
                    "max_s": max(vals) if vals else 0.0,
                    "flags": self.flagged.get(k, 0),
                }
            return out

"""Straggler detection over per-replica / per-step durations.

On a TPU pod one slow participant sets the pace for everyone: a serving
replica with a flaky host drags every batch routed to it, a device whose
steps degrade throttles the whole data-parallel step (GDP, arxiv
1910.01578, builds its placement decisions on exactly this per-device
timing attribution). The detector consumes the same durations the tracing
spans measure and flags two shapes of skew:

* **spatial** — several keys report the same kind of duration (one per
  serving replica): a key whose recent mean exceeds the median of all key
  means by ``ratio`` is a straggler relative to its peers.
* **temporal** — only one key reports (a single-host trainer's step time):
  an observation exceeding the key's own recent median by ``ratio`` is a
  straggler relative to its past.

The decision math lives in the shared
:class:`paddle_tpu.watch.detectors.SkewDetector` core (so the metric
watcher, tests, and this shell all agree on what "skewed" means); this
module keeps the reporting. Flags are exported three ways so every
consumer sees them: a ``tracing.straggler.flags_total`` counter and
``tracing.straggler.skew_ratio`` gauge (labeled group/key), a runlog
``straggler`` event (which carries the active trace ids when flagged
inside a span), and a ``warn_once`` log line per (group, key).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from paddle_tpu.core import locks
from paddle_tpu.core import logging as ptlog
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.config import flags
from paddle_tpu.observability import runlog
from paddle_tpu.watch.detectors import SkewDetector

__all__ = ["StragglerDetector"]


class StragglerDetector:
    """Sliding-window skew detector. ``record(key, seconds)`` returns True
    when that observation was flagged. Thread-safe — serving worker threads
    record concurrently."""

    def __init__(
        self,
        group: str,
        ratio: Optional[float] = None,
        window: int = 32,
        min_samples: int = 5,
    ):
        self.group = group
        self._core = SkewDetector(
            ratio=float(ratio if ratio is not None else flags().straggler_ratio),
            window=window,
            min_samples=min_samples,
        )
        self.flagged: Dict[str, int] = {}  # key -> flag count
        self._lock = locks.Lock("tracing.straggler")

    @property
    def ratio(self) -> float:
        return self._core.ratio

    @property
    def window(self) -> int:
        return self._core.window

    @property
    def min_samples(self) -> int:
        return self._core.min_samples

    def record(self, key: str, seconds: float) -> bool:
        """Record one duration for ``key``; returns True if it was flagged
        as a straggler."""
        result = self._core.record(key, seconds)
        if result is None or not result.flagged:
            return False
        self._flag(key, seconds, result.score, result.mode)
        return True

    def _flag(self, key: str, seconds: float, skew: float, mode: str) -> None:
        with self._lock:
            self.flagged[key] = self.flagged.get(key, 0) + 1
        labels = {"group": self.group, "key": key}
        prof.inc_counter("tracing.straggler.flags_total", labels=labels)
        prof.set_gauge("tracing.straggler.skew_ratio", round(skew, 4), labels=labels)
        runlog.emit(
            "straggler",
            group=self.group,
            key=key,
            mode=mode,
            seconds=round(seconds, 6),
            skew_ratio=round(skew, 4),
            threshold=self.ratio,
        )
        ptlog.warn_once(
            f"straggler[{self.group}/{key}]",
            "straggler detected: %s %s took %.4fs — %.2fx the %s baseline "
            "(threshold %.2fx)",
            self.group, key, seconds, skew, mode, self.ratio,
        )

    def snapshot(self) -> Dict[str, dict]:
        """Per-key window stats (count/mean/max) plus flag counts."""
        out = self._core.window_stats()
        with self._lock:
            for k, stats in out.items():
                stats["flags"] = self.flagged.get(k, 0)
        return out

"""Per-request token-latency waterfall: TTFT, per-token TPOT, jitter.

The decode engine's spans time *iterations* (a prefill chunk, a decode
step, a verify step); users experience *tokens*. This module converts one
into the other, per request:

- **TTFT** — submit → first generated token (queue wait + prefill);
- **TPOT** — per-token latency after the first. Speculation-aware by
  construction: the engine reports each iteration as "``n`` tokens landed
  at ``t``", and an iteration that landed ``n`` tokens ``dt`` after the
  previous one books ``n`` TPOT samples of ``dt/n`` each — a verify step
  that accepts 4 tokens books 4 samples, so spec-on and spec-off runs
  produce one sample per generated token and stay comparable;
- **jitter** — the population stdev of a request's TPOT samples.

The engine calls :func:`start` at submit, :func:`on_tokens` once per
iteration that appended tokens, and :func:`finish` at terminal state;
:func:`on_tokens` returns the booked ``(ttft_s, tpot_samples)`` so the
caller can feed the ``serving.decode.ttft_seconds`` /
``serving.decode.tpot_seconds`` histogram families without re-deriving
them. Finished waterfall docs stay retrievable (bounded, oldest evicted)
at the exporter's ``/waterfall/<rid>`` endpoint.

All timestamps are ``time.perf_counter()`` seconds — the tracing
timebase — so waterfall events line up with spans in the merged trace.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from paddle_tpu.core import locks

__all__ = [
    "MAX_DOCS",
    "start",
    "on_tokens",
    "finish",
    "doc",
    "rids",
    "reset",
]

# bounded doc store: enough to inspect a burst, small enough to forget
MAX_DOCS = 1024


class _Doc:
    __slots__ = ("rid", "meta", "t_submit_pc", "t_first_token_pc",
                 "t_last_token_pc", "ttft_s", "tpot_s", "events",
                 "tokens", "finished", "reason")

    def __init__(self, rid: str, t_submit_pc: float, meta: Dict[str, str]):
        self.rid = rid
        self.meta = meta
        self.t_submit_pc = t_submit_pc
        self.t_first_token_pc: Optional[float] = None
        self.t_last_token_pc: Optional[float] = None
        self.ttft_s: Optional[float] = None
        self.tpot_s: List[float] = []
        self.events: List[dict] = []
        self.tokens = 0
        self.finished = False
        self.reason: Optional[str] = None


_lock = locks.Lock("tracing.waterfall")
_docs: "OrderedDict[str, _Doc]" = OrderedDict()


def start(rid: str, t_submit_pc: float, **meta) -> None:
    """Open a waterfall for one request at its submit timestamp."""
    if not rid:
        return
    with _lock:
        _docs.pop(rid, None)
        while len(_docs) >= MAX_DOCS:
            _docs.popitem(last=False)
        _docs[rid] = _Doc(rid, float(t_submit_pc),
                          {k: str(v) for k, v in meta.items() if v})


def on_tokens(rid: str, t_pc: float, n: int,
              phase: str = "decode") -> Tuple[Optional[float], List[float]]:
    """Book ``n`` tokens landing at ``t_pc`` (one engine iteration).
    Returns ``(ttft_s, tpot_samples)`` — ``ttft_s`` is non-None only on
    the iteration that produced the request's first token; every token
    after the first yields exactly one TPOT sample (``dt/n`` each for an
    ``n``-token iteration). Unknown rids are ignored."""
    if n <= 0:
        return None, []
    with _lock:
        d = _docs.get(rid)
        if d is None or d.finished:
            return None, []
        t_pc = float(t_pc)
        ttft: Optional[float] = None
        samples: List[float] = []
        remaining = n
        if d.t_first_token_pc is None:
            d.t_first_token_pc = t_pc
            ttft = d.ttft_s = max(0.0, t_pc - d.t_submit_pc)
            remaining -= 1
        if remaining > 0:
            # dt since the previous token-landing iteration, split evenly
            # over this iteration's tokens (the speculation contract)
            dt = max(0.0, t_pc - (d.t_last_token_pc
                                  if d.t_last_token_pc is not None
                                  else d.t_first_token_pc))
            samples = [dt / remaining] * remaining
            d.tpot_s.extend(samples)
        d.t_last_token_pc = t_pc
        d.tokens += n
        d.events.append({"t_pc": t_pc, "n": n, "phase": phase})
        return ttft, samples


def finish(rid: str, t_pc: float, reason: str) -> None:
    """Mark a request's waterfall terminal (eos / length / cancel / ...)."""
    with _lock:
        d = _docs.get(rid)
        if d is None or d.finished:
            return
        d.finished = True
        d.reason = str(reason)
        d.events.append({"t_pc": float(t_pc), "n": 0, "phase": "finish"})


def _stats(samples: List[float]) -> dict:
    if not samples:
        return {"count": 0, "mean_s": None, "p50_s": None, "p99_s": None,
                "jitter_s": None}
    s = sorted(samples)
    n = len(s)
    mean = sum(s) / n
    var = sum((x - mean) ** 2 for x in s) / n
    return {
        "count": n,
        "mean_s": mean,
        "p50_s": s[min(n - 1, int(0.50 * n))],
        "p99_s": s[min(n - 1, int(0.99 * n))],
        "jitter_s": math.sqrt(var),
    }


def doc(rid: str) -> Optional[dict]:
    """One request's waterfall document (None when unknown/evicted)."""
    with _lock:
        d = _docs.get(rid)
        if d is None:
            return None
        return {
            "rid": d.rid,
            **d.meta,
            "t_submit_pc": d.t_submit_pc,
            "t_first_token_pc": d.t_first_token_pc,
            "t_last_token_pc": d.t_last_token_pc,
            "ttft_s": d.ttft_s,
            "tokens": d.tokens,
            "tpot_s": list(d.tpot_s),
            "tpot": _stats(d.tpot_s),
            "events": [dict(e) for e in d.events],
            "finished": d.finished,
            "reason": d.reason,
        }


def rids(finished_only: bool = False) -> List[str]:
    """Known request ids, oldest first."""
    with _lock:
        return [r for r, d in _docs.items()
                if d.finished or not finished_only]


def reset() -> None:
    with _lock:
        _docs.clear()

"""paddle_tpu.tracing — end-to-end request/step tracing.

Causally-linked spans with W3C-traceparent-style propagated IDs across the
whole stack (serving queue → batcher → dispatch → device execution → reply;
trainer data-wait → h2d → compile → step → checkpoint), per-device HBM
telemetry, straggler detection, and a merged Chrome/Perfetto trace export.
See README "Tracing".

Importing this package registers a runlog context provider: every runlog
event emitted inside an active span automatically gains ``trace_id``/
``span_id`` fields, so fault/rollback/straggler lines correlate with the
span tree without call-site changes.
"""

from __future__ import annotations

from paddle_tpu.tracing import export, memory, straggler, waterfall  # noqa: F401
from paddle_tpu.tracing.context import (  # noqa: F401
    Span,
    SpanContext,
    active_spans,
    current_context,
    disable_tracing,
    enable_tracing,
    epoch_s_to_pc_us,
    pc_us_to_epoch_s,
    phase_totals,
    record_span,
    reset_tracing,
    spans,
    spans_for_trace,
    start_span,
    start_trace,
    tracing_enabled,
    validate_trace,
)
from paddle_tpu.tracing.export import (  # noqa: F401
    chrome_trace_doc,
    export_chrome_trace,
    validate_chrome_trace,
)
from paddle_tpu.tracing.memory import (  # noqa: F401
    device_label,
    memory_history,
    record_executable_memory,
    reset_memory_telemetry,
    sample_device_memory,
)
from paddle_tpu.tracing.straggler import StragglerDetector  # noqa: F401

__all__ = [
    "SpanContext",
    "Span",
    "start_span",
    "start_trace",
    "record_span",
    "current_context",
    "spans",
    "spans_for_trace",
    "active_spans",
    "phase_totals",
    "validate_trace",
    "reset_tracing",
    "enable_tracing",
    "disable_tracing",
    "tracing_enabled",
    "pc_us_to_epoch_s",
    "epoch_s_to_pc_us",
    "chrome_trace_doc",
    "export_chrome_trace",
    "validate_chrome_trace",
    "sample_device_memory",
    "record_executable_memory",
    "memory_history",
    "reset_memory_telemetry",
    "device_label",
    "StragglerDetector",
    "export",
    "memory",
    "straggler",
    "waterfall",
]


def _runlog_trace_context():
    ctx = current_context()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def _install_runlog_provider() -> None:
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.observability import runlog as _runlog

    _runlog.set_context_provider(_runlog_trace_context)
    _metrics.declare_tracing_families()


_install_runlog_provider()

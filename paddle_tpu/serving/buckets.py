"""Shape buckets: the AOT-compile contract between requests and the engine.

Under XLA every distinct argument shape is a distinct executable, so a
serving engine that jits whatever shape arrives recompiles (20-40 s on TPU)
in the latency path of live traffic. The standard fix (TVM's
shape-specialized compiled functions, arxiv 1802.04799) is a finite set of
padded shape buckets compiled ahead of time: a request is rounded UP to the
smallest bucket that fits, padded with zeros, and the result rows are
sliced back out.

Buckets are derived from :class:`paddle_tpu.reader.feeder.FeedSpec`:

- fixed per-sample dims come straight from ``spec.shape``;
- ragged dims (``None`` in ``spec.shape``, or ``spec.ragged``) are rounded
  up to a configured ``length_buckets`` entry;
- the batch (row) dim is rounded up to a ``batch_buckets`` entry
  (default: powers of two up to ``max_batch_size``).

The full signature set is the cross product of ragged-dim buckets — one
compiled executable per (signature, batch bucket) pair, all warmed at
engine startup.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.core.enforce import EnforceError, enforce
from paddle_tpu.reader.feeder import FeedSpec

__all__ = ["ShapeBuckets"]

# per-slot per-sample padded shape, e.g. ((16, 4), (1,))
Signature = Tuple[Tuple[int, ...], ...]


def _pow2_buckets(max_value: int) -> Tuple[int, ...]:
    out = []
    b = 1
    while b < max_value:
        out.append(b)
        b *= 2
    out.append(max_value)
    return tuple(out)


class ShapeBuckets:
    """Maps request shapes to the finite padded-shape vocabulary."""

    def __init__(
        self,
        feed_specs: Sequence[FeedSpec],
        max_batch_size: int,
        batch_buckets: Optional[Sequence[int]] = None,
        length_buckets: Optional[Sequence[int]] = None,
    ):
        enforce(max_batch_size >= 1, "max_batch_size must be >= 1")
        self.specs = list(feed_specs)
        self.max_batch_size = int(max_batch_size)
        self.batch_buckets: Tuple[int, ...] = tuple(
            sorted(set(int(b) for b in batch_buckets))
            if batch_buckets
            else _pow2_buckets(self.max_batch_size)
        )
        enforce(
            self.batch_buckets[-1] == self.max_batch_size,
            "largest batch bucket must equal max_batch_size "
            f"({self.batch_buckets[-1]} != {self.max_batch_size})",
        )
        self.length_buckets: Optional[Tuple[int, ...]] = (
            tuple(sorted(set(int(b) for b in length_buckets)))
            if length_buckets
            else None
        )
        # which dims of each slot's per-sample shape are bucketable
        self._ragged_dims: List[Tuple[int, ...]] = []
        for spec in self.specs:
            dims = spec.ragged_dims()
            self._ragged_dims.append(dims)
            if dims and self.length_buckets is None:
                raise EnforceError(
                    f"feed slot {spec.name!r} has ragged dims {dims} but no "
                    "length_buckets were configured — the engine cannot "
                    "enumerate its compile set"
                )

    @property
    def has_ragged(self) -> bool:
        return any(self._ragged_dims)

    def _round_length(self, n: int) -> int:
        assert self.length_buckets is not None
        for b in self.length_buckets:
            if n <= b:
                return b
        raise EnforceError(
            f"sequence length {n} exceeds the largest length bucket "
            f"{self.length_buckets[-1]}"
        )

    def batch_bucket(self, rows: int) -> int:
        """Smallest batch bucket that holds ``rows``."""
        enforce(
            1 <= rows <= self.max_batch_size,
            f"rows={rows} outside [1, {self.max_batch_size}]",
        )
        for b in self.batch_buckets:
            if rows <= b:
                return b
        return self.batch_buckets[-1]

    def signature(self, sample_shapes: Sequence[Tuple[int, ...]]) -> Signature:
        """Round per-sample shapes up to the bucket vocabulary, validating
        fixed dims against the FeedSpecs."""
        enforce(
            len(sample_shapes) == len(self.specs),
            f"expected {len(self.specs)} feed slots, got {len(sample_shapes)}",
        )
        sig = []
        for spec, ragged, shape in zip(self.specs, self._ragged_dims, sample_shapes):
            shape = tuple(int(d) for d in shape)
            if len(shape) != len(spec.shape):
                raise EnforceError(
                    f"slot {spec.name!r}: rank {len(shape)} != spec rank "
                    f"{len(spec.shape)} (per-sample shape {spec.shape})"
                )
            padded = []
            for i, d in enumerate(shape):
                if i in ragged:
                    padded.append(self._round_length(d))
                else:
                    want = spec.shape[i]
                    if want is not None and d != want:
                        raise EnforceError(
                            f"slot {spec.name!r} dim {i}: got {d}, spec "
                            f"requires {want}"
                        )
                    padded.append(d)
            sig.append(tuple(padded))
        return tuple(sig)

    def all_signatures(self) -> List[Signature]:
        """Every signature the engine must pre-compile (cross product of
        ragged-dim length buckets; a single signature when all dims are
        static)."""
        per_slot: List[List[Tuple[int, ...]]] = []
        for spec, ragged in zip(self.specs, self._ragged_dims):
            variants: List[Tuple[int, ...]] = [()]
            for i, d in enumerate(spec.shape):
                choices = (
                    list(self.length_buckets) if i in ragged else [int(d)]
                )
                variants = [v + (c,) for v in variants for c in choices]
            per_slot.append(variants)
        sigs: List[Signature] = [()]
        for variants in per_slot:
            sigs = [s + (v,) for s in sigs for v in variants]
        return sigs

    # -- padding helpers ---------------------------------------------------

    def pad_to_signature(self, arrays: Sequence[np.ndarray], sig: Signature):
        """Zero-pad each slot's per-sample dims up to ``sig`` (row count
        untouched)."""
        out = []
        for arr, shape in zip(arrays, sig):
            arr = np.asarray(arr)
            pad = [(0, 0)] + [
                (0, t - s) for t, s in zip(shape, arr.shape[1:])
            ]
            if any(p[1] for p in pad):
                arr = np.pad(arr, pad)
            out.append(arr)
        return out

    @staticmethod
    def pad_rows(arrays: Sequence[np.ndarray], target_rows: int):
        """Zero-pad the leading (row) dim of every slot to ``target_rows``."""
        out = []
        for arr in arrays:
            arr = np.asarray(arr)
            short = target_rows - arr.shape[0]
            if short > 0:
                pad = [(0, short)] + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            out.append(arr)
        return out

"""Dynamic micro-batcher: the queue→batch policy loop.

Pulls requests off a bounded :class:`paddle_tpu.concurrency.Channel`,
groups them by padded shape signature, and flushes a group when either

- its row count reaches ``max_batch_rows`` (a full batch beats latency), or
- its OLDEST request has waited ``max_delay_s`` (latency beats occupancy).

This is the classic dynamic-batching policy pair (max batch size + max
queue delay). Deadline-expired requests are rejected here — before any
device time is spent on them — via the ``on_expired`` callback.

The batcher owns no threads itself: :meth:`run` is a plain loop the engine
puts on one ``concurrency.go`` goroutine. It exits when the request channel
is closed AND drained, flushing every pending group first — that single
rule is what makes ``engine.close()`` a graceful drain.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.concurrency import Channel

__all__ = ["MicroBatcher", "Group"]


class Group:
    """Requests sharing one shape signature, awaiting flush."""

    __slots__ = ("sig", "requests", "rows", "t_first")

    def __init__(self, sig, t_first: float):
        self.sig = sig
        self.requests: List[Any] = []
        self.rows = 0
        self.t_first = t_first


class MicroBatcher:
    def __init__(
        self,
        queue: Channel,
        max_batch_rows: int,
        max_delay_s: float,
        flush: Callable[[Group], None],
        on_expired: Callable[[Any], None],
        clock: Callable[[], float] = time.monotonic,
    ):
        self._queue = queue
        self._max_rows = int(max_batch_rows)
        self._max_delay = float(max_delay_s)
        self._flush = flush
        self._on_expired = on_expired
        self._clock = clock

    def run(self) -> None:
        groups: Dict[Any, Group] = {}
        while True:
            timeout: Optional[float] = None
            if groups:
                due = min(g.t_first for g in groups.values()) + self._max_delay
                timeout = max(1e-4, due - self._clock())
            try:
                req, ok = self._queue.recv(timeout=timeout)
            except TimeoutError:
                req, ok = None, True
            now = self._clock()
            if req is not None:
                if req.deadline is not None and now > req.deadline:
                    self._on_expired(req)
                else:
                    group = groups.get(req.sig)
                    if group is not None and group.rows + req.n > self._max_rows:
                        # the new request would overflow the bucket: ship the
                        # current group and start a fresh one
                        self._flush(groups.pop(req.sig))
                        group = None
                    if group is None:
                        group = groups.setdefault(req.sig, Group(req.sig, now))
                    group.requests.append(req)
                    group.rows += req.n
                    try:
                        # tracing mark: end of the request's queue wait.
                        # perf_counter (the span timebase), NOT self._clock —
                        # tests inject fake clocks for the delay policy.
                        req.t_grouped_pc = time.perf_counter()
                    except AttributeError:
                        pass  # tests batch plain fake objects with __slots__
                    if group.rows >= self._max_rows:
                        self._flush(groups.pop(req.sig))
            # flush whatever has aged past the delay budget
            for sig in [
                s
                for s, g in groups.items()
                if now >= g.t_first + self._max_delay
            ]:
                self._flush(groups.pop(sig))
            if not ok:
                # channel closed and fully drained: final flush, then exit
                for group in groups.values():
                    self._flush(group)
                return

"""Weighted fair scheduling over per-tenant request queues.

The serving engine used to drain one global FIFO ``Channel`` — a
single-tenant design where any one client could occupy every queue slot
and every batch. The reference stack had the same failure mode: the gRPC
``listen_and_serv`` server queued sends unboundedly per connection with no
notion of whose work was whose. :class:`WeightedFairScheduler` replaces
the FIFO with one bounded queue per ``(tenant, class)`` drained by deficit
round-robin:

- **Tenants** each carry a *weight*; over time a backlogged tenant is
  served rows in proportion to its weight (classic DRR: each tenant
  accrues a row *deficit* per scheduling round and spends it on its queued
  requests, so fairness is by rows — the unit of device time — not by
  request count).
- **Priority classes**: ``interactive`` requests preempt ``batch`` at
  group-formation time (the scheduler hands interactive work to the
  micro-batcher first), but batch is guaranteed a minimum drain share
  (``batch_min_share``): at least one of every ``1/batch_min_share`` picks
  goes to batch while batch work is pending, so a saturating interactive
  tenant can never starve batch completely.
- **Prompt expiry**: requests whose deadline lapses while queued are
  evicted at the queue head (and en-masse under quota pressure) instead of
  occupying bounded capacity until dispatch discovers them.

The scheduler is deliberately Channel-shaped — ``send`` / ``recv`` /
``close`` / ``qsize`` with ``(value, ok)`` recv semantics and
:class:`~paddle_tpu.concurrency.ChannelClosedError` on send-after-close —
so the existing :class:`~paddle_tpu.serving.batcher.MicroBatcher` drains
it unchanged and ``engine.close()`` keeps its graceful-drain contract.
``send`` preserves the legacy blocking-backpressure contract (used when
admission control is off); :meth:`try_put` is the non-blocking admission
path that reports a quota-rejection reason instead of ever blocking the
caller.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from paddle_tpu.core import locks
from paddle_tpu.concurrency import ChannelClosedError
from paddle_tpu.core.enforce import enforce

__all__ = [
    "INTERACTIVE",
    "BATCH",
    "CLASSES",
    "WeightedFairScheduler",
]

INTERACTIVE = "interactive"
BATCH = "batch"
CLASSES = (INTERACTIVE, BATCH)

# quota-rejection reasons returned by try_put (admission turns them into
# typed AdmissionRejected errors)
REASON_QUEUE_QUOTA = "queue_quota"
REASON_BYTE_QUOTA = "byte_quota"


class _TenantState:
    """One tenant's queues + DRR accounting (all access under the
    scheduler lock)."""

    __slots__ = ("config", "queues", "deficit", "queued", "queued_bytes")

    def __init__(self, config):
        self.config = config
        self.queues: Dict[str, collections.deque] = {
            c: collections.deque() for c in CLASSES
        }
        self.deficit: Dict[str, float] = {c: 0.0 for c in CLASSES}
        self.queued = 0          # requests across both classes
        self.queued_bytes = 0    # payload bytes across both classes


class WeightedFairScheduler:
    """Per-tenant queues + deficit-round-robin drain (see module docstring).

    ``tenants`` maps name -> :class:`~paddle_tpu.serving.admission.
    TenantConfig`. ``quantum_rows`` is the DRR quantum (rows granted to the
    highest-weight tenant per scheduling round); the engine passes its max
    batch size so one quantum always covers one maximal request.
    ``legacy_capacity`` enables the blocking single-FIFO contract for
    ``send`` (total queued requests bounded, callers park) — the
    compatibility mode used when admission control is off.
    ``on_expired(req)`` is invoked (outside the lock) for every request
    evicted because its deadline lapsed in the queue.
    """

    def __init__(
        self,
        tenants: Dict[str, Any],
        *,
        quantum_rows: int = 8,
        batch_min_share: float = 0.1,
        legacy_capacity: Optional[int] = None,
        on_expired: Optional[Callable[[Any], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        enforce(bool(tenants), "scheduler needs at least one tenant")
        enforce(quantum_rows >= 1,
                f"quantum_rows must be >= 1, got {quantum_rows}")
        enforce(0.0 < batch_min_share < 1.0,
                f"batch_min_share must be in (0, 1), got {batch_min_share}")
        self._tenants: Dict[str, _TenantState] = {
            name: _TenantState(cfg) for name, cfg in tenants.items()
        }
        for name, st in self._tenants.items():
            enforce(st.config.weight > 0,
                    f"tenant {name!r}: weight must be > 0")
        self._order: List[str] = list(tenants.keys())
        self._max_weight = max(
            st.config.weight for st in self._tenants.values())
        self._quantum = float(quantum_rows)
        self.batch_min_share = float(batch_min_share)
        # guaranteed batch share: after this many consecutive interactive
        # picks with batch work pending, the next pick is batch
        self._interactive_burst = max(
            1, round((1.0 - batch_min_share) / batch_min_share))
        self._interactive_streak = 0
        self._legacy_capacity = legacy_capacity
        self._on_expired = on_expired
        self._clock = clock
        self._lock = locks.Lock("serving.scheduler")
        self._readable = locks.Condition(self._lock, name="serving.scheduler.readable")  # work available
        self._space = locks.Condition(self._lock, name="serving.scheduler.space")     # capacity freed
        self._rr: Dict[str, int] = {c: 0 for c in CLASSES}
        self._total = 0
        self._closed = False
        self._poked = False

    # -- introspection -----------------------------------------------------

    def qsize(self) -> int:
        with self._lock:
            return self._total

    def is_closed(self) -> bool:
        with self._lock:
            return self._closed

    def poke(self) -> None:
        """Bounce one parked ``recv`` caller out through its timeout path
        without delivering work. The decode-engine loop parks here when
        idle, but handoff/rescue adoptions arrive on side lists only the
        loop thread may touch — without a poke the adoption waits out the
        full idle poll. Only ``recv`` calls WITH a timeout return early;
        an untimed ``recv`` ignores the flag (and leaves it set for the
        next timed caller), so blocking consumers never see a spurious
        ``TimeoutError``."""
        with self._lock:
            self._poked = True
            self._readable.notify()

    def tenant_names(self) -> List[str]:
        return list(self._order)

    def depths(self) -> Dict[str, dict]:
        """Per-tenant queue snapshot: {tenant: {class: depth, ...,
        "bytes": queued_bytes}} — the source for the ``serving.tenant.*``
        queue gauges and the ``/tenants`` endpoint."""
        with self._lock:
            return {
                name: {
                    **{c: len(st.queues[c]) for c in CLASSES},
                    "bytes": st.queued_bytes,
                }
                for name, st in self._tenants.items()
            }

    # -- enqueue -----------------------------------------------------------

    def _req_bytes(self, req) -> int:
        return int(getattr(req, "bytes", 0) or 0)

    def _enqueue_locked(self, st: _TenantState, req) -> None:
        st.queues[req.cls].append(req)
        st.queued += 1
        st.queued_bytes += self._req_bytes(req)
        self._total += 1
        self._readable.notify()

    def try_put(self, req) -> Optional[str]:
        """Non-blocking enqueue for the admission path. Atomically checks
        the tenant's request and byte quotas and enqueues on success.
        Returns None (accepted) or the quota-rejection reason. Expired
        requests already in the tenant's queues are evicted before the
        quota check, so dead work never causes a live rejection. Raises
        :class:`ChannelClosedError` after close."""
        enforce(req.cls in CLASSES,
                f"unknown priority class {req.cls!r} (expected one of {CLASSES})")
        expired: List[Any] = []
        try:
            with self._lock:
                if self._closed:
                    raise ChannelClosedError("scheduler is closed")
                st = self._tenants[req.tenant]
                cfg = st.config
                if st.queued >= cfg.queue_capacity:
                    self._evict_expired_locked(expired, tenant=req.tenant,
                                               full_scan=True)
                if st.queued >= cfg.queue_capacity:
                    return REASON_QUEUE_QUOTA
                nbytes = self._req_bytes(req)
                if cfg.byte_quota and st.queued_bytes + nbytes > cfg.byte_quota:
                    self._evict_expired_locked(expired, tenant=req.tenant,
                                               full_scan=True)
                if cfg.byte_quota and st.queued_bytes + nbytes > cfg.byte_quota:
                    return REASON_BYTE_QUOTA
                self._enqueue_locked(st, req)
                return None
        finally:
            self._fire_expired(expired)

    def send(self, req, timeout: Optional[float] = None) -> None:
        """Blocking enqueue — the legacy bounded-FIFO contract (admission
        off): parks while ``legacy_capacity`` total requests are queued,
        raising ``TimeoutError`` on timeout and
        :class:`ChannelClosedError` if the scheduler is or becomes closed.
        Without a ``legacy_capacity`` the put only bounds per-tenant (the
        admission path should be using :meth:`try_put` instead)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        expired: List[Any] = []
        try:
            with self._lock:
                while True:
                    if self._closed:
                        raise ChannelClosedError("scheduler is closed")
                    cap = self._legacy_capacity
                    if cap is None or self._total < cap:
                        break
                    # free slots held by dead work before parking the caller
                    self._evict_expired_locked(expired, full_scan=True)
                    if self._total < cap:
                        break
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError("scheduler send timed out")
                    self._space.wait(remaining)
                self._enqueue_locked(self._tenants[req.tenant], req)
        finally:
            self._fire_expired(expired)

    # -- expiry ------------------------------------------------------------

    def _pop_locked(self, st: _TenantState, cls: str):
        req = st.queues[cls].popleft()
        st.queued -= 1
        st.queued_bytes -= self._req_bytes(req)
        self._total -= 1
        self._space.notify_all()
        return req

    def _evict_expired_locked(self, out: List[Any],
                              tenant: Optional[str] = None,
                              full_scan: bool = False) -> None:
        """Move expired requests out of the queues into ``out`` (their
        ``on_expired`` callbacks run after the lock is released). Head-only
        by default (O(1) per drain step); ``full_scan`` sweeps whole queues
        — used under quota pressure so an expired request buried mid-queue
        cannot cause a live rejection."""
        now = self._clock()
        names = [tenant] if tenant is not None else self._order
        for name in names:
            st = self._tenants[name]
            for cls in CLASSES:
                q = st.queues[cls]
                while q and q[0].deadline is not None and now > q[0].deadline:
                    out.append(self._pop_locked(st, cls))
                if full_scan and q:
                    live = [r for r in q
                            if r.deadline is None or now <= r.deadline]
                    if len(live) != len(q):
                        for r in q:
                            if r.deadline is not None and now > r.deadline:
                                out.append(r)
                                st.queued -= 1
                                st.queued_bytes -= self._req_bytes(r)
                                self._total -= 1
                        q.clear()
                        q.extend(live)
                        self._space.notify_all()

    def _fire_expired(self, expired: List[Any]) -> None:
        if self._on_expired is not None:
            for req in expired:
                self._on_expired(req)

    # -- drain (DRR + priority) --------------------------------------------

    def _has_work_locked(self, cls: str) -> bool:
        return any(st.queues[cls] for st in self._tenants.values())

    def _choose_class_locked(self) -> Optional[str]:
        has_i = self._has_work_locked(INTERACTIVE)
        has_b = self._has_work_locked(BATCH)
        if has_i and has_b:
            # interactive preempts batch — except for batch's guaranteed
            # minimum share, granted one pick per interactive burst
            if self._interactive_streak >= self._interactive_burst:
                self._interactive_streak = 0
                return BATCH
            self._interactive_streak += 1
            return INTERACTIVE
        if has_i:
            return INTERACTIVE
        if has_b:
            self._interactive_streak = 0
            return BATCH
        return None

    def _pick_from_class_locked(self, cls: str):
        """Deficit round-robin: serve the current tenant while its deficit
        covers its head request's rows; grant weighted quanta to every
        backlogged tenant when no deficit suffices. Terminates because
        quanta are positive and request rows are bounded."""
        order = self._order
        n = len(order)
        while True:
            for k in range(n):
                idx = (self._rr[cls] + k) % n
                st = self._tenants[order[idx]]
                q = st.queues[cls]
                if not q:
                    st.deficit[cls] = 0.0  # classic DRR: idle queues reset
                    continue
                if st.deficit[cls] >= q[0].n:
                    req = self._pop_locked(st, cls)
                    st.deficit[cls] -= req.n
                    if not q:
                        st.deficit[cls] = 0.0
                        self._rr[cls] = (idx + 1) % n
                    else:
                        self._rr[cls] = idx  # keep draining this tenant
                    return req
            for name in order:
                st = self._tenants[name]
                if st.queues[cls]:
                    st.deficit[cls] += (
                        self._quantum * st.config.weight / self._max_weight)

    def recv(self, timeout: Optional[float] = None):
        """Next request by scheduling policy as ``(req, True)``; blocks
        until work arrives, the timeout lapses (``TimeoutError``), or the
        scheduler is closed AND drained (``(None, False)`` — Go's
        ``v, ok``, matching :class:`~paddle_tpu.concurrency.Channel` so the
        micro-batcher's drain loop works unchanged)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            expired: List[Any] = []
            result: Optional[Tuple[Any, bool]] = None
            timed_out = False
            with self._lock:
                self._evict_expired_locked(expired)
                cls = self._choose_class_locked()
                if cls is not None:
                    result = (self._pick_from_class_locked(cls), True)
                elif self._closed:
                    result = (None, False)
                else:
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        timed_out = True
                    elif deadline is not None and self._poked:
                        self._poked = False
                        timed_out = True  # poke(): out-of-band work waits
                    elif not expired:
                        # with evicted requests in hand, skip the wait:
                        # their on_expired callbacks must fire now (outside
                        # the lock), not at the next notify — a caller
                        # blocked on one of those requests may be the only
                        # thing that would ever notify again
                        self._readable.wait(remaining)
            self._fire_expired(expired)
            if result is not None:
                return result
            if timed_out:
                raise TimeoutError("scheduler recv timed out")

    def close(self) -> None:
        """Stop intake; queued requests remain drainable via ``recv``
        (graceful drain), parked legacy senders raise. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._readable.notify_all()
            self._space.notify_all()

    def __iter__(self):
        while True:
            value, ok = self.recv()
            if not ok:
                return
            yield value

"""paddle_tpu.serving — dynamically-batched TPU inference serving.

The production path from "trained model" to "heavy concurrent traffic":
requests pass multi-tenant admission control (``serving.admission``:
quotas, deadline-feasibility prediction, SLO-driven brownout shedding),
queue per tenant under a weighted-fair scheduler (``serving.scheduler``:
deficit round-robin, interactive/batch priority classes with a guaranteed
batch share), then a dynamic micro-batcher groups them into zero-padded
shape buckets (AOT compiled at startup via ``Executor.prepare``) and
batches round-robin across one replica per local device. See
``serving.engine`` for the full design; the reference stack's analogue is
the Fluid inference engine behind the gRPC ``listen_and_serv`` server.

Quickstart::

    from paddle_tpu.serving import ServingEngine, ServingConfig
    from paddle_tpu.reader.feeder import FeedSpec

    engine = ServingEngine(
        infer_net, "ckpt/params",
        feed_specs=[FeedSpec("x", (784,), "float32")],
        config=ServingConfig(max_batch_size=16, max_queue_delay_s=0.002),
    )
    logits = engine.infer({"x": batch})     # sync
    fut = engine.submit({"x": batch})        # async → fut.result()
    engine.close()                           # graceful drain
"""

from paddle_tpu.serving.admission import (
    AdmissionController,
    AdmissionRejected,
    TenantConfig,
    TokenBucket,
)
from paddle_tpu.serving.batcher import Group, MicroBatcher
from paddle_tpu.serving.buckets import ShapeBuckets
from paddle_tpu.serving.engine import (
    DeadlineExceeded,
    EngineClosedError,
    PendingResult,
    ReplicaDied,
    ServingConfig,
    ServingEngine,
)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.scheduler import (
    BATCH,
    INTERACTIVE,
    WeightedFairScheduler,
)

__all__ = [
    "ServingEngine",
    "ServingConfig",
    "PendingResult",
    "DeadlineExceeded",
    "EngineClosedError",
    "ReplicaDied",
    "MicroBatcher",
    "Group",
    "ShapeBuckets",
    "ServingMetrics",
    "AdmissionController",
    "AdmissionRejected",
    "TenantConfig",
    "TokenBucket",
    "WeightedFairScheduler",
    "INTERACTIVE",
    "BATCH",
]

"""paddle_tpu.serving — dynamically-batched TPU inference serving.

The production path from "trained model" to "heavy concurrent traffic":
requests pass multi-tenant admission control (``serving.admission``:
quotas, deadline-feasibility prediction, SLO-driven brownout shedding),
queue per tenant under a weighted-fair scheduler (``serving.scheduler``:
deficit round-robin, interactive/batch priority classes with a guaranteed
batch share), then a dynamic micro-batcher groups them into zero-padded
shape buckets (AOT compiled at startup via ``Executor.prepare``) and
batches round-robin across one replica per local device. See
``serving.engine`` for the full design; the reference stack's analogue is
the Fluid inference engine behind the gRPC ``listen_and_serv`` server.

Quickstart::

    from paddle_tpu.serving import ServingEngine, ServingConfig
    from paddle_tpu.reader.feeder import FeedSpec

    engine = ServingEngine(
        infer_net, "ckpt/params",
        feed_specs=[FeedSpec("x", (784,), "float32")],
        config=ServingConfig(max_batch_size=16, max_queue_delay_s=0.002),
    )
    logits = engine.infer({"x": batch})     # sync
    fut = engine.submit({"x": batch})        # async → fut.result()
    engine.close()                           # graceful drain

Autoregressive decode uses the continuous-batching path instead
(``serving.decode`` + ``serving.kv_cache``): iteration-level admission
into a paged KV cache, so a freed slot refills on the next decode step
instead of idling until the slowest request in a static batch drains::

    from paddle_tpu.serving import DecodeEngine, DecodeConfig

    eng = DecodeEngine(variables, cfg, decode=DecodeConfig(max_slots=8))
    out = eng.infer(prompt_ids, max_new_tokens=64)   # DecodeOutput
    eng.close()

Zero-loss serving (``serving.recovery``) layers three safety rings over
the decode engine — step-fault quarantine + re-admission, cross-engine
migration behind per-engine circuit breakers (:class:`DecodeFleet`), and
a durable request journal whose replay resumes in-flight generations
after a process restart::

    decode = DecodeConfig(journal_path="j/decode.wal")
    fleet = DecodeFleet([DecodeEngine(v, cfg, decode=decode), ...])
    h = fleet.submit(prompt_ids, 64)         # routed to a healthy engine
    # after a restart over the same journal:
    handles = resume_incomplete(new_engine, "j/decode.wal")
"""

from paddle_tpu.serving.admission import (
    AdmissionController,
    AdmissionRejected,
    TenantConfig,
    TokenBucket,
)
from paddle_tpu.serving.batcher import Group, MicroBatcher
from paddle_tpu.serving.buckets import ShapeBuckets
from paddle_tpu.serving.decode import (
    DecodeConfig,
    DecodeCostModel,
    DecodeEngine,
    DecodeHandle,
    DecodeOutput,
)
from paddle_tpu.serving.disagg import (
    Autoscaler,
    AutoscalerConfig,
    DisaggRouter,
    HandoffCorrupt,
    HandoffPayload,
)
from paddle_tpu.serving.host_tier import (
    HostPageCorrupt,
    HostPagePool,
    prefix_digests,
)
from paddle_tpu.serving.engine import (
    DeadlineExceeded,
    EngineClosedError,
    PendingResult,
    ReplicaDied,
    ServingConfig,
    ServingEngine,
)
from paddle_tpu.serving.kv_cache import (
    SCRATCH_PAGE,
    PageAllocator,
    PagedKVCache,
)
from paddle_tpu.serving.metrics import DecodeMetrics, ServingMetrics
from paddle_tpu.serving.prefix_cache import RadixPrefixCache
from paddle_tpu.serving.recovery import (
    DecodeFleet,
    EngineUnhealthy,
    RequestJournal,
    RescuePacket,
    RetriesExhausted,
    replay_journal,
    resume_incomplete,
)
from paddle_tpu.serving.scheduler import (
    BATCH,
    INTERACTIVE,
    WeightedFairScheduler,
)
from paddle_tpu.serving.shardgroup import (
    GroupLayout,
    GroupStragglerWatch,
    ReplicaGroup,
    default_layout,
    make_groups,
    probe_members,
)

__all__ = [
    "ServingEngine",
    "ServingConfig",
    "PendingResult",
    "DeadlineExceeded",
    "EngineClosedError",
    "ReplicaDied",
    "MicroBatcher",
    "Group",
    "ShapeBuckets",
    "ServingMetrics",
    "AdmissionController",
    "AdmissionRejected",
    "TenantConfig",
    "TokenBucket",
    "WeightedFairScheduler",
    "INTERACTIVE",
    "BATCH",
    "DecodeEngine",
    "DecodeConfig",
    "DecodeCostModel",
    "DecodeHandle",
    "DecodeOutput",
    "DecodeMetrics",
    "PagedKVCache",
    "PageAllocator",
    "RadixPrefixCache",
    "HostPagePool",
    "HostPageCorrupt",
    "prefix_digests",
    "SCRATCH_PAGE",
    "DecodeFleet",
    "EngineUnhealthy",
    "RequestJournal",
    "RescuePacket",
    "RetriesExhausted",
    "replay_journal",
    "resume_incomplete",
    "DisaggRouter",
    "HandoffPayload",
    "HandoffCorrupt",
    "Autoscaler",
    "AutoscalerConfig",
    "ReplicaGroup",
    "GroupLayout",
    "GroupStragglerWatch",
    "make_groups",
    "default_layout",
    "probe_members",
]

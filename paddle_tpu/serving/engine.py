"""ServingEngine: dynamically-batched, AOT-compiled TPU inference serving.

The reference stack served trained models through the Fluid inference
engine behind the gRPC ``listen_and_serv`` server; the TPU-native
replacement is built around what actually limits an XLA device under mixed
request load: compilation (one executable per shape) and occupancy (a
device running batch-1 requests is idle silicon).

Request path::

    submit(feed) ──▶ bounded Channel (backpressure) ──▶ MicroBatcher
        ──▶ shape-bucket groups, padded to (signature, batch bucket)
        ──▶ round-robin replica Channel ──▶ replica worker thread
        ──▶ Executor.prepare-cached executable on that device
        ──▶ per-request row slices complete each PendingResult

Key properties:

- **AOT warmup**: every (signature, batch-bucket) executable compiles at
  startup on every replica; steady-state traffic never waits on XLA.
- **Dynamic micro-batching**: max batch size + max queue delay, padding to
  shape buckets derived from ``FeedSpec`` (see ``serving.buckets``).
- **Replica round-robin**: one ``Executor`` per local device, each with its
  own resident copy of the variables; batches rotate across them.
- **Deadlines**: a request carries an absolute deadline; if it expires in
  the queue it gets a :class:`DeadlineExceeded` response without spending
  device time.
- **Backpressure**: the request channel is bounded; ``submit`` blocks (or
  times out) when the engine is saturated instead of growing an unbounded
  queue.
- **Graceful drain**: ``close()`` stops intake, lets the batcher flush
  everything already accepted, waits for the replica workers, and only
  then returns — no accepted request is dropped.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from paddle_tpu.concurrency import Channel, ChannelClosedError, go
from paddle_tpu.core import config as cfg
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.enforce import EnforceError, enforce
from paddle_tpu.executor import Executor
from paddle_tpu.framework import Model, Variables, build
from paddle_tpu.reader.feeder import FeedSpec
from paddle_tpu.serving.batcher import Group, MicroBatcher
from paddle_tpu.serving.buckets import ShapeBuckets
from paddle_tpu.serving.metrics import ServingMetrics

__all__ = [
    "ServingEngine",
    "ServingConfig",
    "PendingResult",
    "DeadlineExceeded",
    "EngineClosedError",
]


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before it reached a device."""


class EngineClosedError(RuntimeError):
    """submit() after close() — the engine no longer accepts requests."""


@dataclasses.dataclass
class ServingConfig:
    """Batching/compilation policy knobs."""

    max_batch_size: int = 8
    # latency budget a request may wait for co-batching company
    max_queue_delay_s: float = 0.005
    # bounded request queue: submit blocks past this depth (backpressure)
    queue_capacity: int = 64
    # padded batch sizes compiled AOT; default powers of 2 up to max_batch
    batch_buckets: Optional[Sequence[int]] = None
    # padded lengths for ragged FeedSpec dims (required if any are ragged)
    length_buckets: Optional[Sequence[int]] = None
    # device replicas; None = every local device of the place's platform
    num_replicas: Optional[int] = None
    # compile every (signature, batch bucket) executable at startup
    warmup: bool = True
    # abstract-trace the model through paddle_tpu.analysis.lint_model before
    # warm-up and log findings (never fatal); catches stale checkpoints,
    # sharding-rank mistakes and f64 leaks before paying compile time
    lint_model: bool = True
    # default per-request deadline; None = no deadline
    default_deadline_s: Optional[float] = None


class PendingResult:
    """Future-like handle for one submitted request."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def _complete(self, value) -> None:
        self._value = value
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()


class _Request:
    __slots__ = ("arrays", "n", "sig", "deadline", "t_submit", "pending")

    def __init__(self, arrays, n, sig, deadline, t_submit):
        self.arrays = arrays
        self.n = n
        self.sig = sig
        self.deadline = deadline
        self.t_submit = t_submit
        self.pending = PendingResult()


class _ReplicaPlace(cfg.Place):
    """Indexed place on any platform (CPUPlace carries no index; replicas
    need one per local device)."""

    def __init__(self, platform: str, device_id: int):
        self.platform = platform
        self.device_id = device_id

    def __repr__(self):
        return f"_ReplicaPlace({self.platform!r}, {self.device_id})"


class _Replica:
    __slots__ = ("index", "exe", "variables", "compiled", "channel", "thread")

    def __init__(self, index: int, exe: Executor, variables, compiled, channel):
        self.index = index
        self.exe = exe
        self.variables = variables
        self.compiled = compiled
        self.channel = channel
        self.thread = None


class ServingEngine:
    """Concurrent inference over a trained :class:`Model`.

    ::

        engine = ServingEngine(infer_net, variables, feed_specs)
        out = engine.infer({"x": batch})          # sync
        fut = engine.submit({"x": batch})          # async
        ...
        engine.close()                             # graceful drain
    """

    def __init__(
        self,
        model: Union[Model, Any],
        variables: Union[Variables, str],
        feed_specs: Sequence[FeedSpec],
        config: Optional[ServingConfig] = None,
        place: Optional[cfg.Place] = None,
    ):
        self.model = model if isinstance(model, Model) else build(model)
        if isinstance(variables, str):
            from paddle_tpu import io as io_mod

            variables = io_mod.load_params(variables)
        self.config = config or ServingConfig()
        self.specs = list(feed_specs)
        enforce(bool(self.specs), "feed_specs must be non-empty")
        self.buckets = ShapeBuckets(
            self.specs,
            self.config.max_batch_size,
            batch_buckets=self.config.batch_buckets,
            length_buckets=self.config.length_buckets,
        )
        self.metrics = ServingMetrics()
        self._closed = False
        self._close_lock = threading.Lock()
        self._rr = 0  # round-robin cursor (batcher thread only)

        base_place = place or cfg.default_place()
        platform = base_place.platform
        local = [
            d
            for d in jax.devices()
            if cfg._platform_matches(d, platform)
        ] or jax.devices()
        n_rep = self.config.num_replicas or len(local)
        n_rep = max(1, min(n_rep, len(local)))

        def _fwd(vs, *arrays):
            out, _ = self.model.apply(vs, *arrays, is_train=False)
            return out

        self._fwd = _fwd

        self._replicas: List[_Replica] = []
        for i in range(n_rep):
            exe = Executor(_ReplicaPlace(platform, i))
            rep_vars = jax.device_put(variables, exe.device)
            compiled = exe.prepare(self._fwd, key=("serving", self.model.name, i))
            self._replicas.append(
                _Replica(i, exe, rep_vars, compiled, Channel(capacity=2))
            )

        if self.config.lint_model:
            self._lint_model(variables)
        if self.config.warmup:
            self._warmup()

        self._queue: Channel = Channel(capacity=self.config.queue_capacity)
        self._batcher = MicroBatcher(
            self._queue,
            max_batch_rows=self.config.max_batch_size,
            max_delay_s=self.config.max_queue_delay_s,
            flush=self._dispatch,
            on_expired=self._expire,
        )
        for rep in self._replicas:
            rep.thread = go(self._worker, rep)
        self._batcher_thread = go(self._batcher.run)

    # -- startup -----------------------------------------------------------

    def _lint_model(self, variables) -> None:
        """Abstract-trace the model over the smallest warm-up signature and
        surface structural findings (stale params, sharding-rank mismatches,
        f64 leaks) in the log before compile time is spent. Best-effort:
        lint failure never blocks serving."""
        from paddle_tpu.core import logging as ptlog

        try:
            from paddle_tpu.analysis import lint_model as _lint

            sig = sorted(self.buckets.all_signatures())[0]
            rows = min(self.buckets.batch_buckets)
            diags = _lint(
                self.model, self._zeros_for(sig, rows),
                variables=variables, train=False,
            )
            for d in diags:
                ptlog.warn_once(
                    ("serving-model-lint", self.model.name, d.code, d.where),
                    "model lint [%s]: %s", d.code, str(d),
                )
        except Exception as e:  # pragma: no cover - defensive
            ptlog.warn_once(
                ("serving-model-lint-failed", self.model.name),
                "model lint skipped: %s", e,
            )

    def _zeros_for(self, sig, rows: int):
        return [
            np.zeros((rows,) + shape, dtype=spec.dtype)
            for spec, shape in zip(self.specs, sig)
        ]

    def _warmup(self) -> None:
        """AOT-compile every (signature, batch bucket) on every replica so
        live traffic never pays XLA compile latency."""
        with prof.record_event("serving.warmup"):
            for sig in self.buckets.all_signatures():
                for b in self.buckets.batch_buckets:
                    args = self._zeros_for(sig, b)
                    for rep in self._replicas:
                        out = rep.compiled(rep.variables, *args)
                        jax.device_get(out)  # force the compile + run
                        self.metrics.record_warmup()

    def aot_cache_sizes(self) -> List[int]:
        """Per-replica count of compiled executables inside the jitted
        forward (−1 when jax doesn't expose it). Steady after warmup ⇒ no
        request ever triggered a fresh compile."""
        return [
            rep.compiled._cache_size()
            if hasattr(rep.compiled, "_cache_size")
            else -1
            for rep in self._replicas
        ]

    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    # -- request intake ----------------------------------------------------

    def _normalize_feed(self, feed) -> Tuple[np.ndarray, ...]:
        """feed → per-slot arrays in FeedSpec order. Dict feeds are looked
        up BY NAME (never by insertion order); sequences must already be in
        spec order."""
        if isinstance(feed, dict):
            missing = [s.name for s in self.specs if s.name not in feed]
            if missing:
                raise EnforceError(f"feed missing slots {missing}")
            arrays = [feed[s.name] for s in self.specs]
        else:
            if not isinstance(feed, (tuple, list)):
                feed = (feed,)  # bare array = the single feed slot
            enforce(
                len(feed) == len(self.specs),
                f"expected {len(self.specs)} feed slots, got {len(feed)}",
            )
            arrays = list(feed)
        return tuple(
            np.asarray(a, dtype=spec.dtype)
            for a, spec in zip(arrays, self.specs)
        )

    def submit(
        self,
        feed,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
    ) -> PendingResult:
        """Enqueue one request (arrays carry a leading batch dim). Returns a
        :class:`PendingResult`. Blocks while the bounded queue is full;
        ``timeout`` bounds that wait (TimeoutError = backpressure rejection).
        """
        if self._closed:
            raise EngineClosedError("engine is closed")
        arrays = self._normalize_feed(feed)
        rows = {int(a.shape[0]) for a in arrays if a.ndim > 0}
        enforce(len(rows) == 1, f"feed slots disagree on batch dim: {rows}")
        n = rows.pop()
        enforce(
            1 <= n <= self.config.max_batch_size,
            f"request rows {n} outside [1, {self.config.max_batch_size}]",
        )
        sig = self.buckets.signature([a.shape[1:] for a in arrays])
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline = None if deadline_s is None else now + deadline_s
        req = _Request(arrays, n, sig, deadline, now)
        try:
            self._queue.send(req, timeout=timeout)
        except ChannelClosedError:
            raise EngineClosedError("engine is closed") from None
        # counted only once accepted: a backpressure rejection (TimeoutError
        # above) never shows up as a request that went missing
        self.metrics.record_submit(n, self._queue.qsize())
        return req.pending

    def infer(self, feed, deadline_s: Optional[float] = None):
        """Synchronous request: submit + wait. Raises
        :class:`DeadlineExceeded` if the deadline expires in the queue."""
        return self.submit(feed, deadline_s=deadline_s).result()

    # -- batching / dispatch (batcher thread) ------------------------------

    def _expire(self, req: _Request) -> None:
        self.metrics.record_timeout()
        req.pending._fail(
            DeadlineExceeded(
                f"request expired after {time.monotonic() - req.t_submit:.3f}s in queue"
            )
        )

    def _dispatch(self, group: Group) -> None:
        """Pad one signature group to its batch bucket and round-robin it to
        a replica. Runs on the batcher thread; a busy replica channel blocks
        here, which is the intended backpressure toward the request queue."""
        live = []
        now = time.monotonic()
        for req in group.requests:
            if req.deadline is not None and now > req.deadline:
                self._expire(req)
            else:
                live.append(req)
        if not live:
            return
        rows = sum(r.n for r in live)
        bucket_b = self.buckets.batch_bucket(rows)
        slots = []
        for j in range(len(self.specs)):
            per_req = [
                self.buckets.pad_to_signature([r.arrays[j]], group.sig[j : j + 1])[0]
                for r in live
            ]
            col = per_req[0] if len(per_req) == 1 else np.concatenate(per_req, axis=0)
            slots.append(col)
        slots = self.buckets.pad_rows(slots, bucket_b)
        self.metrics.record_batch(rows, bucket_b, group.sig)
        self.metrics.set_queue_depth(self._queue.qsize())
        rep = self._replicas[self._rr % len(self._replicas)]
        self._rr += 1
        rep.channel.send((live, slots, bucket_b))

    # -- execution (replica worker threads) --------------------------------

    def _worker(self, rep: _Replica) -> None:
        for live, slots, bucket_b in rep.channel:
            try:
                with prof.record_event(f"serving.batch:replica{rep.index}"):
                    out = rep.compiled(rep.variables, *slots)
                    out = jax.device_get(out)
            except Exception as e:  # complete, never hang the callers
                self.metrics.record_error(len(live))
                for req in live:
                    req.pending._fail(e)
                continue
            offset = 0
            now = time.monotonic()
            for req in live:
                req.pending._complete(
                    self._slice_out(out, bucket_b, offset, req.n)
                )
                self.metrics.record_response(now - req.t_submit)
                offset += req.n

    @staticmethod
    def _slice_out(out, bucket_b: int, offset: int, n: int):
        """Slice each batched output leaf back to one request's rows
        (non-batched leaves — scalars, globals — pass through whole)."""
        return jax.tree_util.tree_map(
            lambda leaf: leaf[offset : offset + n]
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == bucket_b
            else leaf,
            out,
        )

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful drain: stop intake, flush every accepted request through
        the device, then stop all threads. Idempotent."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._queue.close()  # batcher drains the buffer, flushes, exits
        self._batcher_thread.join(timeout)
        for rep in self._replicas:
            rep.channel.close()
        for rep in self._replicas:
            if rep.thread is not None:
                rep.thread.join(timeout)
        self.metrics.set_queue_depth(0)

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        self.close()
        return False

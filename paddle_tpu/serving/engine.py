"""ServingEngine: dynamically-batched, AOT-compiled TPU inference serving.

The reference stack served trained models through the Fluid inference
engine behind the gRPC ``listen_and_serv`` server; the TPU-native
replacement is built around what actually limits an XLA device under mixed
request load: compilation (one executable per shape) and occupancy (a
device running batch-1 requests is idle silicon).

Request path::

    submit(feed) ──▶ admission control (typed shedding, multi-tenant)
        ──▶ per-tenant queues / weighted-fair scheduler ──▶ MicroBatcher
        ──▶ shape-bucket groups, padded to (signature, batch bucket)
        ──▶ round-robin replica Channel ──▶ replica worker thread
        ──▶ Executor.prepare-cached executable on that device
        ──▶ per-request row slices complete each PendingResult

Key properties:

- **AOT warmup**: every (signature, batch-bucket) executable compiles at
  startup on every replica; steady-state traffic never waits on XLA.
- **Dynamic micro-batching**: max batch size + max queue delay, padding to
  shape buckets derived from ``FeedSpec`` (see ``serving.buckets``).
- **Replica round-robin**: one ``Executor`` per local device, each with its
  own resident copy of the variables; batches rotate across them.
- **Deadlines**: a request carries an absolute deadline; if it expires in
  the queue it gets a :class:`DeadlineExceeded` response without spending
  device time.
- **Backpressure / admission**: the request queue is bounded; without
  tenants ``submit`` blocks (or times out) when the engine is saturated
  instead of growing an unbounded queue. With tenants configured,
  admission control sheds early and typed instead of blocking — per-tenant
  quotas, deadline-feasibility prediction from observed latencies, and
  SLO-driven brownout (see ``serving.admission`` / ``serving.scheduler``).
- **Graceful drain**: ``close()`` stops intake, lets the batcher flush
  everything already accepted, waits for the replica workers, and only
  then returns — no accepted request is dropped.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from paddle_tpu.core import locks
from paddle_tpu import tracing
from paddle_tpu.concurrency import Channel, ChannelClosedError, go
from paddle_tpu.core import config as cfg
from paddle_tpu.core import logging as ptlog
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.enforce import EnforceError, enforce
from paddle_tpu.executor import Executor
from paddle_tpu.framework import Model, Variables, build
from paddle_tpu import observability
from paddle_tpu.core import retry as retry_mod
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import runlog
from paddle_tpu.reader.feeder import FeedSpec
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.circuit import CircuitBreaker
from paddle_tpu.serving import admission as admission_mod
from paddle_tpu.serving import scheduler as sched_mod
from paddle_tpu.serving.admission import AdmissionRejected, TenantConfig
from paddle_tpu.serving.batcher import Group, MicroBatcher
from paddle_tpu.serving.buckets import ShapeBuckets
from paddle_tpu.serving.metrics import ServingMetrics

__all__ = [
    "ServingEngine",
    "ServingConfig",
    "PendingResult",
    "DeadlineExceeded",
    "EngineClosedError",
    "ReplicaDied",
    "AdmissionRejected",
    "TenantConfig",
]


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before it reached a device."""


class EngineClosedError(RuntimeError):
    """submit() after close() — the engine no longer accepts requests."""


class ReplicaDied(RuntimeError):
    """The replica worker thread exited while this request was queued on
    its channel (and no healthy replica could take the batch)."""


@dataclasses.dataclass
class ServingConfig:
    """Batching/compilation policy knobs."""

    max_batch_size: int = 8
    # latency budget a request may wait for co-batching company
    max_queue_delay_s: float = 0.005
    # bounded request queue: submit blocks past this depth (backpressure)
    queue_capacity: int = 64
    # padded batch sizes compiled AOT; default powers of 2 up to max_batch
    batch_buckets: Optional[Sequence[int]] = None
    # padded lengths for ragged FeedSpec dims (required if any are ragged)
    length_buckets: Optional[Sequence[int]] = None
    # metric label distinguishing this engine's families in the registry /
    # scrape output; None = auto ("serving0", "serving1", ... per process)
    engine_label: Optional[str] = None
    # device replicas; None = every local device of the place's platform
    num_replicas: Optional[int] = None
    # compile every (signature, batch bucket) executable at startup
    warmup: bool = True
    # with warmup off, replay the persisted warmup manifest (the compiled
    # keys a previous process recorded — see paddle_tpu.tune.warmup)
    # before admitting traffic; None = the `prewarm` flag
    prewarm: Optional[bool] = None
    # abstract-trace the model through paddle_tpu.analysis.lint_model before
    # warm-up and log findings (never fatal); catches stale checkpoints,
    # sharding-rank mistakes and f64 leaks before paying compile time
    lint_model: bool = True
    # default per-request deadline; None = no deadline
    default_deadline_s: Optional[float] = None
    # -- replica health (resilience.circuit.CircuitBreaker per replica) ----
    # consecutive batch failures that eject a replica from rotation
    replica_failure_threshold: int = 3
    # cooldown before an ejected replica gets a half-open probe batch;
    # successive re-trips back off exponentially up to the max
    replica_cooldown_s: float = 1.0
    replica_max_cooldown_s: float = 30.0
    # flag a replica whose execute durations exceed the cross-replica
    # baseline by this ratio (None = the straggler_ratio flag; see
    # paddle_tpu.tracing.straggler)
    straggler_ratio: Optional[float] = None
    # -- watch layer (paddle_tpu.watch: anomaly detection + SLOs) ----------
    # attach a MetricWatcher/SloEngine to this engine's metric streams;
    # None = no watching (watch.WatchConfig(enabled=True) for defaults)
    watch: Optional[Any] = None
    # let a per-replica latency-anomaly alert trip that replica's circuit
    # breaker (same ejection path as consecutive failures) — requires a
    # watch config with the per-replica exec rule (on by default)
    anomaly_eject: bool = False
    # -- multi-tenant admission (serving.admission / serving.scheduler) ----
    # tenant set (admission.TenantConfig) for weighted-fair scheduling;
    # None = one implicit "default" tenant with legacy FIFO backpressure
    tenants: Optional[Sequence[TenantConfig]] = None
    # early typed shedding at submit() (AdmissionRejected); None = enabled
    # exactly when tenants are configured
    admission: Optional[bool] = None
    # guaranteed batch-class drain share under interactive pressure
    # (scheduler anti-starvation floor); None = the
    # PADDLE_TPU_TENANT_BATCH_MIN_SHARE flag
    batch_min_share: Optional[float] = None
    # minimum dwell in brownout before the SLO probe may exit it
    brownout_min_s: float = 1.0
    # per-engine retry budget for submit(retries=...): a token bucket so
    # client retry storms cannot amplify overload
    retry_budget_per_s: float = 8.0
    retry_budget_burst: float = 16.0
    # -- autoregressive decode (serving.decode.DecodeEngine) ---------------
    # KV-cache dtype for decode engines built over this config (e.g.
    # jnp.bfloat16 halves decode HBM traffic — the same lever generate()'s
    # cache_dtype exposes); None = f32. The static-batch path ignores it.
    cache_dtype: Optional[Any] = None


class PendingResult:
    """Future-like handle for one submitted request. ``trace`` carries the
    request's root :class:`~paddle_tpu.tracing.SpanContext` so callers can
    reconstruct the request's span tree (``tracing.spans_for_trace``) or
    propagate it onward (``trace.to_traceparent()``)."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self.trace: Optional[tracing.SpanContext] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def _complete(self, value) -> None:
        self._value = value
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()


class _Request:
    __slots__ = ("arrays", "n", "sig", "deadline", "t_submit", "pending",
                 "tenant", "cls", "bytes",
                 "trace", "t_enqueue_pc", "t_grouped_pc", "t_dispatch_pc")

    def __init__(self, arrays, n, sig, deadline, t_submit,
                 tenant="default", cls="interactive"):
        self.arrays = arrays
        self.n = n
        self.sig = sig
        self.deadline = deadline
        self.t_submit = t_submit
        self.tenant = tenant
        self.cls = cls
        self.bytes = sum(int(a.nbytes) for a in arrays)
        self.pending = PendingResult()
        # tracing: root context + perf_counter marks (t_submit stays on
        # time.monotonic for deadline math; spans share the profiler
        # timebase). t_dispatch_pc is stamped by the router BEFORE the
        # replica-channel send; the worker turns it into the
        # serving.dispatch span.
        self.trace: Optional[tracing.SpanContext] = None
        self.t_enqueue_pc: Optional[float] = None
        self.t_grouped_pc: Optional[float] = None
        self.t_dispatch_pc: Optional[float] = None


class _ReplicaPlace(cfg.Place):
    """Indexed place on any platform (CPUPlace carries no index; replicas
    need one per local device)."""

    def __init__(self, platform: str, device_id: int):
        self.platform = platform
        self.device_id = device_id

    def __repr__(self):
        return f"_ReplicaPlace({self.platform!r}, {self.device_id})"


class _Replica:
    __slots__ = (
        "index", "exe", "variables", "compiled", "channel", "thread",
        "breaker", "dead",
    )

    def __init__(self, index: int, exe: Executor, variables, compiled, channel, breaker):
        self.index = index
        self.exe = exe
        self.variables = variables
        self.compiled = compiled
        self.channel = channel
        self.thread = None
        self.breaker = breaker  # health gate: CLOSED/OPEN/HALF_OPEN
        self.dead = False       # worker thread exited abnormally


class ServingEngine:
    """Concurrent inference over a trained :class:`Model`.

    ::

        engine = ServingEngine(infer_net, variables, feed_specs)
        out = engine.infer({"x": batch})          # sync
        fut = engine.submit({"x": batch})          # async
        ...
        engine.close()                             # graceful drain
    """

    def __init__(
        self,
        model: Union[Model, Any],
        variables: Union[Variables, str],
        feed_specs: Sequence[FeedSpec],
        config: Optional[ServingConfig] = None,
        place: Optional[cfg.Place] = None,
    ):
        self.model = model if isinstance(model, Model) else build(model)
        if isinstance(variables, str):
            from paddle_tpu import io as io_mod

            variables = io_mod.load_params(variables)
        self.config = config or ServingConfig()
        self.specs = list(feed_specs)
        enforce(bool(self.specs), "feed_specs must be non-empty")
        self.buckets = ShapeBuckets(
            self.specs,
            self.config.max_batch_size,
            batch_buckets=self.config.batch_buckets,
            length_buckets=self.config.length_buckets,
        )
        self.metrics = ServingMetrics(engine_label=self.config.engine_label)
        observability.setup()  # flags-driven exporter/runlog, idempotent
        # cross-replica skew watch over per-batch execute durations
        self._straggler = tracing.StragglerDetector(
            "serving.execute", ratio=self.config.straggler_ratio
        )
        # watch layer: anomaly detectors / SLOs over this engine's metric
        # streams, attached via config (paddle_tpu.watch)
        self._watcher = None
        if self.config.watch is not None:
            from paddle_tpu import watch as watch_mod

            self._watcher = watch_mod.build(self.config.watch)
            if self._watcher is not None and self.config.anomaly_eject:
                self._watcher.hub.register_action(self._on_alert)
        self._closed = False
        self._close_lock = locks.Lock("serving.engine_close")
        self._rr = 0  # round-robin cursor (guarded by _pick_lock)
        # replica picking happens on the batcher thread AND on worker
        # threads redispatching a failed batch
        self._pick_lock = locks.Lock("serving.engine_pick")

        base_place = place or cfg.default_place()
        platform = base_place.platform
        local = [
            d
            for d in jax.devices()
            if cfg._platform_matches(d, platform)
        ] or jax.devices()
        n_rep = self.config.num_replicas or len(local)
        n_rep = max(1, min(n_rep, len(local)))

        def _fwd(vs, *arrays):
            out, _ = self.model.apply(vs, *arrays, is_train=False)
            return out

        self._fwd = _fwd

        self._replicas: List[_Replica] = []
        for i in range(n_rep):
            exe = Executor(_ReplicaPlace(platform, i))
            rep_vars = jax.device_put(variables, exe.device)
            compiled = exe.prepare(self._fwd, key=("serving", self.model.name, i))
            breaker = CircuitBreaker(
                failure_threshold=self.config.replica_failure_threshold,
                cooldown_s=self.config.replica_cooldown_s,
                max_cooldown_s=self.config.replica_max_cooldown_s,
            )
            self._replicas.append(
                _Replica(i, exe, rep_vars, compiled, Channel(capacity=2), breaker)
            )
        self.metrics.set_healthy_replicas(n_rep)

        if self.config.lint_model:
            self._lint_model(variables)
        if self.config.warmup:
            self._warmup()
        elif (self.config.prewarm if self.config.prewarm is not None
              else cfg.flags().prewarm):
            self.prewarm()

        # per-tenant queues + weighted-fair drain replace the old global
        # FIFO Channel; with no tenants configured one implicit "default"
        # tenant plus legacy_capacity reproduces the bounded-FIFO contract
        # (submit blocks on a full queue) exactly
        tenant_cfgs = [t.resolved() for t in (self.config.tenants or ())]
        if not tenant_cfgs:
            tenant_cfgs = [TenantConfig(
                "default", queue_capacity=self.config.queue_capacity,
            ).resolved()]
        self._tenants = {t.name: t for t in tenant_cfgs}
        self._default_tenant = (
            "default" if "default" in self._tenants else tenant_cfgs[0].name)
        admission_on = (self.config.admission
                        if self.config.admission is not None
                        else self.config.tenants is not None)
        self._queue = sched_mod.WeightedFairScheduler(
            self._tenants,
            quantum_rows=self.config.max_batch_size,
            batch_min_share=(self.config.batch_min_share
                             if self.config.batch_min_share is not None
                             else cfg.flags().tenant_batch_min_share),
            legacy_capacity=(None if admission_on
                             else self.config.queue_capacity),
            on_expired=self._expire,
        )
        self._retry_budget = admission_mod.TokenBucket(
            self.config.retry_budget_per_s, self.config.retry_budget_burst)
        self._admission: Optional[admission_mod.AdmissionController] = None
        if admission_on:
            self._admission = admission_mod.AdmissionController(
                self._queue, self.metrics, self._tenants,
                exec_snapshot=self._merged_exec_snapshot,
                healthy_replicas=self._count_healthy,
                slo_probe=self._slo_breached,
                brownout_min_s=self.config.brownout_min_s,
            )
            admission_mod.install(self._admission)
            if self._watcher is not None:
                # SLO burn-rate breaches drive brownout shedding
                self._watcher.hub.register_action(self._on_brownout_alert)
        self._batcher = MicroBatcher(
            self._queue,
            max_batch_rows=self.config.max_batch_size,
            max_delay_s=self.config.max_queue_delay_s,
            flush=self._dispatch,
            on_expired=self._expire,
        )
        for rep in self._replicas:
            rep.thread = go(self._worker, rep)
        self._batcher_thread = go(self._batcher.run)

    # -- startup -----------------------------------------------------------

    def _lint_model(self, variables) -> None:
        """Abstract-trace the model over the smallest warm-up signature and
        surface structural findings (stale params, sharding-rank mismatches,
        f64 leaks) in the log before compile time is spent. Best-effort:
        lint failure never blocks serving."""
        from paddle_tpu.core import logging as ptlog

        try:
            from paddle_tpu.analysis import lint_model as _lint

            sig = sorted(self.buckets.all_signatures())[0]
            rows = min(self.buckets.batch_buckets)
            diags = _lint(
                self.model, self._zeros_for(sig, rows),
                variables=variables, train=False,
            )
            for d in diags:
                ptlog.warn_once(
                    ("serving-model-lint", self.model.name, d.code, d.where),
                    "model lint [%s]: %s", d.code, str(d),
                )
        except Exception as e:  # pragma: no cover - defensive
            ptlog.warn_once(
                ("serving-model-lint-failed", self.model.name),
                "model lint skipped: %s", e,
            )

    def _zeros_for(self, sig, rows: int):
        return [
            np.zeros((rows,) + shape, dtype=spec.dtype)
            for spec, shape in zip(self.specs, sig)
        ]

    def _warmup(self) -> None:
        """AOT-compile every (signature, batch bucket) on every replica so
        live traffic never pays XLA compile latency. Every warmed key is
        recorded into the persistent warmup manifest (paddle_tpu.tune) so
        a restarted process can :meth:`prewarm` the same set."""
        from paddle_tpu.tune import warmup as tune_warmup

        with prof.record_event("serving.warmup"):
            for sig in self.buckets.all_signatures():
                for b in self.buckets.batch_buckets:
                    args = self._zeros_for(sig, b)
                    for rep in self._replicas:
                        out = rep.compiled(rep.variables, *args)
                        jax.device_get(out)  # force the compile + run
                        self.metrics.record_warmup()
                    tune_warmup.record_compile(
                        self.model.name, "serving", save=False,
                        sig=[list(s) for s in sig], bucket=int(b))
        self._save_manifest()

    def _save_manifest(self) -> None:
        from paddle_tpu.tune import warmup as tune_warmup

        path = tune_warmup.manifest_path(self.model.name)
        if path:
            try:
                tune_warmup.get_manifest(self.model.name, path).save()
            except Exception as e:  # never let bookkeeping fail startup
                ptlog.warning("warmup manifest save failed: %s", e)

    def prewarm(self) -> int:
        """Replay the persisted warmup manifest — compile every (signature,
        bucket) key a previous process recorded — before traffic is
        admitted. With the JAX persistent compilation cache populated each
        replay is a disk hit, so a restarted server's ``compile_seconds``
        collapses to near-zero. Entries that no longer match the current
        bucket config are skipped. Returns the number of keys replayed."""
        from paddle_tpu.tune import warmup as tune_warmup

        manifest = tune_warmup.get_manifest(self.model.name)
        valid_sigs = set(self.buckets.all_signatures())
        valid_buckets = set(self.buckets.batch_buckets)
        n = 0
        with prof.record_event("serving.prewarm"):
            for ent in manifest.entries("serving"):
                try:
                    sig = tuple(tuple(int(x) for x in s) for s in ent["sig"])
                    b = int(ent["bucket"])
                except Exception:
                    continue
                if sig not in valid_sigs or b not in valid_buckets:
                    continue
                args = self._zeros_for(sig, b)
                for rep in self._replicas:
                    jax.device_get(rep.compiled(rep.variables, *args))
                    self.metrics.record_warmup()
                n += 1
        if n:
            prof.inc_counter("tune.prewarm.replayed_total", n)
            runlog.emit("tune", phase="prewarm", engine="serving",
                        model=self.model.name, keys=n)
        return n

    def aot_cache_sizes(self) -> List[int]:
        """Per-replica count of compiled executables inside the jitted
        forward (−1 when jax doesn't expose it). Steady after warmup ⇒ no
        request ever triggered a fresh compile."""
        return [
            rep.compiled._cache_size()
            if hasattr(rep.compiled, "_cache_size")
            else -1
            for rep in self._replicas
        ]

    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    # -- request intake ----------------------------------------------------

    def _normalize_feed(self, feed) -> Tuple[np.ndarray, ...]:
        """feed → per-slot arrays in FeedSpec order. Dict feeds are looked
        up BY NAME (never by insertion order); sequences must already be in
        spec order."""
        if isinstance(feed, dict):
            missing = [s.name for s in self.specs if s.name not in feed]
            if missing:
                raise EnforceError(f"feed missing slots {missing}")
            arrays = [feed[s.name] for s in self.specs]
        else:
            if not isinstance(feed, (tuple, list)):
                feed = (feed,)  # bare array = the single feed slot
            enforce(
                len(feed) == len(self.specs),
                f"expected {len(self.specs)} feed slots, got {len(feed)}",
            )
            arrays = list(feed)
        return tuple(
            np.asarray(a, dtype=spec.dtype)
            for a, spec in zip(arrays, self.specs)
        )

    def submit(
        self,
        feed,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
        tenant: Optional[str] = None,
        cls: Optional[str] = None,
        retries: int = 0,
        backoff: float = 0.01,
    ) -> PendingResult:
        """Enqueue one request (arrays carry a leading batch dim). Returns a
        :class:`PendingResult`.

        Without admission control the bounded queue applies backpressure:
        submit blocks while full, ``timeout`` bounds that wait
        (TimeoutError = backpressure rejection). With tenants configured,
        submit never blocks — it raises :class:`AdmissionRejected` with a
        typed reason instead. An already-expired ``deadline_s`` (<= 0) is
        rejected here as :class:`DeadlineExceeded`, before it can occupy a
        queue slot.

        ``tenant``/``cls`` attribute the request for scheduling (defaults:
        the "default" tenant — or the first configured one — and that
        tenant's default class). ``retries > 0`` retries rejections
        (AdmissionRejected / backpressure TimeoutError, never
        DeadlineExceeded) with jittered exponential backoff starting at
        ``backoff`` seconds, capped by the per-engine retry-budget token
        bucket so storms cannot amplify overload.
        """
        enforce(retries >= 0, f"retries must be >= 0, got {retries}")
        attempt = 0
        while True:
            try:
                return self._submit_once(feed, deadline_s, timeout,
                                         tenant, cls)
            except (AdmissionRejected, TimeoutError) as e:
                if isinstance(e, DeadlineExceeded) or attempt >= retries:
                    raise
                if not self._retry_budget.try_take():
                    self.metrics.record_retry_budget_exhausted()
                    raise
                self.metrics.record_retry()
                time.sleep(retry_mod.next_backoff(
                    attempt, base_delay=backoff, max_delay=1.0))
                attempt += 1

    def _submit_once(
        self,
        feed,
        deadline_s: Optional[float],
        timeout: Optional[float],
        tenant: Optional[str],
        cls: Optional[str],
    ) -> PendingResult:
        if self._closed:
            raise EngineClosedError("engine is closed")
        arrays = self._normalize_feed(feed)
        rows = {int(a.shape[0]) for a in arrays if a.ndim > 0}
        enforce(len(rows) == 1, f"feed slots disagree on batch dim: {rows}")
        n = rows.pop()
        enforce(
            1 <= n <= self.config.max_batch_size,
            f"request rows {n} outside [1, {self.config.max_batch_size}]",
        )
        sig = self.buckets.signature([a.shape[1:] for a in arrays])
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            # already dead on arrival: reject without burning a queue slot
            self.metrics.record_timeout()
            raise DeadlineExceeded(
                f"deadline {deadline_s}s already expired at submit")
        deadline = None if deadline_s is None else now + deadline_s
        tname = tenant if tenant is not None else self._default_tenant
        tcfg = self._tenants.get(tname)
        rcls = cls if cls is not None else (
            tcfg.default_class if tcfg is not None
            else cfg.flags().tenant_default_class)
        enforce(rcls in sched_mod.CLASSES,
                f"unknown priority class {rcls!r} "
                f"(expected one of {sched_mod.CLASSES})")
        if self._admission is None:
            # admission rejects unknown tenants with a typed reason; the
            # legacy blocking path has no shed channel, so refuse up front
            enforce(tcfg is not None,
                    f"unknown tenant {tname!r} "
                    f"(configured: {sorted(self._tenants)})")
        req = _Request(arrays, n, sig, deadline, now, tenant=tname, cls=rcls)
        if tracing.tracing_enabled():
            req.trace = tracing.SpanContext.new_trace()
            req.pending.trace = req.trace
            req.t_enqueue_pc = time.perf_counter()
        try:
            if self._admission is not None:
                # never blocks: quota/deadline/brownout shedding raises a
                # typed AdmissionRejected instead of parking the caller
                self._admission.admit(req)
            else:
                self._queue.send(req, timeout=timeout)
        except ChannelClosedError:
            raise EngineClosedError("engine is closed") from None
        except AdmissionRejected:
            if req.trace is not None:
                self._finish_trace(req, time.perf_counter(), status="shed")
            raise
        if req.trace is not None:
            # the enqueue span covers any backpressure wait on the bounded
            # channel — visible queue-pressure in the request's own trace
            tracing.record_span(
                "serving.enqueue", req.t_enqueue_pc, time.perf_counter(),
                parent=req.trace, rows=n, tenant=tname, cls=rcls,
            )
        # counted only once accepted: a backpressure rejection (TimeoutError
        # above) never shows up as a request that went missing
        self.metrics.record_submit(n, self._queue.qsize())
        return req.pending

    def infer(self, feed, deadline_s: Optional[float] = None, **kwargs):
        """Synchronous request: submit + wait. Raises
        :class:`DeadlineExceeded` if the deadline expires in the queue.
        Extra kwargs (``tenant``, ``cls``, ``retries``...) pass through to
        :meth:`submit`."""
        return self.submit(feed, deadline_s=deadline_s, **kwargs).result()

    # -- batching / dispatch (batcher thread) ------------------------------

    def _finish_trace(self, req: _Request, t1_pc: float, **attrs) -> None:
        """Record the request's ROOT span (serving.request) — every
        completion path runs through exactly one of the three callers
        (worker success, _expire, _fail_requests), always before the
        PendingResult is released so a caller that checks the trace right
        after result() finds it complete."""
        if req.trace is None:
            return
        tracing.record_span(
            "serving.request", req.t_enqueue_pc, t1_pc, context=req.trace,
            rows=req.n, engine=self.metrics.engine_label,
            tenant=req.tenant, cls=req.cls, **attrs,
        )

    def _expire(self, req: _Request) -> None:
        self.metrics.record_timeout()
        if req.trace is not None:
            now_pc = time.perf_counter()
            tracing.record_span(
                "serving.queue_wait", req.t_enqueue_pc, now_pc,
                parent=req.trace,
            )
            self._finish_trace(req, now_pc, status="deadline_exceeded")
        req.pending._fail(
            DeadlineExceeded(
                f"request expired after {time.monotonic() - req.t_submit:.3f}s in queue"
            )
        )

    def _dispatch(self, group: Group) -> None:
        """Pad one signature group to its batch bucket and round-robin it to
        a replica. Runs on the batcher thread; a busy replica channel blocks
        here, which is the intended backpressure toward the request queue."""
        live = []
        now = time.monotonic()
        for req in group.requests:
            if req.deadline is not None and now > req.deadline:
                self._expire(req)
            else:
                live.append(req)
        if not live:
            return
        t_pad0 = time.perf_counter()
        for req in live:
            if req.trace is not None:
                # queue wait = submit → the moment the batcher grouped it
                tracing.record_span(
                    "serving.queue_wait", req.t_enqueue_pc,
                    req.t_grouped_pc if req.t_grouped_pc is not None else t_pad0,
                    parent=req.trace,
                )
        rows = sum(r.n for r in live)
        bucket_b = self.buckets.batch_bucket(rows)
        slots = []
        for j in range(len(self.specs)):
            per_req = [
                self.buckets.pad_to_signature([r.arrays[j]], group.sig[j : j + 1])[0]
                for r in live
            ]
            col = per_req[0] if len(per_req) == 1 else np.concatenate(per_req, axis=0)
            slots.append(col)
        slots = self.buckets.pad_rows(slots, bucket_b)
        t_pad1 = time.perf_counter()
        for req in live:
            if req.trace is not None:
                tracing.record_span(
                    "serving.pad", t_pad0, t_pad1, parent=req.trace,
                    bucket_rows=bucket_b,
                )
        self.metrics.record_batch(rows, bucket_b, group.sig)
        self.metrics.set_queue_depth(self._queue.qsize())
        self.metrics.set_tenant_depths(self._queue.depths())
        self._send_to_replica(live, slots, bucket_b, attempt=0)

    def _pick_replica(self, exclude: Optional[_Replica] = None) -> Optional[_Replica]:
        """Next replica in round-robin order whose breaker admits a batch.
        When EVERY live breaker is open mid-cooldown, degrade: force a
        half-open probe on the one closest to its retry time — serving at
        reduced health beats failing all traffic. None = no live replicas."""
        with self._pick_lock:
            alive = [r for r in self._replicas if not r.dead and r is not exclude]
            if not alive:
                return None
            n = len(self._replicas)
            for k in range(n):
                rep = self._replicas[(self._rr + k) % n]
                if rep.dead or rep is exclude:
                    continue
                if rep.breaker.allow():
                    self._rr = (self._rr + k + 1) % n
                    return rep
            rep = min(alive, key=lambda r: r.breaker.retry_in())
            rep.breaker.force_allow()
            return rep

    def _send_to_replica(self, live, slots, bucket_b: int, attempt: int) -> None:
        """Route one padded batch to a healthy replica; a replica dying
        between pick and send is retried against the others. With no live
        replica left, the callers fail instead of hanging."""
        t0 = time.perf_counter()
        for req in live:
            # stamped BEFORE the send: the send wakes the worker, which can
            # complete the request before this thread runs again, so the
            # worker itself records serving.dispatch (see _worker_loop) to
            # keep every span committed ahead of the result release
            req.t_dispatch_pc = t0
        exclude = None
        for _ in range(len(self._replicas)):
            rep = self._pick_replica(exclude=exclude)
            if rep is None:
                break
            try:
                rep.channel.send((live, slots, bucket_b, attempt))
                return
            except ChannelClosedError:
                exclude = rep  # died between pick and send
        self._fail_requests(live, ReplicaDied("no healthy replicas available"))

    def _fail_requests(self, live, exc: BaseException) -> None:
        self.metrics.record_error(len(live))
        now_pc = time.perf_counter()
        for req in live:
            self._finish_trace(req, now_pc, status="error",
                               error=type(exc).__name__)
            req.pending._fail(exc)

    # -- execution (replica worker threads) --------------------------------

    def _worker(self, rep: _Replica) -> None:
        """Replica thread wrapper: ANY exit of the loop itself — including
        BaseException (KeyboardInterrupt, MemoryError, a bug in the loop) —
        marks the replica dead and fails everything queued on its channel,
        so no caller ever hangs on a worker that silently died."""
        try:
            self._worker_loop(rep)
        except BaseException as e:
            self._replica_died(rep, e)

    def _worker_loop(self, rep: _Replica) -> None:
        for live, slots, bucket_b, attempt in rep.channel:
            t_exec0 = time.perf_counter()
            for req in live:
                if req.trace is not None and req.t_dispatch_pc is not None:
                    # covers replica pick + the wait on this worker's
                    # channel; recorded here rather than by the router so
                    # it cannot land after the request's result is released
                    tracing.record_span(
                        "serving.dispatch", req.t_dispatch_pc, t_exec0,
                        parent=req.trace, replica=rep.index, attempt=attempt,
                    )
            try:
                # fault point: a seeded "error" here exercises the breaker
                # exactly like a real device failure would
                faults.inject(faults.SERVING_DISPATCH, replica=rep.index)
                with prof.record_event(f"serving.batch.replica{rep.index}"):
                    out = rep.compiled(rep.variables, *slots)
                    out = jax.device_get(out)
            except Exception as e:  # complete, never hang the callers
                self._batch_failed(rep, live, slots, bucket_b, attempt, e)
                continue
            except BaseException as e:
                # the worker is about to die (KeyboardInterrupt, MemoryError,
                # SystemExit): the in-flight batch must fail, not hang
                self._fail_requests(
                    live, ReplicaDied(f"replica {rep.index} worker died: {e!r}")
                )
                raise
            if rep.breaker.record_success():
                self.metrics.record_replica_recovery()
                runlog.emit("breaker_close", replica=rep.index,
                            engine=self.metrics.engine_label)
                ptlog.vlog(
                    0, "serving replica %d recovered (half-open probe ok)",
                    rep.index,
                )
                self.metrics.set_healthy_replicas(self._count_healthy())
            t_exec1 = time.perf_counter()
            for req in live:
                if req.trace is not None:
                    tracing.record_span(
                        "serving.execute", t_exec0, t_exec1, parent=req.trace,
                        replica=rep.index, attempt=attempt,
                        bucket_rows=bucket_b,
                    )
            self._straggler.record(f"replica{rep.index}", t_exec1 - t_exec0)
            self.metrics.record_exec(rep.index, t_exec1 - t_exec0)
            offset = 0
            now = time.monotonic()
            for req in live:
                sliced = self._slice_out(out, bucket_b, offset, req.n)
                t_reply = time.perf_counter()
                if req.trace is not None:
                    tracing.record_span(
                        "serving.reply", t_exec1, t_reply, parent=req.trace,
                    )
                # root span lands BEFORE the result is released: a caller
                # inspecting the trace right after result() sees it complete
                self._finish_trace(req, t_reply, status="ok",
                                   replica=rep.index)
                req.pending._complete(sliced)
                self.metrics.record_response(now - req.t_submit)
                self.metrics.record_tenant_response(
                    req.tenant, req.cls, now - req.t_submit)
                offset += req.n

    def _batch_failed(
        self, rep: _Replica, live, slots, bucket_b: int, attempt: int,
        exc: Exception,
    ) -> None:
        """One batch failed on ``rep``: charge its breaker and give the
        batch ONE redispatch to a different healthy replica (a sick device
        must not fail callers a healthy one could serve) before failing the
        callers for real."""
        if rep.breaker.record_failure():
            self.metrics.record_replica_ejection()
            runlog.emit("breaker_open", replica=rep.index,
                        engine=self.metrics.engine_label, error=repr(exc))
            ptlog.error(
                "serving replica %d ejected after %d consecutive failures "
                "(retry in %.2fs): %s",
                rep.index, rep.breaker.consecutive_failures,
                rep.breaker.retry_in(), exc,
            )
            self.metrics.set_healthy_replicas(self._count_healthy())
        if attempt == 0:
            target = self._pick_replica(exclude=rep)
            if target is not None:
                t0 = time.perf_counter()
                for req in live:
                    req.t_dispatch_pc = t0  # target worker records the span
                try:
                    target.channel.send((live, slots, bucket_b, 1), timeout=5.0)
                    t1 = time.perf_counter()
                    for req in live:
                        if req.trace is not None:
                            tracing.record_span(
                                "serving.redispatch", t0, t1,
                                parent=req.trace, from_replica=rep.index,
                                to_replica=target.index,
                                error=type(exc).__name__,
                            )
                    self.metrics.record_redispatch()
                    return
                except (ChannelClosedError, TimeoutError):
                    pass  # target gone/wedged: fall through to failing
        self._fail_requests(live, exc)

    def _replica_died(self, rep: _Replica, exc: BaseException) -> None:
        """Permanently remove a replica whose worker thread is gone; every
        batch still queued on its channel fails (or redispatches via the
        batcher's next pick — they are failed here to stay bounded)."""
        rep.dead = True
        self.metrics.record_replica_death()
        runlog.emit("replica_died", replica=rep.index,
                    engine=self.metrics.engine_label, error=repr(exc))
        self.metrics.set_healthy_replicas(self._count_healthy())
        ptlog.error("serving replica %d worker died: %r", rep.index, exc)
        rep.channel.close()
        while True:  # drain: nothing queued may hang its caller (a closed
            item, ok = rep.channel.recv()  # channel's recv never blocks)
            if not ok:
                break
            self._fail_requests(
                item[0], ReplicaDied(f"replica {rep.index} worker died: {exc!r}")
            )

    def _on_alert(self, alert) -> None:
        """Alert-hub action (``anomaly_eject=True``): a per-replica latency
        anomaly trips that replica's breaker — the same ejection/backoff/
        half-open-probe path consecutive FAILURES take, but driven by the
        watch layer's latency detector instead of errors. Never ejects the
        last healthy replica: degraded-but-slow beats down."""
        if alert.source != "watch.serving.replica_exec_seconds":
            return
        if alert.labels.get("engine") != self.metrics.engine_label:
            return
        try:
            index = int(alert.labels.get("replica", ""))
        except ValueError:
            return
        healthy = [r for r in self._replicas
                   if not r.dead and r.breaker.state == "closed"]
        for rep in self._replicas:
            if rep.index != index or rep.dead:
                continue
            if len(healthy) <= 1 and rep in healthy:
                ptlog.warn_once(
                    ("anomaly-eject-last", self.metrics.engine_label, index),
                    "not ejecting replica %d on latency anomaly: it is the "
                    "last healthy replica", index)
                return
            if rep.breaker.trip():
                self.metrics.record_replica_ejection()
                runlog.emit("breaker_open", replica=rep.index,
                            engine=self.metrics.engine_label,
                            error=f"latency anomaly: {alert.message}")
                ptlog.error(
                    "serving replica %d ejected on latency anomaly "
                    "(retry in %.2fs): %s",
                    rep.index, rep.breaker.retry_in(), alert.message)
                self.metrics.set_healthy_replicas(self._count_healthy())
            return

    def _count_healthy(self) -> int:
        return sum(
            1 for r in self._replicas if not r.dead and r.breaker.state == "closed"
        )

    # -- admission / brownout ----------------------------------------------

    def _merged_exec_snapshot(self) -> Optional[dict]:
        """All replicas' execute-latency histograms merged into one
        distribution — the admission controller's deadline-feasibility
        input (registry.quantile reads a single child; exec latencies are
        labeled per replica)."""
        reg = obs_metrics.default_registry()
        return admission_mod.merge_histogram_snapshots([
            reg.histogram_snapshot(
                "serving.replica_exec_seconds",
                {"engine": self.metrics.engine_label,
                 "replica": str(rep.index)})
            for rep in self._replicas
        ])

    def _slo_breached(self) -> bool:
        """Brownout exit probe: True while any SLO on this engine's watcher
        still reports a breach."""
        if self._watcher is None or self._watcher.slo_engine is None:
            return False
        return any(s.get("breached") for s in
                   self._watcher.slo_engine.status())

    def _on_brownout_alert(self, alert) -> None:
        """Alert-hub action (admission enabled): an SLO burn-rate breach on
        this engine enters brownout — warning sheds batch admission,
        critical sheds everything. Exit happens via the probe path in the
        admission controller, not here (alerts are edge-triggered)."""
        if not alert.source.startswith("slo."):
            return
        eng = alert.labels.get("engine")
        if eng is not None and eng != self.metrics.engine_label:
            return
        if self._admission is not None:
            self._admission.enter_brownout(alert.severity,
                                           reason=alert.source)

    @property
    def admission(self) -> Optional[admission_mod.AdmissionController]:
        return self._admission

    def set_brownout(self, severity: str = "warning",
                     reason: str = "manual") -> None:
        """Manually enter brownout (operator override / tests / chaos
        drills) — same shedding path an SLO alert takes."""
        enforce(self._admission is not None,
                "set_brownout requires admission control (configure tenants)")
        self._admission.enter_brownout(severity, reason)

    def clear_brownout(self) -> None:
        if self._admission is not None:
            self._admission.exit_brownout()

    def replica_health(self) -> List[dict]:
        """Per-replica health readout: breaker state + lifetime counters."""
        return [
            dict(index=r.index, dead=r.dead, **r.breaker.snapshot())
            for r in self._replicas
        ]

    @staticmethod
    def _slice_out(out, bucket_b: int, offset: int, n: int):
        """Slice each batched output leaf back to one request's rows
        (non-batched leaves — scalars, globals — pass through whole)."""
        return jax.tree_util.tree_map(
            lambda leaf: leaf[offset : offset + n]
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == bucket_b
            else leaf,
            out,
        )

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: Optional[float] = None) -> List[str]:
        """Graceful drain: stop intake, flush every accepted request through
        the device, then stop all threads. Idempotent. Returns the names of
        threads that did NOT join within ``timeout`` (empty list = clean
        shutdown) — a wedged worker must be reported, not silently leaked."""
        with self._close_lock:
            if self._closed:
                return []
            self._closed = True
        unjoined: List[str] = []
        self._queue.close()  # batcher drains the buffer, flushes, exits
        self._batcher_thread.join(timeout)
        if self._batcher_thread.is_alive():
            unjoined.append(self._batcher_thread.name)
        for rep in self._replicas:
            rep.channel.close()
        for rep in self._replicas:
            if rep.thread is not None:
                rep.thread.join(timeout)
                if rep.thread.is_alive():
                    unjoined.append(rep.thread.name)
        if unjoined:
            ptlog.error(
                "ServingEngine.close: %d thread(s) failed to join within %s: %s",
                len(unjoined), timeout, ", ".join(unjoined),
            )
        self.metrics.set_queue_depth(0)
        if self._admission is not None:
            admission_mod.uninstall(self._admission)
            if self._watcher is not None:
                self._watcher.hub.unregister_action(self._on_brownout_alert)
        if self._watcher is not None:
            self._watcher.hub.unregister_action(self._on_alert)
            if self._watcher.slo_engine is not None:
                from paddle_tpu.watch import slo as _slo

                _slo.uninstall(self._watcher.slo_engine)
            self._watcher.close()
            self._watcher = None
        return unjoined

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        self.close()
        return False

"""Hierarchical KV: a bounded host-RAM page pool behind the radix
prefix cache, doubling as the fleet's crash-recovery substrate.

The radix prefix cache (``serving.prefix_cache``) is bounded by one
chip's HBM page pool and private to one engine: an evicted system prompt
re-prefills from scratch, two engines never share a warm prefix, and
when an engine dies its whole warm tree dies with it. The reference
framework's memory layer exists for exactly this shape of problem — its
buddy allocator spans CPUPlace/CUDAPinnedPlace so hot device state can
stage through host RAM. :class:`HostPagePool` is that tier for KV pages:

- **Demote (write-through).** When an engine publishes a finished
  prefill into its radix tree it also gathers the fully-written pages
  off-device and stores them here, keyed by the page-aligned token
  prefix they encode. Eviction from the radix tree therefore costs
  nothing extra — the evicted page's bytes are already resident in the
  host tier.
- **Promote (asynchronous).** On a radix miss whose continuation the
  pool holds, the engine enqueues a promote job and answers the request
  by prefilling as usual (token-exact either way). The loop thread
  applies a bounded number of promotions per iteration off the step
  path: allocate a device page, implant the host bytes, insert into the
  tree — the NEXT request with that prefix hits in HBM.
- **Integrity.** Every stored page carries a CRC32 per K/V blob —
  the same self-validating discipline as
  :class:`~paddle_tpu.serving.disagg.HandoffPayload`. A bit-flipped
  host page fails verification at promote time and is quarantined
  (dropped + counted), and the request re-prefills token-exactly rather
  than trusting corrupt KV state.
- **Recovery.** Because demotion is write-through for completed prefill
  pages, a pool SHARED across a fleet survives any one engine's
  ``kill()``: after journal replay, the restarted (or surviving) engine
  repopulates its radix tree from the host tier instead of re-prefilling
  the world — the recovery ladder's adopt-from-host-tier rung, between
  "re-prefill locally" and "migrate".

Unlike the allocator and the radix tree (single-loop-thread state), the
pool is shared across engines and therefore thread-safe: one named
``core.locks`` lock guards the entry map. CRC computation and
verification run OUTSIDE the lock — blobs are immutable ``bytes``, so a
reader can validate its snapshot lock-free and a stall injected on the
demote path never extends the lock hold.

Keys are the full page-aligned token prefixes (exact-match by
construction — no hash collision can alias two prompts onto one page).
:func:`prefix_digests` derives the compact per-prefix digests the
prefix-aware fleet routing publishes and matches on.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.core import locks
from paddle_tpu.core.enforce import enforce
from paddle_tpu.resilience import faults

__all__ = ["HostPagePool", "HostPage", "HostPageCorrupt", "prefix_digests"]


class HostPageCorrupt(RuntimeError):
    """A host page failed CRC verification at promote time (bit-flipped
    host memory, or the injected corrupt-on-promote fault). The entry is
    already quarantined when this raises — the caller must re-prefill
    the span token-exactly instead of adopting the page."""


def prefix_digests(tokens: Sequence[int], page_size: int) -> List[int]:
    """Running CRC32 digest per page-aligned token prefix of ``tokens``:
    ``out[i]`` identifies ``tokens[:(i+1) * page_size]``. The compact
    form engines publish for prefix-aware routing — a fleet compares a
    prompt's digest chain against each engine's published set and routes
    to the longest match."""
    ps = int(page_size)
    enforce(ps >= 1, f"page_size must be >= 1, got {ps}")
    arr = np.asarray(tokens, np.int32).reshape(-1)
    out: List[int] = []
    crc = 0
    for i in range(len(arr) // ps):
        crc = zlib.crc32(arr[i * ps:(i + 1) * ps].tobytes(), crc)
        out.append(crc & 0xFFFFFFFF)
    return out


class HostPage:
    """One demoted KV page: the K and V blobs for ``page_size`` tokens,
    each CRC-protected, keyed by the exact token prefix they encode."""

    __slots__ = ("key", "k_blob", "v_blob", "k_crc", "v_crc",
                 "shape", "dtype", "nbytes")

    def __init__(self, key: Tuple[int, ...], k_blob: bytes, v_blob: bytes,
                 shape: Tuple[int, ...], dtype: str):
        self.key = key
        self.k_blob = k_blob
        self.v_blob = v_blob
        self.k_crc = zlib.crc32(k_blob) & 0xFFFFFFFF
        self.v_crc = zlib.crc32(v_blob) & 0xFFFFFFFF
        self.shape = tuple(int(d) for d in shape)
        self.dtype = str(dtype)
        self.nbytes = len(k_blob) + len(v_blob)


class HostPagePool:
    """Byte-bounded LRU store of demoted KV pages, shared across a
    fleet. Thread-safe (named lock); CRC verify/compute stay outside the
    lock. ``page_size`` pins the geometry — a pool never serves an
    engine with a different page size (the caller enforces via
    :meth:`compatible`)."""

    def __init__(self, max_bytes: int, page_size: int):
        enforce(max_bytes > 0, f"max_bytes must be > 0, got {max_bytes}")
        enforce(page_size >= 1, f"page_size must be >= 1, got {page_size}")
        self.max_bytes = int(max_bytes)
        self.page_size = int(page_size)
        self._lock = locks.Lock("serving.host_tier")
        self._entries: "OrderedDict[Tuple[int, ...], HostPage]" = OrderedDict()
        self._bytes = 0
        # counters (read via stats(); the engine mirrors them into
        # serving.host_tier.* metric families)
        self.puts_total = 0
        self.hits_total = 0
        self.misses_total = 0
        self.evicted_total = 0
        self.quarantined_total = 0
        self.backpressure_total = 0

    # -- geometry ----------------------------------------------------------

    def compatible(self, page_size: int) -> bool:
        return int(page_size) == self.page_size

    @staticmethod
    def _key(tokens: Sequence[int], n_pages: int,
             page_size: int) -> Tuple[int, ...]:
        arr = np.asarray(tokens, np.int32).reshape(-1)
        return tuple(int(t) for t in arr[:n_pages * page_size])

    # -- readout -----------------------------------------------------------

    @property
    def num_pages(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pages": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "puts": self.puts_total,
                "hits": self.hits_total,
                "misses": self.misses_total,
                "evicted": self.evicted_total,
                "quarantined": self.quarantined_total,
                "backpressure": self.backpressure_total,
            }

    def contains(self, tokens: Sequence[int], n_pages: int) -> bool:
        """Does the pool hold the ``n_pages``-th page of this prefix
        (page index ``n_pages - 1``)? Cheap probe used at admission to
        decide whether a promote job is worth enqueueing."""
        key = self._key(tokens, n_pages, self.page_size)
        if len(key) < n_pages * self.page_size:
            return False
        with self._lock:
            return key in self._entries

    # -- demote (write-through insert) -------------------------------------

    def put(self, tokens: Sequence[int], page_index: int,
            k_page: np.ndarray, v_page: np.ndarray,
            **ctx) -> Dict[str, int]:
        """Store logical page ``page_index`` of the page-aligned prefix
        of ``tokens``. Returns ``{"added": 0|1, "evicted": n}`` —
        ``added=0`` means the page was already resident (dedup:
        re-demoting a shared system prompt is a no-op).

        Inserting past ``max_bytes`` LRU-evicts; when the insert itself
        triggered eviction, the demote-backpressure counter bumps — a
        sustained climb means the working set outgrew the tier (the
        ``watch`` rule subscribes to the mirrored metric family)."""
        # chaos: stall-on-demote fires HERE, before the lock — a slow
        # host tier must never extend the pool's lock hold
        faults.inject(faults.HOST_TIER, op="demote", **ctx)
        key = self._key(tokens, page_index + 1, self.page_size)
        enforce(len(key) == (page_index + 1) * self.page_size,
                f"put: page {page_index} needs "
                f"{(page_index + 1) * self.page_size} tokens, "
                f"got {len(key)}")
        k = np.ascontiguousarray(k_page)
        v = np.ascontiguousarray(v_page)
        enforce(k.shape == v.shape,
                f"put: K/V shape mismatch {k.shape} vs {v.shape}")
        entry = HostPage(key, k.tobytes(), v.tobytes(), k.shape,
                         str(k.dtype))
        enforce(entry.nbytes <= self.max_bytes,
                f"put: one page ({entry.nbytes}B) exceeds the pool "
                f"budget ({self.max_bytes}B)")
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return {"added": 0, "evicted": 0}
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self.puts_total += 1
            evicted = 0
            while self._bytes > self.max_bytes:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                evicted += 1
            if evicted:
                self.evicted_total += evicted
                self.backpressure_total += 1
        return {"added": 1, "evicted": evicted}

    # -- promote (verified read) -------------------------------------------

    def get(self, tokens: Sequence[int], page_index: int,
            **ctx) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Fetch logical page ``page_index`` of the prefix, CRC-verified.
        Returns ``(k_page, v_page)`` or None on a miss. A CRC mismatch
        (bit-flipped host memory — or the injected corrupt-on-promote
        fault) quarantines the entry (dropped + counted) and raises
        :class:`HostPageCorrupt` — the caller re-prefills token-exactly
        instead of trusting it."""
        key = self._key(tokens, page_index + 1, self.page_size)
        if len(key) < (page_index + 1) * self.page_size:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses_total += 1
                return None
            self._entries.move_to_end(key)
        # chaos: corrupt-on-promote ("nan" kind) — the fetched bytes are
        # poisoned BEFORE verification, so the CRC check must catch it.
        # Injected on the HIT path only (after the lookup, outside the
        # lock): the fault models the host-memory copy, which a miss
        # never performs — and hit-only firing keeps ``times=N`` specs
        # deterministic for the chaos harness.
        spec = faults.inject(faults.HOST_TIER, op="promote", **ctx)
        k_blob, v_blob = entry.k_blob, entry.v_blob
        if spec is not None and spec.kind == "nan":
            k_blob = bytes([k_blob[0] ^ 0xFF]) + k_blob[1:]
        # verify OUTSIDE the lock: blobs are immutable bytes
        if (zlib.crc32(k_blob) & 0xFFFFFFFF) != entry.k_crc or \
                (zlib.crc32(v_blob) & 0xFFFFFFFF) != entry.v_crc:
            self.quarantine(key)
            raise HostPageCorrupt(
                f"host page for prefix of {len(key)} tokens failed CRC "
                f"verification; quarantined")
        with self._lock:
            self.hits_total += 1
        dtype = np.dtype(entry.dtype)
        k = np.frombuffer(k_blob, dtype=dtype).reshape(entry.shape)
        v = np.frombuffer(v_blob, dtype=dtype).reshape(entry.shape)
        return k, v

    def quarantine(self, key: Tuple[int, ...]) -> None:
        """Drop one entry as untrusted (CRC mismatch). Idempotent."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry.nbytes
                self.quarantined_total += 1

    def clear(self) -> int:
        """Drop every entry (tests / operator reset). Returns the number
        dropped. NOT called by engine ``kill()``/``close()`` — the whole
        point of the tier is surviving an engine's death."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            return n

"""Paged KV cache: fixed-size pages + per-sequence page tables, so the
decode step's shapes never depend on which requests are in flight.

The static cache in :func:`models.transformer_lm.generate` allocates
``[L, B, H_kv, Tp + max_new_tokens, dh]`` per *batch*: every sequence in
the batch owns a contiguous region sized for the worst case, and the
jitted program is specialized to ``(B, T_max)`` — admitting a request
with a different prompt length or budget means a new executable. That is
the wrong shape discipline for continuous batching, where the set of
in-flight sequences changes every iteration.

Here HBM is carved into ``num_pages`` fixed ``page_size``-token pages
(``k_pages``/``v_pages``: ``[L, num_pages, H_kv, page_size, dh]``), and
each of ``max_slots`` sequence slots holds a page *table* — an int32 row
of physical page ids, one per logical page. The jitted decode step takes
``(tokens [S], positions [S], page_tables [S, P], k_pages, v_pages)``:
every shape is a function of static config only, so XLA compiles the
step ONCE and admission/eviction between steps never recompiles.
Attention gathers a sequence's pages through its table row and masks
positions ``> seq_len``; writes scatter one token's K/V into
``table[pos // page_size]`` at offset ``pos % page_size``.

Page 0 is reserved scratch: inactive slots point their whole table at it
and their (garbage) writes land there harmlessly, so the step needs no
per-slot branching. The allocator hands out pages ``1..num_pages-1``
from a free list; :meth:`PageAllocator.assert_empty` is the no-leak
invariant the drain tests pin.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu.core.enforce import enforce

__all__ = ["PageAllocator", "PagedKVCache", "SCRATCH_PAGE"]

# physical page 0: never allocated; inactive slots write/read it
SCRATCH_PAGE = 0


class PageAllocator:
    """Free-list allocator over the physical page pool (host-side, not
    thread-safe — the decode loop is the only caller). Allocation is
    all-or-nothing: ``alloc(n)`` returns ``n`` page ids or ``None``
    without splitting, so a failed grow never leaks a partial grant."""

    def __init__(self, num_pages: int):
        enforce(num_pages >= 2,
                f"need >= 2 pages (page {SCRATCH_PAGE} is reserved scratch), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        # LIFO free list: recently-freed (cache-warm) pages are reused first
        self._free: List[int] = list(range(num_pages - 1, SCRATCH_PAGE, -1))
        # refcount per page: 0 = free. Prefix sharing holds extra refs on a
        # page (the radix tree plus every slot whose table maps it), and the
        # page returns to the free list only when the last ref drops.
        self._refs = [0] * num_pages

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def refcounts(self) -> List[int]:
        """Copy of every page's refcount (diagnostics; the host-tier
        promote tests pin the ownership-handoff discipline with this).

        The handoff pattern for loading externally-held page bytes (host
        tier promote, handoff adoption into a cache structure): the
        loader ``alloc(1)``\\ s the page (ref 1, loader-owned), implants
        the bytes, hands ownership to the long-lived holder (e.g.
        ``RadixPrefixCache.insert`` takes its own ref → 2), then
        ``free``\\ s its loader ref (→ 1, holder-owned). If the holder
        declined the page (already cached), the final ``free`` returns
        it to the pool — never a leak, never a double-own."""
        return list(self._refs)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` distinct page ids (each with refcount 1), or None if fewer
        than ``n`` are free."""
        enforce(n >= 0, f"alloc: n must be >= 0, got {n}")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def ref(self, pages: Sequence[int]) -> None:
        """Take an extra reference on already-allocated pages (prefix
        sharing: a cache hit maps the same physical page into another
        slot's table)."""
        for p in pages:
            enforce(SCRATCH_PAGE < p < self.num_pages,
                    f"ref: page id {p} out of range")
            enforce(self._refs[p] > 0,
                    f"ref: page {p} is not allocated")
            self._refs[p] += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page returns to the pool when its
        refcount hits 0. Freeing an unallocated page or scratch is a
        programming error and raises (a silently-tolerated double free
        would hand one physical page to two sequences later)."""
        for p in pages:
            enforce(SCRATCH_PAGE < p < self.num_pages,
                    f"free: page id {p} out of range")
            enforce(self._refs[p] > 0, f"free: page {p} is not allocated "
                    "(double free?)")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)

    def assert_empty(self) -> None:
        """The no-leak invariant: after a full drain every page is back in
        the free list."""
        leaked = [i for i, r in enumerate(self._refs) if r > 0]
        enforce(not leaked,
                f"page leak after drain: {len(leaked)} page(s) still "
                f"allocated: {leaked[:8]}")


class PagedKVCache:
    """Host-side bookkeeping for the paged cache: slot lifecycle, page
    tables, and sequence lengths. The device arrays themselves
    (``k_pages``/``v_pages``) are created and threaded through the jitted
    step by the engine — this class only decides *which* physical pages
    each slot's logical positions map to.

    A slot's logical capacity is ``pages_per_slot * page_size`` tokens
    (``pages_per_slot`` is the static page-table width ``P``). Pages are
    granted lazily by :meth:`ensure_capacity` as the sequence grows, so a
    short request never reserves worst-case HBM.
    """

    def __init__(self, *, max_slots: int, page_size: int, num_pages: int,
                 pages_per_slot: int):
        enforce(max_slots >= 1, f"max_slots must be >= 1, got {max_slots}")
        enforce(page_size >= 1, f"page_size must be >= 1, got {page_size}")
        enforce(pages_per_slot >= 1,
                f"pages_per_slot must be >= 1, got {pages_per_slot}")
        # one fully-grown sequence must always fit, else a lone request
        # could deadlock against an exhausted pool with nothing to preempt
        enforce(num_pages - 1 >= pages_per_slot,
                f"num_pages ({num_pages}) must exceed pages_per_slot "
                f"({pages_per_slot}): one max-length sequence has to fit "
                "even with every other slot evicted")
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.max_context = self.pages_per_slot * self.page_size
        self.allocator = PageAllocator(num_pages)
        self.page_tables = np.full((max_slots, pages_per_slot), SCRATCH_PAGE,
                                   dtype=np.int32)
        self.seq_lens = np.zeros((max_slots,), dtype=np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
        self._active = [False] * max_slots
        # logical page indices this slot shares with the prefix cache (or
        # other slots): writes into these must copy-on-write first
        self._slot_shared: List[set] = [set() for _ in range(max_slots)]

    def geometry(self) -> dict:
        """The cache's shape contract as a plain dict. Two caches with
        equal geometry index the same logical pages — the invariant the
        tp replica groups lean on: page ids are global across a group
        (only KV *heads* are sharded over the ``tp`` axis), so this one
        host-side bookkeeper serves every shard and refcounts, the radix
        prefix cache, CoW and trim run unchanged per shard."""
        return {
            "max_slots": self.max_slots,
            "page_size": self.page_size,
            "num_pages": self.allocator.num_pages,
            "pages_per_slot": self.pages_per_slot,
        }

    # -- slot lifecycle ----------------------------------------------------

    def acquire_slot(self) -> Optional[int]:
        """Claim a free slot (None when all are occupied)."""
        for s in range(self.max_slots):
            if not self._active[s]:
                self._active[s] = True
                self.seq_lens[s] = 0
                return s
        return None

    def release_slot(self, slot: int) -> int:
        """Free the slot's pages and point its table back at scratch.
        Returns the number of pages released."""
        enforce(self._active[slot], f"release_slot: slot {slot} not active")
        pages = self._slot_pages[slot]
        n = len(pages)
        self.allocator.free(pages)  # drops this slot's ref; shared pages
        self._slot_pages[slot] = []  # survive under the prefix cache's ref
        self.page_tables[slot, :] = SCRATCH_PAGE
        self.seq_lens[slot] = 0
        self._active[slot] = False
        self._slot_shared[slot].clear()
        return n

    def release_all(self) -> int:
        """Release every active slot (quarantine after a poisoned decode
        iteration, or engine teardown). Returns the number of slots freed.
        The device pages are untouched — their contents are garbage once
        the tables point back at scratch, which is exactly the semantics
        recovery wants: the faulted iteration's KV writes are lost and
        every sequence re-prefills from host-side tokens."""
        slots = self.active_slots()
        for s in slots:
            self.release_slot(s)
        return len(slots)

    def ensure_capacity(self, slot: int, n_positions: int) -> bool:
        """Grow ``slot`` to cover logical positions ``[0, n_positions)``.
        All-or-nothing: returns False (state unchanged) when the pool
        cannot supply the missing pages — the engine's preempt-or-queue
        decision point."""
        enforce(self._active[slot], f"ensure_capacity: slot {slot} not active")
        enforce(
            n_positions <= self.max_context,
            f"sequence needs {n_positions} positions but the slot capacity "
            f"is {self.max_context} (pages_per_slot * page_size)")
        have = len(self._slot_pages[slot])
        need = -(-n_positions // self.page_size) - have  # ceil div
        if need <= 0:
            return True
        grant = self.allocator.alloc(need)
        if grant is None:
            return False
        for i, p in enumerate(grant):
            self.page_tables[slot, have + i] = p
        self._slot_pages[slot].extend(grant)
        return True

    def trim(self, slot: int, n_positions: int) -> int:
        """Shrink ``slot`` to exactly the pages covering positions
        ``[0, n_positions)``, freeing the surplus (speculative rollback:
        pages granted for a draft block whose tokens were rejected).
        Returns the number of pages released."""
        enforce(self._active[slot], f"trim: slot {slot} not active")
        keep = -(-n_positions // self.page_size)  # ceil div
        pages = self._slot_pages[slot]
        if keep >= len(pages):
            return 0
        surplus = pages[keep:]
        self.allocator.free(surplus)
        self._slot_pages[slot] = pages[:keep]
        self.page_tables[slot, keep:] = SCRATCH_PAGE
        self._slot_shared[slot] = {
            li for li in self._slot_shared[slot] if li < keep}
        return len(surplus)

    # -- prefix sharing ----------------------------------------------------

    def adopt_pages(self, slot: int, pages: Sequence[int]) -> None:
        """Map already-written ``pages`` (a prefix-cache hit) as the slot's
        first logical pages, taking one reference per page. The slot must
        not have grown yet — hits apply at admission, before any prefill.
        The adopted logical indices are marked shared: a write into one
        (a continuation chunk straddling the hit boundary) must
        copy-on-write through :meth:`private_copy` first."""
        enforce(self._active[slot], f"adopt_pages: slot {slot} not active")
        enforce(not self._slot_pages[slot],
                f"adopt_pages: slot {slot} already has pages")
        enforce(len(pages) <= self.pages_per_slot,
                f"adopt_pages: {len(pages)} pages exceed table width "
                f"{self.pages_per_slot}")
        self.allocator.ref(pages)
        for i, p in enumerate(pages):
            self.page_tables[slot, i] = p
        self._slot_pages[slot] = list(pages)
        self._slot_shared[slot] = set(range(len(pages)))

    def is_shared(self, slot: int, logical_index: int) -> bool:
        return logical_index in self._slot_shared[slot]

    def shared_indices(self, slot: int) -> List[int]:
        return sorted(self._slot_shared[slot])

    def private_copy(self, slot: int, logical_index: int) -> Optional[tuple]:
        """Copy-on-write bookkeeping: replace the shared page at
        ``logical_index`` with a fresh private page. Returns
        ``(src_page, dst_page)`` for the engine's device-side page copy, or
        None when the pool is exhausted (state unchanged — caller preempts
        or evicts). The old page keeps its other refs (prefix cache /
        other slots); this slot's ref is dropped."""
        enforce(self._active[slot], f"private_copy: slot {slot} not active")
        enforce(logical_index in self._slot_shared[slot],
                f"private_copy: slot {slot} logical page {logical_index} "
                "is not shared")
        grant = self.allocator.alloc(1)
        if grant is None:
            return None
        src = self._slot_pages[slot][logical_index]
        dst = grant[0]
        self.allocator.free([src])
        self._slot_pages[slot][logical_index] = dst
        self.page_tables[slot, logical_index] = dst
        self._slot_shared[slot].discard(logical_index)
        return src, dst

    # -- readout -----------------------------------------------------------

    def active_slots(self) -> List[int]:
        return [s for s in range(self.max_slots) if self._active[s]]

    def slot_page_count(self, slot: int) -> int:
        return len(self._slot_pages[slot])

    def slot_pages(self, slot: int) -> List[int]:
        """The slot's physical page ids in logical order (a copy)."""
        return list(self._slot_pages[slot])

    @property
    def pages_in_use(self) -> int:
        return self.allocator.in_use

    @property
    def pages_free(self) -> int:
        return self.allocator.num_free

    def assert_no_leaks(self) -> None:
        """Drain invariant: no active slots and every page back in the
        free list (slot bookkeeping and allocator must agree)."""
        enforce(not any(self._active),
                f"active slots after drain: {self.active_slots()}")
        enforce(sum(len(p) for p in self._slot_pages) == 0,
                "slot page lists non-empty after drain")
        self.allocator.assert_empty()

"""Continuous (iteration-level) batching for autoregressive decode.

The static-batch path (:mod:`serving.engine`) dispatches whole requests:
for autoregressive decode that means every slot in a micro-batch idles
until the SLOWEST request in it drains — measured tokens/sec is bounded
by the worst request per batch, not the hardware. :class:`DecodeEngine`
replaces that execution model for LM decode: requests are admitted into
and evicted from the running batch *between decode iterations*, so a slot
freed by a short generation is refilled on the very next step while long
generations keep streaming.

Execution model (single decode-loop thread)::

    submit(prompt, max_new_tokens) ──▶ admission control (per-token cost)
        ──▶ weighted-fair scheduler (DRR by predicted token cost)
        ──▶ slot + page assignment (serving.kv_cache)
        ──▶ chunked prefill, bounded per iteration (never stalls decode)
        ──▶ ONE jitted decode step per iteration over all active slots
        ──▶ host-side finish checks (eos / budget / cancel / deadline)
        ──▶ freed slots refill from the queue before the next step

The KV cache is paged (:mod:`serving.kv_cache`): fixed-size pages plus
per-slot page tables, so the jitted step's shapes depend only on static
config ``(max_slots, table_width, page_size)`` — XLA compiles the step
once at warmup and admission/eviction/preemption never recompile
(:meth:`DecodeEngine.decode_step_cache_size` stays flat; the acceptance
test pins it). Prefill runs as fixed-size chunks through the same pages,
at most ``prefill_chunks_per_iter`` per iteration, so a long prompt is
absorbed a chunk at a time between decode steps instead of stalling them.

When the page pool is exhausted mid-growth the engine preempts the most
recently admitted other request (LIFO — oldest work finishes first):
its pages are freed, its generated prefix is kept, and it re-enters at
the front of the line to re-prefill ``prompt + generated`` and continue.
Greedy decode therefore produces identical tokens with or without
preemption. ``num_pages`` must exceed one slot's worth of pages
(enforced), so a lone request can always run to completion — the
preemption loop cannot deadlock.

Deadline admission uses a per-token cost model (:class:`DecodeCostModel`)
instead of the whole-request latency histograms the static path predicts
from: predicted latency = chunks x chunk-EMA + max_new_tokens x step-EMA,
which prices a 4-token and a 400-token generation differently where a
request-latency histogram cannot.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

import jax
import numpy as np

from paddle_tpu.core import locks
from paddle_tpu import observability, tracing
from paddle_tpu.concurrency import ChannelClosedError, go
from paddle_tpu.core import config as cfg_mod
from paddle_tpu.core import logging as ptlog
from paddle_tpu.core import profiler as prof
from paddle_tpu.core import retry as retry_mod
from paddle_tpu.core.enforce import enforce
from paddle_tpu.models.transformer_lm import (
    paged_cache_shape,
    paged_decode_step,
    paged_prefill_chunk,
    paged_verify_step,
)
from paddle_tpu.observability import roofline, runlog
from paddle_tpu.parallel import collective
from paddle_tpu.tracing import waterfall
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.circuit import CircuitBreaker
from paddle_tpu.serving import admission as admission_mod
from paddle_tpu.serving import scheduler as sched_mod
from paddle_tpu.serving.admission import AdmissionRejected, TenantConfig
from paddle_tpu.serving.engine import (
    DeadlineExceeded,
    EngineClosedError,
    PendingResult,
    ServingConfig,
)
from paddle_tpu.serving.host_tier import HostPageCorrupt, HostPagePool
from paddle_tpu.serving.kv_cache import SCRATCH_PAGE, PagedKVCache
from paddle_tpu.serving.metrics import DecodeMetrics
from paddle_tpu.serving.prefix_cache import RadixPrefixCache
from paddle_tpu.serving.shardgroup import (
    GroupLayout,
    GroupStragglerWatch,
    ReplicaGroup,
    default_layout,
    probe_members,
)
from paddle_tpu.serving.recovery import (
    EngineUnhealthy,
    RequestJournal,
    RescuePacket,
    RetriesExhausted,
)

__all__ = [
    "DecodeConfig",
    "DecodeCostModel",
    "DecodeEngine",
    "DecodeHandle",
    "DecodeOutput",
]

# request-id salt: keeps rids unique across processes sharing one journal
# (engine labels restart from decode0 in every process)
_RID_SALT = os.urandom(3).hex()


@dataclasses.dataclass
class DecodeConfig:
    """Continuous-batching policy knobs (the model/tenant/admission side
    rides on :class:`~paddle_tpu.serving.engine.ServingConfig`)."""

    # concurrent sequences per decode step (the step's static batch dim)
    max_slots: int = 4
    # tokens per KV page; pages are the HBM allocation granularity
    page_size: int = 16
    # per-sequence position capacity (prompt + generation); must be a
    # multiple of both page_size and prefill_chunk
    max_context: int = 256
    # physical page pool; None = every slot fully grown + scratch
    num_pages: Optional[int] = None
    # prompt tokens absorbed per prefill call (fixed-shape chunks)
    prefill_chunk: int = 32
    # prefill chunks run per decode iteration (prefill never monopolizes
    # the loop; decode steps keep landing between chunks)
    prefill_chunks_per_iter: int = 1
    # sampling policy (engine-wide; greedy by default)
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    rng_seed: int = 0
    # stop token; None = run every request to its max_new_tokens budget
    eos_id: Optional[int] = None
    # KV page dtype; overrides ServingConfig.cache_dtype when set
    cache_dtype: Optional[Any] = None
    # compile the prefill + step executables at init
    warmup: bool = True
    # with warmup off, compile them anyway when a persisted warmup
    # manifest (paddle_tpu.tune.warmup) says a previous process did —
    # replayed before the scheduler loop starts; None = the `prewarm` flag
    prewarm: Optional[bool] = None
    # idle poll interval on the scheduler when no slot is active
    idle_poll_s: float = 0.02
    # -- speculative decoding (draft-and-verify) --------------------------
    # draft tokens proposed per verify iteration; takes effect when the
    # engine is built with draft model params (greedy only — acceptance
    # compares argmaxes, so temperature must stay 0.0)
    spec_tokens: int = 4
    # -- radix prefix cache (serving.prefix_cache) ------------------------
    # share prompt-prefix KV pages across requests: a hit skips whole
    # prefill chunks; pages are refcounted with copy-on-write
    prefix_cache: bool = False
    # page budget for the tree (LRU-evicted past it); None = unbounded,
    # evicted only under allocator pressure
    prefix_cache_pages: Optional[int] = None
    # -- zero-loss recovery (serving.recovery) ----------------------------
    # survive decode-step faults by quarantining the poisoned iteration
    # and re-admitting live requests through the proven resume path
    # (False = the pre-recovery behavior: one step fault fails every
    # in-flight request)
    recovery: bool = True
    # per-request quarantine budget over its LIFETIME (not reset on
    # progress — re-prefill samples one token per cycle, so a progress
    # reset would let a deterministic poison loop forever): past this
    # many re-admissions the request fails with RetriesExhausted
    recovery_retries: int = 8
    # decorrelated-jitter backoff between faulted iterations (core.retry)
    recovery_base_delay_s: float = 0.002
    recovery_max_delay_s: float = 0.1
    # consecutive faulted decode iterations before this engine declares
    # itself unhealthy: trips its CircuitBreaker and — inside a
    # DecodeFleet — drains live requests to a healthy engine
    unhealthy_after: int = 3
    breaker_cooldown_s: float = 0.25
    breaker_max_cooldown_s: float = 5.0
    # durable request journal (WAL): records admissions + every
    # generated token; recovery.replay_journal()/resume_incomplete()
    # rebuild in-flight work after a process restart. None = off.
    journal_path: Optional[str] = None
    journal_fsync_every: int = 16
    # WAL size (bytes) that triggers an in-place compaction: finished
    # requests drop, incomplete ones are rewritten as snapshots into a
    # fresh segment (atomic publish). None = unbounded growth.
    journal_compact_bytes: Optional[int] = None
    # -- replica groups (serving.shardgroup) ------------------------------
    # per-member canary cadence when the engine is group-backed: each
    # member device is timed individually so a fault or stall is
    # attributable to ONE chip of the group
    group_probe_every_s: float = 0.05
    # per-shard probe-time skew (vs the median shard) that flags a
    # straggler chip inside the group
    group_skew_ratio: float = 4.0
    # statically lint the GroupLayout against the actual param tree + KV
    # geometry BEFORE placing anything on devices (analysis.shard_analysis):
    # layout errors (dead rules, rank mismatches, kv-geometry violations)
    # raise here instead of surfacing as a wrong placement on a pod
    lint_layout: bool = True
    # -- hierarchical KV host tier (serving.host_tier) --------------------
    # byte budget for a PRIVATE host-RAM page pool behind the radix tree
    # (requires prefix_cache): radix inserts write through to host RAM,
    # radix misses whose continuation the pool holds promote back
    # asynchronously. None = no private pool; pass a shared HostPagePool
    # to DecodeEngine(host_tier=...) for fleet-wide sharing + crash
    # recovery (the pool survives any one engine's kill()).
    host_tier_bytes: Optional[int] = None
    # publish a compact per-prefix digest set for prefix-aware fleet
    # routing: DecodeFleet/DisaggRouter route each prompt to the engine
    # with the longest cached prefix (least-loaded tiebreak)
    prefix_digest: bool = False
    # promote-apply budget: pages implanted from the host tier per loop
    # iteration — bounds added per-iteration latency so promotion stays
    # decode-p99-neutral (the bench leg pins this)
    host_promote_pages_per_iter: int = 4


@dataclasses.dataclass
class DecodeOutput:
    """One finished generation. ``tokens`` holds the generated ids
    (including ``eos_id`` when that ended it); ``finish_reason`` is
    ``"eos"`` | ``"length"`` | ``"cancelled"`` | ``"drain_timeout"``
    (close() deadline enforced: partial tokens returned)."""

    tokens: np.ndarray
    finish_reason: str
    prompt_len: int
    n_preemptions: int = 0


class DecodeHandle(PendingResult):
    """Future for one decode request, plus mid-generation cancellation:
    :meth:`cancel` marks the request; the loop completes it with the
    tokens generated so far (``finish_reason="cancelled"``) at the next
    iteration boundary."""

    def __init__(self, req: "_DecodeRequest"):
        super().__init__()
        self._req = req

    def cancel(self) -> None:
        self._req.cancelled = True


class _DecodeRequest:
    __slots__ = ("prompt", "mnt", "n", "bytes", "tenant", "cls", "deadline",
                 "t_submit", "handle", "generated", "slot", "phase", "seq",
                 "chunks_done", "cur_len", "last_tok", "cancelled",
                 "n_preemptions", "trace", "t_enqueue_pc", "t_admit_pc",
                 "rid", "recoveries")

    def __init__(self, prompt: np.ndarray, mnt: int, n_chunks: int,
                 deadline: Optional[float], t_submit: float,
                 tenant: str = "default", cls: str = "interactive"):
        self.prompt = prompt
        self.mnt = mnt
        # DRR weight: predicted device iterations (decode steps + prefill
        # chunks), so fairness is by token cost, not request count
        self.n = mnt + n_chunks
        self.bytes = int(prompt.nbytes)
        self.tenant = tenant
        self.cls = cls
        self.deadline = deadline
        self.t_submit = t_submit
        self.handle = DecodeHandle(self)
        self.generated: List[int] = []
        self.slot: Optional[int] = None
        self.phase = "queued"          # queued | prefill | decode
        self.seq: Optional[np.ndarray] = None  # tokens being prefilled
        self.chunks_done = 0
        self.cur_len = 0               # K/V positions written so far
        self.last_tok = 0              # next token to feed the step
        self.cancelled = False
        self.n_preemptions = 0
        self.trace: Optional[tracing.SpanContext] = None
        self.t_enqueue_pc: Optional[float] = None
        self.t_admit_pc: Optional[float] = None
        self.rid: Optional[str] = None   # journal/migration identity
        self.recoveries = 0              # quarantine cycles survived


class DecodeCostModel:
    """EMA cost model for decode admission: per-iteration step cost and
    per-chunk prefill cost, observed by the loop. ``step_s``/``chunk_s``
    preset the EMAs (deterministic tests / warm handoff); cold (no step
    observations and no preset) estimates are None so admission falls
    back to admitting everything — shedding on zero data would reject
    the traffic that builds the model."""

    def __init__(self, alpha: float = 0.2, step_s: Optional[float] = None,
                 chunk_s: Optional[float] = None,
                 verify_s: Optional[float] = None,
                 accepted_per_step: Optional[float] = None):
        enforce(0.0 < alpha <= 1.0, f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self._step_s = step_s
        self._chunk_s = chunk_s
        # speculative decoding: per-verify-iteration cost and how many
        # tokens one iteration lands on average (1 + accepted drafts).
        # Without these, estimate() assumes 1 token/step — wildly
        # pessimistic under speculation.
        self._verify_s = verify_s
        self._accepted = accepted_per_step
        self._lock = locks.Lock("serving.decode_cost_model")

    def observe_step(self, seconds: float) -> None:
        with self._lock:
            self._step_s = (seconds if self._step_s is None else
                            self.alpha * seconds +
                            (1 - self.alpha) * self._step_s)

    def observe_chunk(self, seconds: float) -> None:
        with self._lock:
            self._chunk_s = (seconds if self._chunk_s is None else
                             self.alpha * seconds +
                             (1 - self.alpha) * self._chunk_s)

    def observe_verify(self, seconds: float, accepted_tokens: float) -> None:
        """One draft-and-verify iteration: its wall cost (drafting
        included) and the tokens it landed per participating slot."""
        with self._lock:
            self._verify_s = (seconds if self._verify_s is None else
                              self.alpha * seconds +
                              (1 - self.alpha) * self._verify_s)
            self._accepted = (accepted_tokens if self._accepted is None else
                              self.alpha * accepted_tokens +
                              (1 - self.alpha) * self._accepted)

    def estimate(self, n_chunks: int, max_new_tokens: int,
                 queue_cost: int = 0) -> Optional[float]:
        """Predicted service latency: prefill chunks + decode iterations,
        plus ``queue_cost`` iterations already queued ahead. Under
        speculation an iteration is one verify step landing
        ``accepted_per_step`` tokens; otherwise one step = one token.
        None while cold."""
        with self._lock:
            step_s, chunk_s = self._step_s, self._chunk_s
            verify_s, accepted = self._verify_s, self._accepted
        if verify_s is not None:
            per_iter = verify_s
            tokens_per_iter = max(accepted if accepted else 1.0, 1.0)
            if chunk_s is None:
                chunk_s = verify_s
            iters = max_new_tokens / tokens_per_iter
            return (n_chunks * chunk_s + iters * per_iter
                    + queue_cost * per_iter)
        if step_s is None:
            return None
        if chunk_s is None:
            chunk_s = step_s
        return (n_chunks * chunk_s + max_new_tokens * step_s
                + queue_cost * step_s)

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            return {"step_s": self._step_s, "chunk_s": self._chunk_s,
                    "verify_s": self._verify_s,
                    "accepted_per_step": self._accepted}


class DecodeEngine:
    """Iteration-level batched autoregressive serving over a trained
    transformer LM (params as created by
    :func:`~paddle_tpu.models.transformer_lm.lm_forward`).

    ::

        eng = DecodeEngine(variables, cfg, decode=DecodeConfig(max_slots=8))
        out = eng.infer(prompt_ids, max_new_tokens=32)   # DecodeOutput
        h = eng.submit(prompt_ids, 128)                  # async
        h.cancel()                                       # mid-generation
        eng.close()                                      # graceful drain

    Passing ``draft_variables`` (plus its ``draft_cfg`` when the draft is
    a different architecture) turns on draft-and-verify speculative
    decoding: each iteration the draft proposes ``DecodeConfig.spec_tokens``
    tokens sequentially, one jitted ``paged_verify_step`` scores all of
    them (plus the bonus position) against the target's paged cache, and
    the longest draft prefix matching the target's own greedy choices is
    accepted — token-exact vs ``generate()`` by construction. The draft
    shares the slot page tables and allocator geometry with its own page
    arrays, so admission/preemption bookkeeping stays single-sourced.
    ``DecodeConfig.prefix_cache=True`` adds the radix prefix cache: hot
    prompt prefixes prefill once and later requests adopt the shared
    pages (refcounted, copy-on-write).
    """

    def __init__(
        self,
        variables,
        model_cfg: dict,
        *,
        config: Optional[ServingConfig] = None,
        decode: Optional[DecodeConfig] = None,
        draft_variables=None,
        draft_cfg: Optional[dict] = None,
        group: Optional[ReplicaGroup] = None,
        layout: Optional[GroupLayout] = None,
        host_tier: Optional[HostPagePool] = None,
    ):
        self.config = config or ServingConfig()
        self.decode_config = dconf = decode or DecodeConfig()
        self.model_cfg = dict(model_cfg)
        enforce(dconf.max_slots >= 1,
                f"max_slots must be >= 1, got {dconf.max_slots}")
        enforce(dconf.prefill_chunk >= 1,
                f"prefill_chunk must be >= 1, got {dconf.prefill_chunk}")
        enforce(dconf.max_context % dconf.page_size == 0,
                f"max_context ({dconf.max_context}) must be a multiple of "
                f"page_size ({dconf.page_size})")
        # padded prompt chunks must stay inside the slot's table span —
        # a chunk running past it would clamp-scatter into the last page
        enforce(dconf.max_context % dconf.prefill_chunk == 0,
                f"max_context ({dconf.max_context}) must be a multiple of "
                f"prefill_chunk ({dconf.prefill_chunk})")
        pages_per_slot = dconf.max_context // dconf.page_size
        num_pages = (dconf.num_pages if dconf.num_pages is not None
                     else 1 + dconf.max_slots * pages_per_slot)
        self._kv = PagedKVCache(
            max_slots=dconf.max_slots, page_size=dconf.page_size,
            num_pages=num_pages, pages_per_slot=pages_per_slot)
        self.metrics = DecodeMetrics(engine_label=self.config.engine_label)
        observability.setup()
        self.cost = DecodeCostModel()

        params = variables.params if hasattr(variables, "params") else variables
        # replica-group mode (serving.shardgroup): the engine's program
        # spans the group's tp submesh — params and KV pages are committed
        # with the layout's NamedShardings and every jit pins its page
        # outputs to the same sharding, so the cache arrays never change
        # placement and the compile-once invariants hold per GROUP exactly
        # as they do per device
        self._group = group
        self._layout = (layout or default_layout()) if group is not None else None
        self._straggler = (GroupStragglerWatch(group,
                                               ratio=dconf.group_skew_ratio)
                           if group is not None else None)
        self._last_probe = 0.0
        cdt = (dconf.cache_dtype if dconf.cache_dtype is not None
               else self.config.cache_dtype)
        pshape = paged_cache_shape(self.model_cfg, num_pages, dconf.page_size)
        import jax.numpy as jnp

        self._cache_dtype = cdt or jnp.float32
        if group is None:
            self._params = jax.device_put(params)
            self._k_pages = jnp.zeros(pshape, self._cache_dtype)
            self._v_pages = jnp.zeros(pshape, self._cache_dtype)
            kvs = rep = None
        else:
            if dconf.lint_layout:
                # fail on a bad layout BEFORE any device_put: errors raise
                # with every finding listed, warnings warn_once
                from paddle_tpu.analysis.shard_analysis import (
                    lint_group_layout_or_raise,
                )

                lint_group_layout_or_raise(
                    params, self._layout, group.mesh,
                    kv_page_shape=pshape, kv_geometry=self._kv.geometry(),
                    where=f"DecodeEngine[{group.name}]",
                )
            self._params = self._layout.shard_params(group, params)
            kvs = self._layout.kv_page_sharding(group, pshape)
            rep = self._layout.replicated(group)
            self._k_pages = jax.device_put(
                jnp.zeros(pshape, self._cache_dtype), kvs)
            self._v_pages = jax.device_put(
                jnp.zeros(pshape, self._cache_dtype), kvs)
        jit_kw = {} if group is None else {"out_shardings": (rep, kvs, kvs)}
        sample_kw = dict(temperature=dconf.temperature, top_k=dconf.top_k,
                         top_p=dconf.top_p)
        # roofline-instrumented: these jits bypass Executor.prepare(), so
        # they feed the cost ledger through their own wrapper (compiles
        # capture cost/memory analysis, later calls book wall seconds)
        self._step = roofline.instrument(
            "serving.decode.step", jax.jit(functools.partial(
                paged_decode_step, cfg=self.model_cfg,
                page_size=dconf.page_size, **sample_kw), **jit_kw))
        self._prefill = roofline.instrument(
            "serving.decode.prefill", jax.jit(functools.partial(
                paged_prefill_chunk, cfg=self.model_cfg,
                page_size=dconf.page_size, **sample_kw), **jit_kw))
        # disagg KV handoff (serving.disagg): one page is the fixed-shape
        # [L, H_kv, page_size, dh] slice, so gather/implant compile once.
        # In group mode the gather's output is pinned replicated — the
        # wire image is always the FULL logical page regardless of tp —
        # and the implant re-scatters it back over the group's heads.
        self._gather_page = jax.jit(
            collective.gather_kv_page,
            **({} if group is None else {"out_shardings": rep}))
        self._implant_page = jax.jit(
            collective.scatter_kv_page,
            **({} if group is None else {"out_shardings": kvs}))
        self._rng = (jax.random.PRNGKey(dconf.rng_seed)
                     if dconf.temperature > 0.0 else None)

        # -- speculative decoding (draft-and-verify) ----------------------
        self._spec_k = 0
        self._draft_params = None
        if draft_variables is not None:
            enforce(dconf.spec_tokens >= 1,
                    f"spec_tokens must be >= 1 with a draft model, "
                    f"got {dconf.spec_tokens}")
            enforce(dconf.temperature == 0.0,
                    "speculative decoding is greedy-only: acceptance "
                    "compares argmaxes, so temperature must be 0.0")
            self.draft_cfg = dict(draft_cfg) if draft_cfg else self.model_cfg
            enforce(self.draft_cfg.get("vocab") == self.model_cfg.get("vocab"),
                    "draft and target models must share a vocabulary "
                    f"({self.draft_cfg.get('vocab')} vs "
                    f"{self.model_cfg.get('vocab')})")
            dp = (draft_variables.params
                  if hasattr(draft_variables, "params") else draft_variables)
            self._spec_k = int(dconf.spec_tokens)
            # the draft reads/writes THROUGH the same page tables: its own
            # page arrays, same (num_pages, page_size) geometry, so slot
            # bookkeeping (grow/preempt/trim) covers both caches at once
            dshape = paged_cache_shape(self.draft_cfg, num_pages,
                                       dconf.page_size)
            if group is None:
                self._draft_params = jax.device_put(dp)
                self._dk_pages = jnp.zeros(dshape, self._cache_dtype)
                self._dv_pages = jnp.zeros(dshape, self._cache_dtype)
                djit_kw = {}
            else:
                self._draft_params = self._layout.shard_params(group, dp)
                dkvs = self._layout.kv_page_sharding(group, dshape)
                self._dk_pages = jax.device_put(
                    jnp.zeros(dshape, self._cache_dtype), dkvs)
                self._dv_pages = jax.device_put(
                    jnp.zeros(dshape, self._cache_dtype), dkvs)
                djit_kw = {"out_shardings": (rep, dkvs, dkvs)}
            self._draft_step = roofline.instrument(
                "serving.decode.draft_step", jax.jit(functools.partial(
                    paged_decode_step, cfg=self.draft_cfg,
                    page_size=dconf.page_size, temperature=0.0), **djit_kw))
            self._draft_prefill = roofline.instrument(
                "serving.decode.draft_prefill", jax.jit(functools.partial(
                    paged_prefill_chunk, cfg=self.draft_cfg,
                    page_size=dconf.page_size, temperature=0.0), **djit_kw))
            self._verify = roofline.instrument(
                "serving.decode.verify", jax.jit(functools.partial(
                    paged_verify_step, cfg=self.model_cfg,
                    page_size=dconf.page_size), **jit_kw))

        # -- radix prefix cache -------------------------------------------
        self._prefix: Optional[RadixPrefixCache] = None
        if dconf.prefix_cache:
            self._prefix = RadixPrefixCache(
                self._kv.allocator, dconf.page_size,
                max_pages=dconf.prefix_cache_pages)
            # device-side page copy for CoW; src/dst are traced scalars so
            # this compiles once per page-array shape. Group mode pins the
            # output to the page arrays' sharding (target and draft pages
            # may shard differently, hence two jits) so the cache arrays
            # never drift placement between iterations.
            _copy = lambda pages, src, dst: pages.at[:, dst].set(pages[:, src])
            self._copy_page = jax.jit(
                _copy, **({} if group is None else {"out_shardings": kvs}))
            self._copy_page_d = (self._copy_page if group is None
                                 or not self._spec_k else jax.jit(
                                     _copy, out_shardings=dkvs))

        # -- hierarchical KV host tier (serving.host_tier) ----------------
        # a pool passed in is SHARED (fleet-wide prefix sharing + crash
        # recovery: it survives this engine's kill()); host_tier_bytes
        # builds a private one. Draft-model engines skip the tier — the
        # pool carries only target-cache pages, and adopting them without
        # the draft's would desynchronize speculation (same rationale as
        # handoff adoption degrading to re-prefill).
        self._host_tier: Optional[HostPagePool] = host_tier
        if self._host_tier is None and dconf.host_tier_bytes:
            self._host_tier = HostPagePool(dconf.host_tier_bytes,
                                           dconf.page_size)
        if self._host_tier is not None and self._spec_k:
            ptlog.warning(
                "host tier disabled for engine %s: the pool carries only "
                "target-cache pages, which a speculative engine cannot "
                "adopt", self.config.engine_label)
            self._host_tier = None
        if self._host_tier is not None:
            enforce(self._prefix is not None,
                    "host tier requires DecodeConfig(prefix_cache=True): "
                    "it extends the radix tree, not the raw page pool")
            enforce(self._host_tier.compatible(dconf.page_size),
                    f"host tier page_size {self._host_tier.page_size} != "
                    f"engine page_size {dconf.page_size}")
        # promote jobs applied on the loop thread, budgeted per iteration
        # (host_promote_pages_per_iter); keys dedup in-flight prefixes
        self._promote_jobs: Deque = deque()
        self._promote_keys: set = set()
        # prefix-aware routing digest: republished (lock-free swap of an
        # immutable frozenset) on the loop thread whenever the tree's
        # digest_version moved; fleets read it from any thread
        self._digest_pub: frozenset = frozenset()
        self._digest_seen = -1

        # tenants / scheduler / admission — same wiring as ServingEngine,
        # but deadline feasibility runs through the per-token cost model
        tenant_cfgs = [t.resolved() for t in (self.config.tenants or ())]
        if not tenant_cfgs:
            tenant_cfgs = [TenantConfig(
                "default", queue_capacity=self.config.queue_capacity,
            ).resolved()]
        self._tenants = {t.name: t for t in tenant_cfgs}
        self._default_tenant = (
            "default" if "default" in self._tenants else tenant_cfgs[0].name)
        admission_on = (self.config.admission
                        if self.config.admission is not None
                        else self.config.tenants is not None)
        self._queue = sched_mod.WeightedFairScheduler(
            self._tenants,
            quantum_rows=max(8, dconf.max_slots * 8),
            batch_min_share=(self.config.batch_min_share
                             if self.config.batch_min_share is not None
                             else cfg_mod.flags().tenant_batch_min_share),
            legacy_capacity=(None if admission_on
                             else self.config.queue_capacity),
            on_expired=self._expire,
        )
        self._admission: Optional[admission_mod.AdmissionController] = None
        if admission_on:
            self._admission = admission_mod.AdmissionController(
                self._queue, self.metrics, self._tenants,
                request_cost=self._request_cost,
                brownout_min_s=self.config.brownout_min_s,
            )
            admission_mod.install(self._admission)

        self._active: List[_DecodeRequest] = []     # admission order
        self._resume: Deque[_DecodeRequest] = deque()
        self._pending_admit: Deque[_DecodeRequest] = deque()
        # disaggregated serving (serving.disagg): a prefill-role engine
        # publishes finished prefills through _handoff_sink instead of
        # decoding them; a decode-role engine admits adopted payloads
        # from _pending_handoff (implanted on the loop thread)
        self._handoff_sink: Optional[Callable[..., None]] = None
        self._pending_handoff: Deque = deque()  # (req, HandoffPayload)
        self._closed = False
        self._close_lock = locks.Lock("serving.decode_close")
        # zero-loss recovery state (serving.recovery)
        self._breaker = CircuitBreaker(
            failure_threshold=dconf.unhealthy_after,
            cooldown_s=dconf.breaker_cooldown_s,
            max_cooldown_s=dconf.breaker_max_cooldown_s)
        self._rescue_sink: Optional[Callable[..., int]] = None  # DecodeFleet
        self._consec_faults = 0
        self._recover_prev_delay = 0.0
        self._breaker_dirty = False
        self._journal: Optional[RequestJournal] = None
        # a DisaggRouter may swap in a journal SHARED across its workers;
        # then close()/kill() must not close it (the router owns its fd)
        self._journal_owned = True
        if dconf.journal_path:
            self._journal = RequestJournal(
                dconf.journal_path, fsync_every=dconf.journal_fsync_every,
                compact_bytes=dconf.journal_compact_bytes)
        self._rid_seq = itertools.count()
        self._killed = False
        self._drain_abort = False
        self._loop_trace: Optional[tracing.SpanContext] = None
        if tracing.tracing_enabled():
            self._loop_trace = tracing.SpanContext.new_trace()

        if dconf.warmup:
            self._warmup()
        elif (dconf.prewarm if dconf.prewarm is not None
              else cfg_mod.flags().prewarm):
            self.prewarm()
        self._thread = go(self._loop)

    # -- startup -----------------------------------------------------------

    def _warmup(self) -> None:
        """Compile the prefill-chunk and decode-step executables before
        traffic arrives. Warmup writes land on the scratch page (zero
        tables), so no reset is needed afterwards."""
        import jax.numpy as jnp

        dconf = self.decode_config
        S, P = dconf.max_slots, self._kv.pages_per_slot
        table0 = jnp.zeros((P,), jnp.int32)
        key = None
        if self._rng is not None:
            self._rng, key = jax.random.split(self._rng)
        _, self._k_pages, self._v_pages = self._prefill(
            self._params, jnp.zeros((dconf.prefill_chunk,), jnp.int32),
            jnp.int32(0), jnp.int32(0), table0,
            self._k_pages, self._v_pages, key)
        if self._rng is not None:
            self._rng, key = jax.random.split(self._rng)
        out, self._k_pages, self._v_pages = self._step(
            self._params, jnp.zeros((S,), jnp.int32),
            jnp.zeros((S,), jnp.int32),
            jnp.zeros((S, P), jnp.int32),
            self._k_pages, self._v_pages, key)
        jax.block_until_ready(out)
        if self._spec_k:
            _, self._dk_pages, self._dv_pages = self._draft_prefill(
                self._draft_params,
                jnp.zeros((dconf.prefill_chunk,), jnp.int32),
                jnp.int32(0), jnp.int32(0), table0,
                self._dk_pages, self._dv_pages, None)
            _, self._dk_pages, self._dv_pages = self._draft_step(
                self._draft_params, jnp.zeros((S,), jnp.int32),
                jnp.zeros((S,), jnp.int32),
                jnp.zeros((S, P), jnp.int32),
                self._dk_pages, self._dv_pages, None)
            vout, self._k_pages, self._v_pages = self._verify(
                self._params,
                jnp.zeros((S, self._spec_k + 1), jnp.int32),
                jnp.zeros((S,), jnp.int32),
                jnp.zeros((S, P), jnp.int32),
                self._k_pages, self._v_pages)
            jax.block_until_ready(vout)
        if self._prefix is not None:
            # scratch -> scratch: harmless, compiles the CoW copy
            z = jnp.int32(SCRATCH_PAGE)
            self._k_pages = self._copy_page(self._k_pages, z, z)
            self._v_pages = self._copy_page(self._v_pages, z, z)
            if self._spec_k:
                self._dk_pages = self._copy_page_d(self._dk_pages, z, z)
                self._dv_pages = self._copy_page_d(self._dv_pages, z, z)
        # persist the compiled keys so a restarted engine can prewarm
        from paddle_tpu.tune import warmup as tune_warmup

        name = self._manifest_name()
        tune_warmup.record_compile(
            name, "prefill_chunk", save=False,
            chunk=int(dconf.prefill_chunk), page_size=int(dconf.page_size),
            max_context=int(dconf.max_context))
        tune_warmup.record_compile(
            name, "decode_step", save=False,
            max_slots=int(S), page_size=int(dconf.page_size),
            pages_per_slot=int(P))
        if self._spec_k:
            tune_warmup.record_compile(
                name, "verify_step", save=False,
                max_slots=int(S), spec_tokens=int(self._spec_k),
                page_size=int(dconf.page_size), pages_per_slot=int(P))
        path = tune_warmup.manifest_path(name)
        if path:
            try:
                tune_warmup.get_manifest(name, path).save()
            except Exception as e:
                ptlog.warning("warmup manifest save failed: %s", e)

    def _manifest_name(self) -> str:
        """Manifest identity for this engine: model dims + the static
        decode-shape knobs (a config change must not replay stale keys)."""
        d = self.decode_config
        mc = self.model_cfg
        name = ("decode_L{l}_D{dm}_S{s}_P{p}_C{c}".format(
            l=mc.get("n_layers", 0), dm=mc.get("d_model", 0),
            s=d.max_slots, p=d.page_size, c=d.prefill_chunk))
        if self._group is not None:
            # a group program is a different executable than the
            # single-device one — never replay the other's keys
            name += f"_tp{self._group.tp}"
        return name

    def prewarm(self) -> int:
        """Replay the persisted warmup manifest: when a previous process
        recorded this engine's prefill/step keys, compile them now —
        before the scheduler loop admits traffic — so a restart with a
        populated persistent compilation cache pays (near-)zero
        ``compile_seconds``. The jitted step stays compile-once:
        :meth:`decode_step_cache_size` is 1 after prewarm and stays 1
        under traffic. Returns the number of manifest keys replayed."""
        from paddle_tpu.tune import warmup as tune_warmup

        manifest = tune_warmup.get_manifest(self._manifest_name())
        keys = [e for e in manifest.entries()
                if e.get("kind") in ("prefill_chunk", "decode_step",
                                     "verify_step")]
        if not keys:
            return 0
        with prof.record_event("decode.prewarm"):
            self._warmup()
        prof.inc_counter("tune.prewarm.replayed_total", len(keys))
        runlog.emit("tune", phase="prewarm", engine="decode",
                    model=self._manifest_name(), keys=len(keys))
        return len(keys)

    def decode_step_cache_size(self) -> int:
        """Compiled-executable count inside the jitted decode step (−1
        when jax doesn't expose it). Flat after warmup ⇒ continuous
        batching never triggered a recompile — the shape-stability
        contract the acceptance test pins."""
        return (self._step._cache_size()
                if hasattr(self._step, "_cache_size") else -1)

    def prefill_cache_size(self) -> int:
        return (self._prefill._cache_size()
                if hasattr(self._prefill, "_cache_size") else -1)

    def verify_step_cache_size(self) -> int:
        """Compiled-executable count inside the jitted verify step: 0 with
        speculation off, and pinned at 1 under mixed traffic — the block
        shape ``[max_slots, spec_tokens + 1]`` is static config, so the
        verify step compiles exactly once ever."""
        if not self._spec_k:
            return 0
        return (self._verify._cache_size()
                if hasattr(self._verify, "_cache_size") else -1)

    @property
    def kv(self) -> PagedKVCache:
        return self._kv

    @property
    def group(self) -> Optional[ReplicaGroup]:
        """The tp replica group backing this engine (None = the classic
        single-device mode)."""
        return self._group

    @property
    def tp_degree(self) -> int:
        """Tensor-parallel degree of the backing program (1 = single
        device). Stamped into handoff payloads so cross-group adoption
        with a DIFFERENT degree degrades to re-prefill instead of
        implanting pages scattered for the wrong head partition."""
        return self._group.tp if self._group is not None else 1

    @property
    def prefix(self) -> Optional[RadixPrefixCache]:
        """The engine's radix prefix cache (None unless
        ``DecodeConfig.prefix_cache`` is set)."""
        return self._prefix

    @property
    def spec_tokens(self) -> int:
        """Draft tokens proposed per verify iteration (0 = speculation
        off: no draft model configured)."""
        return self._spec_k

    @property
    def admission(self) -> Optional[admission_mod.AdmissionController]:
        return self._admission

    def load(self) -> float:
        """Live work on this engine: active slots plus every parked or
        queued request. ``DecodeFleet._pick`` routes new work to the
        least-loaded healthy engine by this number. Read lock-free from
        any thread — ``len()`` is atomic under the GIL, and staleness
        only costs routing optimality, never correctness."""
        return float(len(self._active) + len(self._resume)
                     + len(self._pending_admit)
                     + len(self._pending_handoff)
                     + self._queue.qsize())

    # -- admission cost ----------------------------------------------------

    def _n_chunks(self, length: int) -> int:
        return max(1, -(-length // self.decode_config.prefill_chunk))

    def _request_cost(self, req) -> Optional[float]:
        """Per-token deadline prediction for the admission controller:
        chunks x chunk-EMA + max_new_tokens x step-EMA, plus the queued
        work ahead priced in iterations."""
        queued = self._queue.qsize() + len(self._pending_admit)
        return self.cost.estimate(
            self._n_chunks(len(req.prompt)), req.mnt,
            queue_cost=queued)

    # -- request intake ----------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
        tenant: Optional[str] = None,
        cls: Optional[str] = None,
    ) -> DecodeHandle:
        """Enqueue one generation request. ``prompt`` is a 1-D int token
        array; the result is a :class:`DecodeOutput` via the returned
        handle. Admission/backpressure semantics mirror
        :meth:`~paddle_tpu.serving.engine.ServingEngine.submit`."""
        if self._closed:
            raise EngineClosedError("engine is closed")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        dconf = self.decode_config
        enforce(prompt.size >= 1, "prompt must be non-empty")
        enforce(max_new_tokens >= 1,
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        enforce(
            int(prompt.size) + max_new_tokens <= dconf.max_context,
            f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_context ({dconf.max_context})")
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            self.metrics.record_timeout()
            raise DeadlineExceeded(
                f"deadline {deadline_s}s already expired at submit")
        deadline = None if deadline_s is None else now + deadline_s
        tname = tenant if tenant is not None else self._default_tenant
        tcfg = self._tenants.get(tname)
        rcls = cls if cls is not None else (
            tcfg.default_class if tcfg is not None
            else cfg_mod.flags().tenant_default_class)
        enforce(rcls in sched_mod.CLASSES,
                f"unknown priority class {rcls!r} "
                f"(expected one of {sched_mod.CLASSES})")
        if self._admission is None:
            enforce(tcfg is not None,
                    f"unknown tenant {tname!r} "
                    f"(configured: {sorted(self._tenants)})")
        req = _DecodeRequest(prompt, int(max_new_tokens),
                             self._n_chunks(int(prompt.size)),
                             deadline, now, tenant=tname, cls=rcls)
        req.rid = (f"{self.metrics.engine_label}-{_RID_SALT}-"
                   f"{next(self._rid_seq)}")
        if tracing.tracing_enabled():
            req.trace = tracing.SpanContext.new_trace()
            req.handle.trace = req.trace
            req.t_enqueue_pc = time.perf_counter()
        # token-latency waterfall opens at submit: TTFT includes queue wait
        waterfall.start(req.rid, time.perf_counter(),
                        engine=self.metrics.engine_label, tenant=req.tenant,
                        cls=req.cls)
        # journal BEFORE enqueue: the loop may start generating (and
        # journaling tokens) the instant the scheduler has the request
        self._j_admit(req)
        try:
            if self._admission is not None:
                self._admission.admit(req)
            else:
                self._queue.send(req, timeout=timeout)
        except ChannelClosedError:
            self._j_fin(req, "shed")
            waterfall.finish(req.rid, time.perf_counter(), "shed")
            raise EngineClosedError("engine is closed") from None
        except AdmissionRejected:
            self._j_fin(req, "shed")
            waterfall.finish(req.rid, time.perf_counter(), "shed")
            if req.trace is not None:
                self._finish_trace(req, time.perf_counter(), status="shed")
            raise
        self.metrics.record_submit()
        return req.handle

    def infer(self, prompt, max_new_tokens: int, **kwargs) -> DecodeOutput:
        """Synchronous decode: submit + wait."""
        return self.submit(prompt, max_new_tokens, **kwargs).result()

    # -- journal hooks (no-ops with journaling off) ------------------------

    def _j_admit(self, req: _DecodeRequest) -> None:
        if self._journal is not None and req.rid is not None:
            self._journal.log_admit(req.rid, req.prompt, req.mnt,
                                    req.generated, req.tenant, req.cls,
                                    trace=(req.trace.to_traceparent()
                                           if req.trace is not None
                                           else None))
            self.metrics.record_journal_records(1)

    def _j_tok(self, req: _DecodeRequest, tok: int) -> None:
        if self._journal is not None and req.rid is not None:
            self._journal.log_token(req.rid, tok)
            self.metrics.record_journal_records(1)

    def _j_fin(self, req: _DecodeRequest, reason: str) -> None:
        if self._journal is not None and req.rid is not None:
            self._journal.log_finish(req.rid, reason)
            self.metrics.record_journal_records(1)

    # -- completion paths (loop thread, except _expire) --------------------

    def _finish_trace(self, req: _DecodeRequest, t1_pc: float,
                      **attrs) -> None:
        if req.trace is None:
            return
        tracing.record_span(
            "serving.decode.request", req.t_enqueue_pc, t1_pc,
            context=req.trace, engine=self.metrics.engine_label,
            tenant=req.tenant, cls=req.cls,
            generated=len(req.generated), **attrs)

    def _wf_tokens(self, req: _DecodeRequest, t_pc: float, n: int,
                   phase: str) -> None:
        """Book ``n`` tokens landing at ``t_pc`` in the request's
        waterfall and mirror the returned TTFT / per-token TPOT samples
        into the labeled histogram families. Called BEFORE the tokens are
        appended — an append can finish the request, and a finished
        waterfall refuses further bookings."""
        if req.rid is None:
            return
        ttft, samples = waterfall.on_tokens(req.rid, t_pc, n, phase=phase)
        if ttft is not None:
            self.metrics.record_ttft(ttft, cls=req.cls)
        if samples:
            self.metrics.record_tpot(samples, cls=req.cls)

    def _expire(self, req: _DecodeRequest) -> None:
        """Deadline lapsed while queued (scheduler callback) or mid-
        generation (loop check)."""
        self.metrics.record_timeout()
        self.metrics.record_evict("deadline")
        self._j_fin(req, "deadline")
        waterfall.finish(req.rid, time.perf_counter(), "deadline")
        self._finish_trace(req, time.perf_counter(),
                           status="deadline_exceeded")
        req.handle._fail(DeadlineExceeded(
            f"request expired after "
            f"{time.monotonic() - req.t_submit:.3f}s "
            f"({len(req.generated)}/{req.mnt} tokens generated)"))

    def _release(self, req: _DecodeRequest) -> None:
        if req.slot is not None:
            self._kv.release_slot(req.slot)
            req.slot = None
        if req in self._active:
            self._active.remove(req)

    def _finish(self, req: _DecodeRequest, reason: str) -> None:
        self._release(req)
        self._j_fin(req, reason)
        self.metrics.record_evict(reason)
        if reason == "cancelled":
            self.metrics.record_cancel()
        latency = time.monotonic() - req.t_submit
        self.metrics.record_response(latency)
        waterfall.finish(req.rid, time.perf_counter(), reason)
        self._finish_trace(req, time.perf_counter(), status=reason)
        runlog.emit("decode_evict", reason=reason, tenant=req.tenant,
                    generated=len(req.generated),
                    engine=self.metrics.engine_label)
        req.handle._complete(DecodeOutput(
            tokens=np.asarray(req.generated, dtype=np.int32),
            finish_reason=reason,
            prompt_len=int(req.prompt.size),
            n_preemptions=req.n_preemptions))

    def _fail(self, req: _DecodeRequest, exc: BaseException) -> None:
        self._release(req)
        self._j_fin(req, "error")
        self.metrics.record_error()
        self.metrics.record_evict("error")
        waterfall.finish(req.rid, time.perf_counter(), "error")
        self._finish_trace(req, time.perf_counter(), status="error",
                           error=type(exc).__name__)
        req.handle._fail(exc)

    # -- the decode loop ---------------------------------------------------

    def _loop(self) -> None:
        try:
            self._loop_body()
        except BaseException as e:  # fail everything rather than hang
            ptlog.error("decode loop died: %r", e)
            for req in (list(self._active) + list(self._resume)
                        + list(self._pending_admit)
                        + [item[0] for item in self._pending_handoff]):
                try:
                    self._fail(req, RuntimeError(f"decode loop died: {e!r}"))
                except Exception:
                    pass
            raise

    def _loop_body(self) -> None:
        dconf = self.decode_config
        while True:
            if self._killed:
                return  # abrupt death: kill() resolves the handles
            if self._drain_abort:
                self._force_drain()
                break
            self._sweep_cancel_deadline()
            self._probe_group()
            self._admit_handoffs()
            self._admit()
            t0 = time.perf_counter()
            did_promote = self._apply_promotes()
            did_prefill = self._prefill_some()
            did_step = self._decode_step()
            if did_prefill or did_step or did_promote:
                self.metrics.set_pages(self._kv.pages_in_use,
                                       self._kv.pages_free)
                self.metrics.set_active_slots(len(self._active))
                self.metrics.set_load(self.load())
                self.metrics.set_queue_depth(self._queue.qsize())
                self._publish_digest()
                if self._loop_trace is not None:
                    tracing.record_span(
                        "serving.decode.step", t0, time.perf_counter(),
                        parent=self._loop_trace,
                        active=len(self._active))
                continue
            # idle: nothing to prefill or step — wait for work or drain out
            if (self._active or self._resume or self._pending_admit
                    or self._pending_handoff):
                continue
            try:
                req, ok = self._queue.recv(timeout=dconf.idle_poll_s)
            except TimeoutError:
                continue
            if not ok:
                break  # closed AND drained, nothing in flight
            self._pending_admit.append(req)
        if self._prefix is not None:
            self._prefix.clear()  # drained: drop the tree's page refs
        self._promote_jobs.clear()
        self._promote_keys.clear()
        self._publish_digest()  # tree gone: publish the empty digest
        self.metrics.set_active_slots(0)
        self.metrics.set_pages(self._kv.pages_in_use, self._kv.pages_free)

    def _sweep_cancel_deadline(self) -> None:
        now = time.monotonic()
        for req in list(self._active):
            if req.cancelled:
                self._finish(req, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                self._release(req)
                self._expire(req)
        for pool in (self._resume, self._pending_admit):
            for req in list(pool):
                if req.cancelled:
                    pool.remove(req)
                    self._finish(req, "cancelled")
                elif req.deadline is not None and now > req.deadline:
                    pool.remove(req)
                    self._expire(req)
        for item in list(self._pending_handoff):
            req = item[0]
            if req.cancelled:
                self._pending_handoff.remove(item)
                self._finish(req, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                self._pending_handoff.remove(item)
                self._expire(req)

    def _admit(self) -> None:
        """Fill free slots: preempted requests first (front of line), then
        parked arrivals, then fresh pops from the scheduler. A request
        that cannot get a slot parks; pages are granted lazily at
        prefill/step time."""
        while len(self._active) < self.decode_config.max_slots:
            resumed = False
            if self._resume:
                req = self._resume.popleft()
                resumed = True
            elif self._pending_admit:
                req = self._pending_admit.popleft()
            else:
                try:
                    req, ok = self._queue.recv(timeout=0)
                except TimeoutError:
                    return
                if not ok:
                    return  # closed and drained
            if req.cancelled:
                self._finish(req, "cancelled")
                continue
            slot = self._kv.acquire_slot()
            if slot is None:  # raced vs max_slots accounting; park
                (self._resume if resumed
                 else self._pending_admit).appendleft(req)
                return
            req.slot = slot
            req.phase = "prefill"
            req.seq = (np.concatenate([req.prompt,
                                       np.asarray(req.generated, np.int32)])
                       if req.generated else req.prompt)
            req.chunks_done = 0
            self._maybe_prefix_adopt(req)
            req.t_admit_pc = time.perf_counter()
            self._active.append(req)
            if resumed:
                self.metrics.record_resume()
                runlog.emit("decode_resume", tenant=req.tenant,
                            generated=len(req.generated),
                            engine=self.metrics.engine_label)
            else:
                self.metrics.record_slot_admit()
                runlog.emit("decode_admit", tenant=req.tenant,
                            prompt_len=int(req.prompt.size), mnt=req.mnt,
                            engine=self.metrics.engine_label)
                if req.trace is not None:
                    tracing.record_span(
                        "serving.decode.queue_wait", req.t_enqueue_pc,
                        req.t_admit_pc, parent=req.trace,
                        engine=self.metrics.engine_label)

    def _admit_handoffs(self) -> None:
        """Admit handed-off requests (serving.disagg): implant the
        transferred KV pages into this engine's page arrays and enter the
        decode phase directly — no re-prefill. Any failure (geometry
        mismatch, page-pool pressure, implant error) degrades to the
        proven resume path, which re-prefills ``prompt + generated``
        token-exactly — a bad transfer costs latency, never a request."""
        import jax.numpy as jnp

        dconf = self.decode_config
        page_shape = (self._k_pages.shape[:1] + self._k_pages.shape[2:])
        while (self._pending_handoff
               and len(self._active) < dconf.max_slots):
            req, payload = self._pending_handoff.popleft()
            if req.cancelled:
                self._finish(req, "cancelled")
                continue
            slot = self._kv.acquire_slot()
            if slot is None:  # raced vs max_slots accounting; park
                self._pending_handoff.appendleft((req, payload))
                return
            req.slot = slot
            n_pages = -(-int(payload.cur_len) // dconf.page_size)
            t0_adopt = time.perf_counter()
            ok = False
            # a draft model keeps its own page arrays, which the payload
            # does not carry — re-prefill fills both caches correctly.
            # A payload gathered under a DIFFERENT tp degree ran a
            # different partitioned program; adopting its pages verbatim
            # would splice two programs' numerics mid-sequence, so
            # cross-degree adoption degrades to re-prefill (the target
            # group recomputes the context self-consistently).
            if (not self._spec_k
                    and int(getattr(payload, "tp_degree", 1)) == self.tp_degree
                    and payload.page_size == dconf.page_size
                    and 0 < payload.cur_len <= dconf.max_context
                    and len(payload.k_pages) == n_pages
                    and len(payload.v_pages) == n_pages
                    and all(p.shape == page_shape
                            for p in payload.k_pages + payload.v_pages)):
                try:
                    if self._ensure_pages(req, int(payload.cur_len)):
                        table = self._kv.page_tables[req.slot]
                        for li in range(n_pages):
                            pid = jnp.int32(table[li])
                            self._k_pages = self._implant_page(
                                self._k_pages, pid,
                                jnp.asarray(payload.k_pages[li],
                                            self._cache_dtype))
                            self._v_pages = self._implant_page(
                                self._v_pages, pid,
                                jnp.asarray(payload.v_pages[li],
                                            self._cache_dtype))
                        ok = True
                except Exception as e:
                    ptlog.warning(
                        "handoff page adoption failed (%r); "
                        "re-prefilling request %s", e, req.rid)
            if not ok:
                self._release(req)
                req.phase = "queued"
                req.seq = None
                req.chunks_done = 0
                req.cur_len = 0
                self._resume.append(req)
                self.metrics.record_recover(1)
                continue
            # the adopted pages cover positions [0, cur_len); last_tok is
            # the token pending its KV write — exactly mid-decode state
            req.seq = None
            req.phase = "decode"
            req.cur_len = int(payload.cur_len)
            req.chunks_done = self._n_chunks(
                int(req.prompt.size) + len(req.generated))
            req.last_tok = int(payload.last_tok)
            self._kv.seq_lens[req.slot] = req.cur_len
            req.t_admit_pc = time.perf_counter()
            self._active.append(req)
            self.metrics.record_handoff_in()
            self.metrics.record_slot_admit()
            if req.trace is not None:
                tracing.record_span(
                    "serving.handoff.adopt", t0_adopt, time.perf_counter(),
                    parent=req.trace, engine=self.metrics.engine_label,
                    from_engine=payload.src, pages=n_pages, rid=req.rid)
            runlog.emit("handoff_adopted", rid=req.rid,
                        from_engine=payload.src, pages=n_pages,
                        engine=self.metrics.engine_label)

    def _maybe_prefix_adopt(self, req: _DecodeRequest) -> None:
        """Consult the radix prefix cache at slot assignment: adopt the
        longest cached page run of ``req.seq`` (capped at ``len(seq)-1`` —
        the final token must always prefill so its logits seed the first
        generated token) and skip the prefill chunks it fully covers.
        When the hit boundary is not chunk-aligned, the continuation chunk
        would write into shared pages, so the straddled pages are
        copied-on-write first (device-side page copy; the chunk then
        rewrites the straddled span with identical values into the private
        pages). If the pool cannot supply the CoW pages, the hit shrinks
        to the chunk-aligned boundary instead — never a partial adopt."""
        if self._prefix is None:
            return
        self.metrics.record_prompt_tokens(len(req.seq))
        ps = self.decode_config.page_size
        C = self.decode_config.prefill_chunk
        max_pages = min((len(req.seq) - 1) // ps, self._kv.pages_per_slot)
        if max_pages <= 0:
            return
        pages = self._prefix.match(req.seq, max_pages)
        m = len(pages)
        # hierarchical KV: the tree's true depth (pre-CoW-shrink) is the
        # promote frontier — when the host tier holds the NEXT page of
        # this prefix, enqueue an async promote so the next same-prefix
        # request hits in HBM. THIS request prefills as usual either way
        # (token-exact regardless of promotion timing).
        if (self._host_tier is not None and m < max_pages
                and self._host_tier.contains(req.seq, m + 1)):
            self._host_request_promote(req.seq, max_pages, trace=req.trace)
        while m > 0:
            c0 = (m * ps) // C
            lo = (c0 * C) // ps  # first logical page the next chunk touches
            n_cow = 0 if (m * ps) % C == 0 else m - lo
            if n_cow == 0 or self._kv.allocator.num_free >= n_cow:
                break
            m = lo  # drop the straddled tail; strictly decreasing
        if m <= 0:
            return
        import jax.numpy as jnp

        self._kv.adopt_pages(req.slot, pages[:m])
        c0 = (m * ps) // C
        cow_done = 0
        if (m * ps) % C != 0:
            for li in range((c0 * C) // ps, m):
                src, dst = self._kv.private_copy(req.slot, li)
                s, d = jnp.int32(src), jnp.int32(dst)
                self._k_pages = self._copy_page(self._k_pages, s, d)
                self._v_pages = self._copy_page(self._v_pages, s, d)
                if self._spec_k:
                    self._dk_pages = self._copy_page_d(self._dk_pages, s, d)
                    self._dv_pages = self._copy_page_d(self._dv_pages, s, d)
                cow_done += 1
        req.chunks_done = c0
        self._kv.seq_lens[req.slot] = m * ps
        if cow_done:
            self.metrics.record_cow(cow_done)
        self.metrics.record_prefix_hit(m * ps, saved_chunks=c0)
        runlog.emit("decode_prefix_hit", hit_tokens=m * ps,
                    saved_chunks=c0, cow=cow_done,
                    engine=self.metrics.engine_label)

    # -- hierarchical KV host tier (serving.host_tier) ---------------------

    def _host_demote(self, req: _DecodeRequest, n_full: int) -> None:
        """Write-through demote: gather ``req``'s first ``n_full`` fully-
        written pages off-device and store them in the host tier. Called
        on the loop thread right after the radix insert, while the tree
        holds refs — the pages are immutable and cannot be recycled under
        a stale key. Also the crash-recovery write: with a SHARED pool,
        these bytes outlive this engine's kill(), so a restarted engine
        repopulates its tree from here after journal replay."""
        if self._host_tier is None:
            return
        import jax.numpy as jnp

        pages = self._kv.slot_pages(req.slot)[:n_full]
        wrote = 0
        bp = 0
        t0_demote = time.perf_counter()
        try:
            for i, p in enumerate(pages):
                if self._host_tier.contains(req.seq, i + 1):
                    continue  # shared prefix already demoted — dedup
                k = np.asarray(self._gather_page(self._k_pages,
                                                 jnp.int32(p)))
                v = np.asarray(self._gather_page(self._v_pages,
                                                 jnp.int32(p)))
                res = self._host_tier.put(
                    req.seq, i, k, v, engine=self.metrics.engine_label)
                wrote += res["added"]
                if res["evicted"]:
                    bp += 1
        except Exception as e:
            # demote is strictly best-effort: an injected stall/error (or
            # real host-memory pressure) must never fail the request —
            # the page simply stays HBM-only
            ptlog.warning("host-tier demote failed: %r; page stays "
                          "HBM-only", e)
        if wrote:
            self.metrics.record_host_demote(wrote)
            if req.trace is not None:
                tracing.record_span(
                    "serving.host_tier.demote", t0_demote,
                    time.perf_counter(), parent=req.trace,
                    engine=self.metrics.engine_label, pages=wrote)
        if bp:
            self.metrics.record_host_backpressure(bp)
        self.metrics.set_host_tier_bytes(self._host_tier.bytes_used,
                                         self._host_tier.max_bytes)

    def _host_request_promote(self, seq: np.ndarray, want_pages: int,
                              trace=None) -> None:
        """Enqueue an async promote of this prefix up to ``want_pages``
        pages; dedup by prefix digest so a storm of same-prefix requests
        enqueues one job. The hit is counted HERE (the routing-visible
        event), not at apply time. ``trace`` is the enqueueing request's
        span context — the applied promote parents its span there, so the
        fleet trace shows which request warmed the prefix."""
        ps = self.decode_config.page_size
        toks = np.asarray(seq[:want_pages * ps], np.int32)
        key = zlib.crc32(toks.tobytes()) & 0xFFFFFFFF
        if key in self._promote_keys:
            return
        self._promote_keys.add(key)
        self._promote_jobs.append((key, toks, want_pages, trace))
        self.metrics.record_host_hit()

    def _apply_promotes(self) -> bool:
        """Apply queued host-tier promotions on the loop thread, at most
        ``host_promote_pages_per_iter`` pages per iteration — off the
        request path (the enqueueing request prefilled normally) and
        bounded so promotion stays decode-p99-neutral.

        Each application re-checks the tree (``peek``) because the job
        may be stale: a concurrent admission may have prefilled the
        prefix already, or eviction may have shortened it since enqueue.
        Page ownership follows the loader-handoff discipline documented
        on ``PageAllocator.refcounts``: alloc (ref 1) → implant →
        ``insert`` refs for the tree (→ 2) → free the loader ref (→ 1,
        tree-owned). A CRC failure quarantines the host page and drops
        the job — the prefix simply stays cold and re-prefills."""
        if self._host_tier is None or not self._promote_jobs:
            return False
        import jax.numpy as jnp

        ps = self.decode_config.page_size
        budget = self.decode_config.host_promote_pages_per_iter
        did = False
        while budget > 0 and self._promote_jobs:
            key, toks, want, job_trace = self._promote_jobs.popleft()
            if self._prefix.max_pages is not None:
                # promoting past the tree's own size cap is wasted motion:
                # the insert would be trimmed right back out
                want = min(want, self._prefix.max_pages)
            tree_pages = self._prefix.peek(toks, want)
            d = len(tree_pages)
            if d >= want:  # stale: someone prefilled it meanwhile
                self._promote_keys.discard(key)
                continue
            t0 = time.perf_counter()
            try:
                got = self._host_tier.get(
                    toks, d, engine=self.metrics.engine_label)
            except HostPageCorrupt:
                # bit-flipped host page: quarantined by the pool; the
                # prefix stays cold and the next request re-prefills
                # token-exactly instead of trusting it
                self.metrics.record_host_quarantine()
                self._promote_keys.discard(key)
                continue
            except Exception as e:
                ptlog.warning("host-tier promote read failed: %r", e)
                self._promote_keys.discard(key)
                continue
            if got is None:  # evicted from the pool since enqueue
                self._promote_keys.discard(key)
                continue
            alloced = self._kv.allocator.alloc(1)
            if alloced is None:
                # never steal device pages from live traffic for a
                # warm-ahead; drop the job — the next admission re-probes
                self._promote_keys.discard(key)
                continue
            page = alloced[0]
            p = jnp.int32(page)
            self._k_pages = self._implant_page(
                self._k_pages, p, jnp.asarray(got[0], self._cache_dtype))
            self._v_pages = self._implant_page(
                self._v_pages, p, jnp.asarray(got[1], self._cache_dtype))
            self._prefix.insert(toks[:(d + 1) * ps], tree_pages + [page])
            self._kv.allocator.free([page])  # hand ownership to the tree
            budget -= 1
            did = True
            t1 = time.perf_counter()
            self.metrics.record_host_promote(t1 - t0)
            parent = job_trace if job_trace is not None else self._loop_trace
            if parent is not None:
                tracing.record_span(
                    "serving.host_tier.promote", t0, t1, parent=parent,
                    engine=self.metrics.engine_label, page=d)
            # progress guard: the insert can be trimmed straight back out
            # (size-cap eviction, allocator pressure). Re-enqueue only on
            # real depth growth — otherwise a capped tree and a warm pool
            # would promote-evict-promote forever and the loop never idles
            nd = len(self._prefix.peek(toks, want))
            if d < nd < want and self._host_tier.contains(toks, nd + 1):
                self._promote_jobs.append((key, toks, want, job_trace))
            else:
                self._promote_keys.discard(key)
        if did:
            self.metrics.set_pages(self._kv.pages_in_use,
                                   self._kv.pages_free)
        return did

    def _publish_digest(self) -> None:
        """Republish the routing digest when the tree changed. Loop-thread
        only; readers (DecodeFleet._pick, any thread) see an immutable
        frozenset swapped atomically under the GIL."""
        if not self.decode_config.prefix_digest or self._prefix is None:
            return
        v = self._prefix.digest_version
        if v != self._digest_seen:
            self._digest_seen = v
            self._digest_pub = self._prefix.digests()

    def prefix_digest(self) -> frozenset:
        """The engine's published prefix-digest set (empty unless
        ``DecodeConfig.prefix_digest``). Lock-free snapshot."""
        return self._digest_pub

    def prefix_match_depth(self, digests: "List[int]") -> int:
        """Longest prefix (in pages) of a prompt's digest chain (from
        :func:`serving.host_tier.prefix_digests`) this engine has cached.
        The routing score: fleets send each prompt to the deepest match."""
        pub = self._digest_pub
        depth = 0
        for dg in digests:
            if dg not in pub:
                break
            depth += 1
        return depth

    @property
    def host_tier(self) -> Optional[HostPagePool]:
        """The engine's host-RAM page pool (shared or private; None when
        the tier is off)."""
        return self._host_tier

    def _ensure_pages(self, req: _DecodeRequest, n_positions: int) -> bool:
        """Grow ``req``'s slot to ``n_positions``, evicting prefix-cache
        pages first and then preempting the most recently admitted OTHER
        request (LIFO) while the pool is short. The kv-cache deadlock
        guard guarantees a lone request can always grow to max_context
        once the tree is drained, so this terminates."""
        while not self._kv.ensure_capacity(req.slot, n_positions):
            if self._prefix is not None and self._prefix.evict(1) > 0:
                continue  # tree pages are cheaper to reclaim than preempts
            victim = next((r for r in reversed(self._active) if r is not req),
                          None)
            if victim is None:  # unreachable per the pool-size guard
                self._fail(req, RuntimeError(
                    "page pool exhausted with no preemption victim"))
                return False
            self._preempt(victim)
        return True

    def _preempt(self, victim: _DecodeRequest) -> None:
        """Evict ``victim`` on page exhaustion, keeping its generated
        prefix: it re-enters at the front of the line and re-prefills
        ``prompt + generated`` — greedy decode continues identically."""
        freed = self._kv.slot_page_count(victim.slot)
        self._release(victim)
        victim.phase = "queued"
        victim.seq = None
        victim.chunks_done = 0
        victim.cur_len = 0
        victim.n_preemptions += 1
        self._resume.append(victim)
        self.metrics.record_preempt()
        runlog.emit("decode_preempt", tenant=victim.tenant,
                    generated=len(victim.generated), pages_freed=freed,
                    engine=self.metrics.engine_label)

    def _append_token(self, req: _DecodeRequest, tok: int) -> None:
        """Host-side finish checks for one sampled token."""
        req.generated.append(tok)
        self._j_tok(req, tok)
        eos = self.decode_config.eos_id
        if eos is not None and tok == eos:
            self._finish(req, "eos")
        elif len(req.generated) >= req.mnt:
            self._finish(req, "length")
        else:
            req.last_tok = tok

    def _next_key(self):
        if self._rng is None:
            return None
        self._rng, key = jax.random.split(self._rng)
        return key

    def _prefill_some(self) -> bool:
        """Run up to ``prefill_chunks_per_iter`` chunks across prefill-
        phase requests (oldest first)."""
        import jax.numpy as jnp

        dconf = self.decode_config
        budget = dconf.prefill_chunks_per_iter
        progressed = False
        for req in list(self._active):
            if budget <= 0:
                break
            if req.phase != "prefill":
                continue
            C = dconf.prefill_chunk
            c = req.chunks_done
            n_chunks = self._n_chunks(len(req.seq))
            chunk_end = (c + 1) * C
            if not self._ensure_pages(req, min(chunk_end, len(req.seq))):
                continue
            chunk = np.zeros((C,), np.int32)
            seg = req.seq[c * C:min((c + 1) * C, len(req.seq))]
            chunk[:len(seg)] = seg
            last = len(req.seq) - 1 - c * C
            t0 = time.perf_counter()
            try:
                table_row = jnp.asarray(self._kv.page_tables[req.slot])
                tok, self._k_pages, self._v_pages = self._prefill(
                    self._params, jnp.asarray(chunk),
                    jnp.int32(c * C), jnp.int32(max(last, 0)),
                    table_row,
                    self._k_pages, self._v_pages, self._next_key())
                if self._spec_k:
                    # the draft's cache must cover the same prefix so its
                    # proposals attend real context (sampled token unused)
                    _, self._dk_pages, self._dv_pages = self._draft_prefill(
                        self._draft_params, jnp.asarray(chunk),
                        jnp.int32(c * C), jnp.int32(max(last, 0)),
                        table_row,
                        self._dk_pages, self._dv_pages, None)
                last_chunk = (c == n_chunks - 1)
                tok = int(tok) if last_chunk else 0
            except Exception as e:
                self._recover_request(req, e)
                continue
            t1 = time.perf_counter()
            self.metrics.record_prefill_chunk(t1 - t0)
            self.cost.observe_chunk(t1 - t0)
            if req.trace is not None:
                tracing.record_span("serving.decode.prefill", t0, t1,
                                    parent=req.trace, chunk=c,
                                    engine=self.metrics.engine_label)
            req.chunks_done = c + 1
            self._kv.seq_lens[req.slot] = min(chunk_end, len(req.seq))
            budget -= 1
            progressed = True
            if last_chunk:
                if self._prefix is not None:
                    # every fully-written page is immutable from here on
                    # (decode writes land past len(seq)) — publish them
                    n_full = len(req.seq) // dconf.page_size
                    if n_full:
                        self._prefix.insert(
                            req.seq, self._kv.slot_pages(req.slot)[:n_full])
                        # write-through demote: the same immutable pages,
                        # while the tree holds refs (no recycle race)
                        self._host_demote(req, n_full)
                req.phase = "decode"
                req.cur_len = len(req.seq)
                # the final chunk's sample IS the next token after the
                # prefilled sequence — the first (or, after a resume, the
                # next) generated token
                self._wf_tokens(req, t1, 1, "prefill")
                self._append_token(req, tok)
                # prefill role (serving.disagg): publish instead of
                # decoding here — unless that one sampled token already
                # finished the request (it left _active via _finish).
                # Draft-model engines keep their work local: the payload
                # carries only the target cache.
                if (self._handoff_sink is not None and not self._spec_k
                        and req in self._active):
                    self._publish_handoff(req)
        return progressed

    def _decode_step(self) -> bool:
        """One decode iteration: with a draft model configured, slots with
        headroom for a full ``spec_tokens + 1`` block go through the
        draft-and-verify path; the rest (within ``spec_tokens`` positions
        of ``max_context``) fall back to the plain one-token step, which
        is always exact. Both substeps keep the scratch-page discipline:
        uninvolved slots get scratch table rows and position 0."""
        did = False
        handled: set = set()
        if self._spec_k:
            limit = self.decode_config.max_context - self._spec_k - 1
            spec = [r for r in self._active
                    if r.phase == "decode" and r.cur_len <= limit]
            if spec:
                handled = {id(r) for r in spec}
                did = self._verify_decode_step(spec) or did
        rest = [r for r in self._active
                if r.phase == "decode" and id(r) not in handled]
        if rest:
            did = self._plain_decode_step(rest) or did
        return did

    def _plain_decode_step(self, decoding: List[_DecodeRequest]) -> bool:
        """One jitted iteration over the given decode-phase slots. Slots
        that are inactive or mid-prefill get a scratch table row and
        position 0, so their garbage writes land on the scratch page and
        their outputs are ignored — no per-slot branching inside the
        step."""
        import jax.numpy as jnp

        if not decoding:
            return False
        for req in list(decoding):
            if req not in self._active:
                # preempted as the victim of an earlier grow this iteration
                decoding.remove(req)
                continue
            if not self._ensure_pages(req, req.cur_len + 1):
                decoding.remove(req)
        # a later grow can also preempt an already-checked request
        decoding = [r for r in decoding if r in self._active]
        if not decoding:
            return False
        S = self.decode_config.max_slots
        P = self._kv.pages_per_slot
        tokens = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        tables = np.full((S, P), SCRATCH_PAGE, np.int32)
        for req in decoding:
            tokens[req.slot] = req.last_tok
            positions[req.slot] = req.cur_len
            tables[req.slot] = self._kv.page_tables[req.slot]
        t0 = time.perf_counter()
        try:
            faults.inject(faults.DECODE_STEP,
                          engine=self.metrics.engine_label)
            nxt, self._k_pages, self._v_pages = self._step(
                self._params, jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(tables), self._k_pages, self._v_pages,
                self._next_key())
            nxt = np.asarray(nxt)
        except Exception as e:
            # a failed step loses this iteration's K/V writes for every
            # in-flight sequence
            if self.decode_config.recovery:
                self._recover_step_fault(e)
                return True
            runlog.emit("decode_step_error", error=repr(e),
                        engine=self.metrics.engine_label)
            ptlog.error("decode step failed: %r", e)
            for req in list(self._active):
                self._fail(req, e)
            return True
        t1 = time.perf_counter()
        self._note_step_ok()
        self.metrics.record_step(len(decoding), S, t1 - t0, len(decoding))
        self.cost.observe_step(t1 - t0)
        for req in list(decoding):
            req.cur_len += 1
            self._kv.seq_lens[req.slot] = req.cur_len
            self._wf_tokens(req, t1, 1, "decode")
            self._append_token(req, int(nxt[req.slot]))
        return True

    def _verify_decode_step(self, spec: List[_DecodeRequest]) -> bool:
        """One draft-and-verify iteration: K sequential draft steps
        propose a block, one jitted verify step scores all K+1 positions
        against the target's paged cache, and each slot accepts the
        longest draft prefix matching the target's own greedy argmaxes
        plus the bonus token — at least 1, at most K+1 tokens per slot
        per iteration, token-exact vs sequential decode.

        Rollback is host-side only: rejected positions sit past the
        accepted frontier, masked until the next block overwrites them
        (both caches), so :meth:`PagedKVCache.trim` just returns the
        surplus pages granted for the block."""
        import jax.numpy as jnp

        K = self._spec_k
        for req in list(spec):
            if req not in self._active:
                # preempted as the victim of an earlier grow this iteration
                spec.remove(req)
                continue
            if not self._ensure_pages(req, req.cur_len + K + 1):
                spec.remove(req)
        spec = [r for r in spec if r in self._active]
        if not spec:
            return False
        S = self.decode_config.max_slots
        P = self._kv.pages_per_slot
        tokens = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        tables = np.full((S, P), SCRATCH_PAGE, np.int32)
        for req in spec:
            tokens[req.slot] = req.last_tok
            positions[req.slot] = req.cur_len
            tables[req.slot] = self._kv.page_tables[req.slot]
        t0 = time.perf_counter()
        try:
            faults.inject(faults.DECODE_STEP,
                          engine=self.metrics.engine_label)
            tables_j = jnp.asarray(tables)
            pos = jnp.asarray(positions)
            cur = jnp.asarray(tokens)
            cols = []
            for j in range(K):
                cur, self._dk_pages, self._dv_pages = self._draft_step(
                    self._draft_params, cur, pos + j, tables_j,
                    self._dk_pages, self._dv_pages, None)
                cols.append(cur)
            draft_mat = np.stack([np.asarray(c) for c in cols], 1)  # [S, K]
            block = np.concatenate([tokens[:, None], draft_mat], 1)
            out, self._k_pages, self._v_pages = self._verify(
                self._params, jnp.asarray(block), pos, tables_j,
                self._k_pages, self._v_pages)
            out = np.asarray(out)
        except Exception as e:
            # same contract as the plain step: the iteration's K/V writes
            # (draft and target) are lost; recovery re-prefills from host
            if self.decode_config.recovery:
                self._recover_step_fault(e)
                return True
            runlog.emit("decode_step_error", error=repr(e),
                        engine=self.metrics.engine_label)
            ptlog.error("verify step failed: %r", e)
            for req in list(self._active):
                self._fail(req, e)
            return True
        t1 = time.perf_counter()
        self._note_step_ok()
        new_tokens = 0
        drafts_accepted = 0
        eos = self.decode_config.eos_id
        for req in list(spec):
            row = out[req.slot]
            n_acc = 0
            while (n_acc < K
                   and int(draft_mat[req.slot, n_acc]) == int(row[n_acc])):
                n_acc += 1
            drafts_accepted += n_acc
            # waterfall booking mirrors _append_token's finish conditions
            # exactly: the block truncates at eos / budget, and the n
            # tokens this iteration lands book n TPOT samples of dt/n —
            # the speculation-aware accounting contract
            n_land = min(n_acc + 1, req.mnt - len(req.generated))
            if eos is not None:
                for j in range(n_land):
                    if int(row[j]) == eos:
                        n_land = j + 1
                        break
            self._wf_tokens(req, t1, n_land, "verify")
            for j in range(n_acc + 1):
                if req not in self._active:
                    break  # finished (eos / budget) mid-block
                req.cur_len += 1
                self._kv.seq_lens[req.slot] = req.cur_len
                self._append_token(req, int(row[j]))
                new_tokens += 1
            if req in self._active:
                # roll back pages granted for rejected draft positions
                self._kv.trim(req.slot, req.cur_len)
        self.metrics.record_verify_step(
            len(spec), S, t1 - t0, new_tokens,
            drafts_proposed=len(spec) * K, drafts_accepted=drafts_accepted)
        self.cost.observe_verify(t1 - t0, new_tokens / len(spec))
        if self._loop_trace is not None:
            tracing.record_span(
                "serving.decode.verify", t0, t1, parent=self._loop_trace,
                slots=len(spec), accepted=new_tokens)
        return True

    # -- zero-loss recovery (serving.recovery) -----------------------------

    @property
    def breaker(self) -> CircuitBreaker:
        """This engine's health breaker: tripped on ``unhealthy_after``
        consecutive step faults; a DecodeFleet routes around OPEN
        breakers and spends half-open probes to re-admit."""
        return self._breaker

    def _flight_dump(self, reason: str) -> None:
        """Best-effort post-mortem hook: when a FlightRecorder is
        installed, dump a bundle capturing this engine's terminal state
        (span/runlog tails, held locks, page refcounts, breaker and
        host-tier snapshots). Never raises — observability must not
        alter the failure path it is recording."""
        try:
            from paddle_tpu.observability import flight_recorder as fr
            fr.maybe_dump(reason, engine=self)
        except Exception as e:
            ptlog.warning("flight-recorder dump failed: %r", e)

    def _note_step_ok(self) -> None:
        """A clean decode iteration: the device is serving again."""
        if not self._consec_faults and not self._breaker_dirty:
            return
        self._consec_faults = 0
        self._recover_prev_delay = 0.0
        self.metrics.set_consecutive_faults(0)
        self._breaker_dirty = False
        if self._breaker.record_success():
            runlog.emit("engine_recovered",
                        engine=self.metrics.engine_label)

    def _probe_group(self) -> None:
        """Group-backed engines only: per-member canary at
        ``group_probe_every_s`` cadence. ANY member fault is fatal for
        the WHOLE group — the jitted program spans every chip, so one
        sick member poisons every shard's collectives: trip the breaker
        and eject (migrate via the fleet when attached, else quarantine
        through the resume path). Healthy probes feed the shard-skew
        straggler watch, which localizes a slow chip by shard index."""
        if self._group is None:
            return
        now = time.monotonic()
        if now - self._last_probe < self.decode_config.group_probe_every_s:
            return
        self._last_probe = now
        try:
            times = probe_members(
                self._group, engine_label=self.metrics.engine_label)
        except Exception as e:
            self.metrics.record_member_fault()
            self._breaker_dirty = True
            runlog.emit("group_member_fault",
                        engine=self.metrics.engine_label,
                        group=self._group.name, error=repr(e),
                        in_flight=len(self._active))
            ptlog.error("group %s member fault (%r): ejecting whole group",
                        self._group.name, e)
            if self._rescue_sink is not None:
                self._migrate_out(e)
            else:
                self._breaker.trip()
                self._quarantine(e)
            return
        skew, flagged = self._straggler.observe(times)
        self.metrics.set_shard_skew(skew)
        for shard, secs in times.items():
            self.metrics.set_shard_probe_seconds(shard, secs)
        if flagged is not None:
            self.metrics.record_shard_straggler()
            runlog.emit("group_shard_straggler",
                        engine=self.metrics.engine_label,
                        group=self._group.name, shard=flagged,
                        skew=round(skew, 3))

    def _recover_step_fault(self, exc: BaseException) -> None:
        """A jitted decode step failed: only that iteration's KV writes
        are lost, and every live request is reconstructible from host
        state. Ladder: quarantine + re-admit (per-request budget) →
        after ``unhealthy_after`` consecutive faults, migrate everything
        to a healthy engine via the fleet's rescue sink. A fault inside
        recovery itself (DECODE_RECOVER) escalates one rung."""
        dconf = self.decode_config
        self.metrics.record_step_fault()
        self._consec_faults += 1
        self.metrics.set_consecutive_faults(self._consec_faults)
        self._breaker_dirty = True
        tripped = self._breaker.record_failure()
        if tripped:
            self._flight_dump("engine_fault")
        runlog.emit("decode_step_error", error=repr(exc), recovering=True,
                    consecutive=self._consec_faults, tripped=tripped,
                    engine=self.metrics.engine_label)
        ptlog.warning(
            "decode step failed (%r); recovering %d request(s) "
            "(consecutive fault %d)", exc, len(self._active),
            self._consec_faults)
        try:
            faults.inject(faults.DECODE_RECOVER,
                          engine=self.metrics.engine_label)
            if (self._consec_faults >= dconf.unhealthy_after
                    and self._rescue_sink is not None):
                self._migrate_out(exc)
                return
            self._quarantine(exc)
        except Exception as rexc:
            # recovery itself faulted: escalate straight to migration
            # when a fleet can take the work, else the pre-recovery
            # fail-everything behavior (never hang the handles)
            ptlog.error("decode recovery failed: %r", rexc)
            if self._rescue_sink is not None:
                self._migrate_out(rexc)
            else:
                for req in list(self._active):
                    self._fail(req, rexc)
                self._kv.release_all()
            return
        # spread repeated quarantine cycles out (decorrelated so engines
        # sharing a sick host don't re-synchronize on the device)
        d = retry_mod.decorrelated_backoff(
            self._recover_prev_delay, dconf.recovery_base_delay_s,
            dconf.recovery_max_delay_s)
        self._recover_prev_delay = d
        time.sleep(d)

    def _quarantine(self, exc: BaseException) -> None:
        """Release every slot (the poisoned iteration's KV writes are
        untrusted) and send live requests back through the proven
        resume/re-prefill path — token-exact, per the preemption
        contract. A request past its lifetime recovery budget fails with
        a typed RetriesExhausted instead of looping."""
        requeued = 0
        for req in list(self._active):
            self._release(req)
            req.recoveries += 1
            if req.recoveries > self.decode_config.recovery_retries:
                self.metrics.record_retries_exhausted()
                err = RetriesExhausted(
                    f"request {req.rid}: recovery budget "
                    f"({self.decode_config.recovery_retries}) exhausted "
                    f"(last fault: {exc!r})", request_id=req.rid)
                err.__cause__ = exc
                self._fail(req, err)
                continue
            req.phase = "queued"
            req.seq = None
            req.chunks_done = 0
            req.cur_len = 0
            self._resume.append(req)
            requeued += 1
            runlog.emit(
                "request_recovered", rid=req.rid,
                recoveries=req.recoveries, generated=len(req.generated),
                engine=self.metrics.engine_label,
                trace_id=req.trace.trace_id if req.trace else None)
        self._kv.release_all()  # nothing survives the poisoned iteration
        if requeued:
            self.metrics.record_recover(requeued)

    def _recover_request(self, req: _DecodeRequest,
                         exc: BaseException) -> None:
        """A prefill chunk failed for ONE request (garbage confined to
        its slot's pages): quarantine just that request through the
        resume path, on the same lifetime budget. Does not count toward
        engine-level consecutive faults — a single poison prompt must
        exhaust its own budget, not condemn the engine."""
        if not self.decode_config.recovery:
            self._fail(req, exc)
            return
        self.metrics.record_step_fault()
        self._release(req)
        req.recoveries += 1
        if req.recoveries > self.decode_config.recovery_retries:
            self.metrics.record_retries_exhausted()
            err = RetriesExhausted(
                f"request {req.rid}: recovery budget "
                f"({self.decode_config.recovery_retries}) exhausted "
                f"(last fault: {exc!r})", request_id=req.rid)
            err.__cause__ = exc
            self._fail(req, err)
            return
        req.phase = "queued"
        req.seq = None
        req.chunks_done = 0
        req.cur_len = 0
        self._resume.append(req)
        self.metrics.record_recover(1)
        runlog.emit("request_recovered", rid=req.rid,
                    recoveries=req.recoveries, generated=len(req.generated),
                    engine=self.metrics.engine_label,
                    trace_id=req.trace.trace_id if req.trace else None)

    def _drain_packets(self) -> List[RescuePacket]:
        """Drain every live request's host state (active slots, parked
        queues, and the scheduler backlog) into RescuePackets. Slots are
        released and each rid closes in the journal with "migrated" so a
        replay of THIS engine's journal won't resurrect them — the
        adopting engine journals them afresh."""
        drained: List[_DecodeRequest] = []
        for req in list(self._active):
            self._release(req)
            drained.append(req)
        while self._resume:
            drained.append(self._resume.popleft())
        while self._pending_admit:
            drained.append(self._pending_admit.popleft())
        while self._pending_handoff:
            drained.append(self._pending_handoff.popleft()[0])
        while True:
            try:
                req, ok = self._queue.recv(timeout=0)
            except Exception:
                break
            if not ok:
                break
            drained.append(req)
        self._kv.release_all()
        packets: List[RescuePacket] = []
        for req in drained:
            self._j_fin(req, "migrated")
            packets.append(RescuePacket(
                rid=req.rid or "", prompt=req.prompt, mnt=req.mnt,
                generated=list(req.generated), tenant=req.tenant,
                cls=req.cls, deadline=req.deadline, t_submit=req.t_submit,
                n_preemptions=req.n_preemptions, handle=req.handle,
                trace=req.trace, cancelled=req.cancelled))
        return packets

    def _migrate_out(self, exc: BaseException) -> None:
        """Declare this engine unhealthy: trip the breaker (the fleet
        stops routing here until a half-open probe succeeds) and hand
        every live request to the rescue sink for adoption elsewhere."""
        self._breaker.trip()
        self._breaker_dirty = True
        self._flight_dump("breaker_trip")
        packets = self._drain_packets()
        runlog.emit("engine_unhealthy", engine=self.metrics.engine_label,
                    error=repr(exc), in_flight=len(packets),
                    consecutive=self._consec_faults)
        ptlog.error(
            "engine %s unhealthy after %d consecutive step faults; "
            "migrating %d request(s)", self.metrics.engine_label,
            self._consec_faults, len(packets))
        adopted = self._rescue_sink(self, packets) if packets else 0
        self.metrics.record_migrate(adopted)
        self._consec_faults = 0
        self._recover_prev_delay = 0.0
        self.metrics.set_consecutive_faults(0)

    def adopt_rescue(self, packet: RescuePacket,
                     from_engine: Optional[str] = None) -> DecodeHandle:
        """Adopt a request drained from an unhealthy engine (or rebuilt
        by journal replay): generation continues token-exactly from its
        ``prompt + generated`` host state through the resume path. The
        client's original handle — when the packet carries one — is
        repointed here, so ``result()``/``cancel()`` keep working across
        the migration. Returns the (possibly fresh) handle."""
        if self._closed:
            raise EngineClosedError("engine is closed")
        t0_rescue = time.perf_counter()
        prompt = np.asarray(packet.prompt, np.int32).reshape(-1)
        req = _DecodeRequest(
            prompt, int(packet.mnt),
            self._n_chunks(int(prompt.size) + len(packet.generated)),
            packet.deadline, packet.t_submit or time.monotonic(),
            tenant=packet.tenant, cls=packet.cls)
        req.generated = [int(t) for t in packet.generated]
        req.n_preemptions = packet.n_preemptions
        req.cancelled = packet.cancelled
        req.rid = packet.rid or (
            f"{self.metrics.engine_label}-{_RID_SALT}-"
            f"{next(self._rid_seq)}")
        if packet.handle is not None:
            req.handle = packet.handle
            packet.handle._req = req  # cancel() must target the new req
        req.trace = packet.trace
        if req.trace is None and tracing.tracing_enabled():
            req.trace = tracing.SpanContext.new_trace()
        if req.trace is not None:
            req.handle.trace = req.trace
            req.t_enqueue_pc = time.perf_counter()
        # already satisfied (e.g. crash landed between the last token and
        # its fin record): complete without re-decoding a single token
        eos = self.decode_config.eos_id
        done_eos = (eos is not None and req.generated
                    and req.generated[-1] == eos)
        if done_eos or len(req.generated) >= req.mnt:
            reason = "eos" if done_eos else "length"
            self._j_admit(req)
            self._j_fin(req, reason)
            req.handle._complete(DecodeOutput(
                tokens=np.asarray(req.generated, dtype=np.int32),
                finish_reason=reason, prompt_len=int(req.prompt.size),
                n_preemptions=req.n_preemptions))
            return req.handle
        self._j_admit(req)
        self.metrics.record_submit()
        if req.trace is not None:
            tracing.record_span(
                "serving.rescue", t0_rescue, time.perf_counter(),
                parent=req.trace, engine=self.metrics.engine_label,
                from_engine=from_engine, rid=req.rid,
                generated=len(req.generated))
        if from_engine is not None:
            runlog.emit(
                "request_migrated", rid=req.rid, from_engine=from_engine,
                to_engine=self.metrics.engine_label,
                generated=len(req.generated),
                trace_id=req.trace.trace_id if req.trace else None)
        # front-of-line with the resumed: the request already waited once
        self._resume.append(req)
        self._queue.poke()  # an idle loop is parked in recv(idle_poll_s)
        return req.handle

    # -- disaggregated prefill/decode handoff (serving.disagg) -------------

    def _publish_handoff(self, req: _DecodeRequest) -> None:
        """Prefill-role exit: prefill just completed, so the slot's pages
        hold the request's full context — gather them off-device, release
        the slot, and hand the payload to the router's sink. Durability
        (journal handoff record + receiver ack) is the router's job; a
        sink failure degrades to decoding locally through the resume
        path, so a broken transfer never loses the request."""
        import jax.numpy as jnp

        from paddle_tpu.serving.disagg import HandoffPayload

        dconf = self.decode_config
        n_pages = -(-req.cur_len // dconf.page_size)
        # gather BEFORE _release: freed pages can be rewritten immediately
        pages = self._kv.slot_pages(req.slot)[:n_pages]
        k_pages = [np.asarray(self._gather_page(self._k_pages,
                                                jnp.int32(p)))
                   for p in pages]
        v_pages = [np.asarray(self._gather_page(self._v_pages,
                                                jnp.int32(p)))
                   for p in pages]
        payload = HandoffPayload(
            rid=req.rid or "", prompt=req.prompt,
            generated=list(req.generated), mnt=req.mnt,
            tenant=req.tenant, cls=req.cls, deadline=req.deadline,
            t_submit=req.t_submit, n_preemptions=req.n_preemptions,
            cur_len=int(req.cur_len), last_tok=int(req.last_tok),
            page_size=dconf.page_size, k_pages=k_pages, v_pages=v_pages,
            src=self.metrics.engine_label, handle=req.handle,
            trace=req.trace, tp_degree=self.tp_degree)
        self._release(req)
        try:
            self._handoff_sink(self, payload)
        except Exception as e:
            ptlog.warning("KV handoff failed (%r); request %s continues "
                          "decoding locally", e, req.rid)
            req.phase = "queued"
            req.seq = None
            req.chunks_done = 0
            req.cur_len = 0
            self._resume.append(req)
            return
        self.metrics.record_handoff_out()
        # with a per-engine WAL, close the rid here — the adopting engine
        # journals it afresh, so a replay of THIS file cannot resurrect a
        # request that now lives elsewhere. With a journal SHARED across
        # the fleet the rid must stay open (the adopter keeps appending
        # under it); the handoff/ack records carry the transfer state.
        if self._journal_owned:
            self._j_fin(req, "migrated")
        runlog.emit("handoff_published", rid=req.rid, pages=n_pages,
                    engine=self.metrics.engine_label)

    def adopt_handoff(self, payload,
                      from_engine: Optional[str] = None) -> DecodeHandle:
        """Adopt a prefilled request handed off by a prefill-role engine
        (:class:`~paddle_tpu.serving.disagg.HandoffPayload`): its KV
        pages are implanted on the loop thread and decode continues from
        ``cur_len`` without re-prefilling. The client's original handle
        is repointed here, mirroring :meth:`adopt_rescue`. Thread-safe;
        returns the (possibly fresh) handle."""
        if self._closed:
            raise EngineClosedError("engine is closed")
        prompt = np.asarray(payload.prompt, np.int32).reshape(-1)
        req = _DecodeRequest(
            prompt, int(payload.mnt),
            self._n_chunks(int(prompt.size) + len(payload.generated)),
            payload.deadline, payload.t_submit or time.monotonic(),
            tenant=payload.tenant, cls=payload.cls)
        req.generated = [int(t) for t in payload.generated]
        req.n_preemptions = payload.n_preemptions
        req.rid = payload.rid or (
            f"{self.metrics.engine_label}-{_RID_SALT}-"
            f"{next(self._rid_seq)}")
        if payload.handle is not None:
            req.handle = payload.handle
            payload.handle._req = req  # cancel() must target the new req
        req.trace = payload.trace
        if req.trace is None and tracing.tracing_enabled():
            req.trace = tracing.SpanContext.new_trace()
        if req.trace is not None:
            req.handle.trace = req.trace
            req.t_enqueue_pc = time.perf_counter()
        # the prefill worker's final-chunk sample may already satisfy the
        # request: complete without decoding (same as adopt_rescue)
        eos = self.decode_config.eos_id
        done_eos = (eos is not None and req.generated
                    and req.generated[-1] == eos)
        if done_eos or len(req.generated) >= req.mnt:
            reason = "eos" if done_eos else "length"
            self._j_admit(req)
            self._j_fin(req, reason)
            req.handle._complete(DecodeOutput(
                tokens=np.asarray(req.generated, dtype=np.int32),
                finish_reason=reason, prompt_len=int(req.prompt.size),
                n_preemptions=req.n_preemptions))
            return req.handle
        self._j_admit(req)
        self.metrics.record_submit()
        if from_engine is not None:
            runlog.emit(
                "request_handed_off", rid=req.rid, from_engine=from_engine,
                to_engine=self.metrics.engine_label,
                generated=len(req.generated),
                trace_id=req.trace.trace_id if req.trace else None)
        self._pending_handoff.append((req, payload))
        self._queue.poke()  # an idle loop is parked in recv(idle_poll_s)
        return req.handle

    def kill(self) -> None:
        """Simulate abrupt engine death (chaos/testing): no drain, no
        journal fin records — exactly the state a crashed process leaves
        behind. In-flight handles fail with :class:`EngineUnhealthy`;
        the journal file still names every incomplete request, which is
        what ``recovery.resume_incomplete()`` rebuilds from."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        # the "crash" happens NOW: nothing more reaches the WAL (in
        # particular no fin records for in-flight requests)
        journal, self._journal = self._journal, None
        self._killed = True
        # post-mortem first, while slots/refcounts still show the crash
        # state the bundle exists to explain
        self._flight_dump("kill")
        self._queue.close()
        self._thread.join(5.0)
        if journal is not None and self._journal_owned:
            journal.close()  # release the fd; on-disk bytes stay as-is
        exc = EngineUnhealthy(
            f"engine {self.metrics.engine_label} killed")
        drained = (list(self._active) + list(self._resume)
                   + list(self._pending_admit)
                   + [item[0] for item in self._pending_handoff])
        self._active.clear()
        self._resume.clear()
        self._pending_admit.clear()
        self._pending_handoff.clear()
        while True:
            try:
                req, ok = self._queue.recv(timeout=0)
            except Exception:
                break
            if not ok:
                break
            drained.append(req)
        self._kv.release_all()
        if self._prefix is not None:
            self._prefix.clear()
        # the host tier is deliberately NOT cleared: a shared pool is the
        # crash-recovery substrate — the restarted engine repopulates its
        # radix tree from it (the recovery ladder's adopt-from-host-tier
        # rung, between "re-prefill locally" and "migrate")
        self._promote_jobs.clear()
        self._promote_keys.clear()
        for req in drained:
            if not req.handle.done():
                req.handle._fail(exc)
        if self._admission is not None:
            admission_mod.uninstall(self._admission)

    # -- shutdown ----------------------------------------------------------

    # grace period for the loop to notice _drain_abort at an iteration
    # boundary once the close() timeout has been overrun
    _DRAIN_ABORT_GRACE_S = 5.0

    def _force_drain(self) -> None:
        """The close() drain deadline passed: complete every in-flight
        request with the tokens it has (``finish_reason="drain_timeout"``)
        instead of leaving its handle hanging forever, then prove no KV
        page leaked."""
        drained = (list(self._active) + list(self._resume)
                   + list(self._pending_admit)
                   + [item[0] for item in self._pending_handoff])
        self._resume.clear()
        self._pending_admit.clear()
        self._pending_handoff.clear()
        while True:
            try:
                req, ok = self._queue.recv(timeout=0)
            except Exception:
                break
            if not ok:
                break
            drained.append(req)
        for req in drained:
            self._finish(req, "drain_timeout")
        if self._prefix is not None:
            self._prefix.clear()
        self._kv.assert_no_leaks()

    def close(self, timeout: Optional[float] = None) -> List[str]:
        """Graceful drain: stop intake, run every accepted request to
        completion, then stop the loop. The drain deadline is ENFORCED:
        when ``timeout`` is overrun, the loop force-finishes stragglers
        with ``finish_reason="drain_timeout"`` (partial tokens returned,
        no handle left waiting forever) and the page-leak check still
        runs. Returns unjoined thread names (empty = clean)."""
        with self._close_lock:
            if self._closed:
                return []
            self._closed = True
        self._queue.close()
        self._thread.join(timeout)
        if timeout is not None and self._thread.is_alive():
            ptlog.error(
                "DecodeEngine.close: drain exceeded %ss; force-finishing "
                "in-flight requests with finish_reason=drain_timeout",
                timeout)
            self._drain_abort = True
            self._thread.join(self._DRAIN_ABORT_GRACE_S)
        unjoined = [self._thread.name] if self._thread.is_alive() else []
        if unjoined:
            ptlog.error("DecodeEngine.close: loop failed to join within %s",
                        timeout)
        if self._journal is not None and self._journal_owned:
            self._journal.close()
        if self._admission is not None:
            admission_mod.uninstall(self._admission)
        return unjoined

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "DecodeEngine":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        self.close()
        return False

"""Multi-tenant admission control: shed doomed or over-quota work at
``submit()``, before it consumes queue capacity or device time.

The reference's serving path (gRPC ``listen_and_serv`` + Fluid inference)
admitted everything and let overload manifest as unbounded send queues and
client-side timeouts. Under real multi-tenant overload the right failure
mode is an *early, typed, attributable* rejection — the caller learns
immediately (and cheaply) that its request will not be served, with a
machine-readable reason it can act on (back off, drop priority, try a
different cell). :class:`AdmissionController` rejects at submit when:

- **quota** — the tenant's queue or byte quota is exhausted
  (``queue_quota`` / ``byte_quota``, enforced atomically by the
  scheduler's :meth:`~paddle_tpu.serving.scheduler.WeightedFairScheduler.
  try_put`);
- **deadline_unmeetable** — the request's deadline cannot be met given the
  tenant's predicted queue wait plus the engine's p90 execute latency,
  both read from the histogram families the engine already collects (GDP's
  idea applied operationally: predict from observed costs instead of
  hard-coding); a request that would expire in the queue is pure waste;
- **brownout** — the watch layer's SLO burn-rate alerting says the engine
  is violating its objectives: batch-class admission sheds first
  (severity ``warning`` → level 1), interactive last (``critical`` →
  level 2). Brownout exits via probing: once the minimum dwell time has
  passed and the SLO probe reports no breach, admission reopens.

Every decision is observable: ``serving.tenant.*`` counters/gauges, runlog
``admission_shed`` / ``brownout_enter`` / ``brownout_exit`` events carrying
the request's trace id, and the exporter's ``/tenants`` endpoint (serving
:meth:`AdmissionController.snapshot` for every :func:`install`-ed
controller, mirroring the ``/slo`` ↔ ``watch.slo.install`` pattern).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from paddle_tpu.core import locks
from paddle_tpu.core import config as cfg
from paddle_tpu.core.enforce import enforce
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import runlog
from paddle_tpu.serving import scheduler as sched_mod

__all__ = [
    "AdmissionRejected",
    "TenantConfig",
    "TokenBucket",
    "AdmissionController",
    "merge_histogram_snapshots",
    "install",
    "uninstall",
    "installed_controllers",
]

# brownout severities → levels: warning sheds batch, critical sheds all
_BROWNOUT_LEVELS = {"warning": 1, "critical": 2}


class AdmissionRejected(RuntimeError):
    """Typed early rejection at ``submit()``. ``reason`` is machine-usable:
    ``queue_quota`` | ``byte_quota`` | ``deadline_unmeetable`` |
    ``brownout`` | ``unknown_tenant``."""

    def __init__(self, reason: str, tenant: str, cls: str, detail: str = ""):
        self.reason = reason
        self.tenant = tenant
        self.cls = cls
        msg = f"admission rejected [{reason}] tenant={tenant} class={cls}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


@dataclasses.dataclass
class TenantConfig:
    """One tenant's scheduling weight, quotas, and default priority class.
    ``None`` fields resolve from the ``PADDLE_TPU_TENANT_*`` flags
    (:meth:`resolved`), so fleet-wide defaults live in the environment and
    per-tenant overrides in code."""

    name: str
    weight: float = 1.0
    # max requests queued for this tenant across both classes
    queue_capacity: Optional[int] = None
    # max queued payload bytes (0 = unlimited)
    byte_quota: Optional[int] = None
    # class used when submit() passes cls=None: "interactive" | "batch"
    default_class: Optional[str] = None

    def resolved(self) -> "TenantConfig":
        f = cfg.flags()
        out = TenantConfig(
            name=self.name,
            weight=self.weight,
            queue_capacity=(self.queue_capacity
                            if self.queue_capacity is not None
                            else f.tenant_queue_capacity),
            byte_quota=(self.byte_quota if self.byte_quota is not None
                        else f.tenant_byte_quota),
            default_class=(self.default_class
                           if self.default_class is not None
                           else f.tenant_default_class),
        )
        enforce(bool(out.name), "TenantConfig needs a name")
        enforce(out.weight > 0,
                f"tenant {out.name!r}: weight must be > 0, got {out.weight}")
        enforce(out.queue_capacity >= 1,
                f"tenant {out.name!r}: queue_capacity must be >= 1")
        enforce(out.byte_quota >= 0,
                f"tenant {out.name!r}: byte_quota must be >= 0")
        enforce(out.default_class in sched_mod.CLASSES,
                f"tenant {out.name!r}: default_class must be one of "
                f"{sched_mod.CLASSES}, got {out.default_class!r}")
        return out


class TokenBucket:
    """Classic token bucket (thread-safe): ``try_take`` never blocks. Used
    as the per-engine retry budget — retries spend tokens that refill at
    ``rate_per_s``, so a retry storm decays to the budget rate instead of
    amplifying overload."""

    def __init__(self, rate_per_s: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        enforce(rate_per_s >= 0,
                f"rate_per_s must be >= 0, got {rate_per_s}")
        enforce(burst > 0, f"burst must be > 0, got {burst}")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = locks.Lock("serving.token_bucket")

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def available(self) -> float:
        with self._lock:
            now = self._clock()
            return min(self.burst,
                       self._tokens + (now - self._last) * self.rate)


def merge_histogram_snapshots(snaps: Sequence[Optional[dict]]) -> Optional[dict]:
    """Elementwise-merge {edges, cumulative, sum, count} snapshots sharing
    one bucket layout (e.g. the per-replica children of
    ``serving.replica_exec_seconds``) into one distribution the quantile
    estimator can read. None/empty snapshots are skipped."""
    merged: Optional[dict] = None
    for snap in snaps:
        if snap is None or snap["count"] <= 0:
            continue
        if merged is None:
            merged = {
                "edges": list(snap["edges"]),
                "cumulative": list(snap["cumulative"]),
                "sum": float(snap["sum"]),
                "count": int(snap["count"]),
            }
            continue
        enforce(merged["edges"] == list(snap["edges"]),
                "cannot merge histograms with different bucket layouts")
        merged["cumulative"] = [
            a + b for a, b in zip(merged["cumulative"], snap["cumulative"])
        ]
        merged["sum"] += float(snap["sum"])
        merged["count"] += int(snap["count"])
    return merged


class AdmissionController:
    """Admission policy over one engine's scheduler (see module docstring).

    ``exec_snapshot`` returns the engine's merged execute-latency histogram
    (``merge_histogram_snapshots`` over per-replica children) — the input
    to deadline-feasibility prediction. ``healthy_replicas`` and
    ``slo_probe`` are callables so the controller holds no engine
    reference; ``slo_probe()`` returns True while any serving SLO is still
    breached (brownout must not exit yet)."""

    def __init__(
        self,
        scheduler: sched_mod.WeightedFairScheduler,
        metrics,
        tenants: Dict[str, TenantConfig],
        *,
        exec_snapshot: Optional[Callable[[], Optional[dict]]] = None,
        healthy_replicas: Callable[[], int] = lambda: 1,
        slo_probe: Optional[Callable[[], bool]] = None,
        request_cost: Optional[Callable[[Any], Optional[float]]] = None,
        brownout_min_s: float = 1.0,
        deadline_quantile: float = 0.9,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.scheduler = scheduler
        self.metrics = metrics
        self.tenants = dict(tenants)
        self._total_weight = sum(t.weight for t in tenants.values())
        self._exec_snapshot = exec_snapshot
        self._healthy_replicas = healthy_replicas
        self._slo_probe = slo_probe
        self._request_cost = request_cost
        self.brownout_min_s = float(brownout_min_s)
        self.deadline_quantile = float(deadline_quantile)
        self._clock = clock
        self._lock = locks.Lock("serving.admission")
        self._brownout_level = 0
        self._brownout_since: Optional[float] = None
        self._brownout_reason = ""

    # -- brownout ----------------------------------------------------------

    @property
    def brownout_level(self) -> int:
        with self._lock:
            return self._brownout_level

    def enter_brownout(self, severity: str, reason: str = "") -> None:
        """Raise the brownout level (never lowers it — a critical alert
        during a warning-level brownout escalates; the probe path is the
        only way down). Level 1 sheds batch admission, level 2 sheds all."""
        level = _BROWNOUT_LEVELS.get(severity, 1)
        with self._lock:
            if level <= self._brownout_level:
                self._brownout_since = self._clock()  # extend the dwell
                return
            self._brownout_level = level
            self._brownout_since = self._clock()
            self._brownout_reason = reason
        self.metrics.set_brownout_level(level)
        runlog.emit("brownout_enter", level=level, severity=severity,
                    reason=reason, engine=self.metrics.engine_label)

    def exit_brownout(self) -> None:
        with self._lock:
            if self._brownout_level == 0:
                return
            level = self._brownout_level
            self._brownout_level = 0
            self._brownout_since = None
            self._brownout_reason = ""
        self.metrics.set_brownout_level(0)
        runlog.emit("brownout_exit", level=level,
                    engine=self.metrics.engine_label)

    def _brownout_check(self) -> int:
        """Current brownout level, probing for exit when the dwell time has
        passed and the SLO probe no longer reports a breach."""
        with self._lock:
            level = self._brownout_level
            since = self._brownout_since
        if level == 0:
            return 0
        if since is not None and self._clock() - since >= self.brownout_min_s:
            breached = True
            if self._slo_probe is not None:
                try:
                    breached = bool(self._slo_probe())
                except Exception:
                    breached = True  # a broken probe must fail shed-ward
            if not breached:
                self.exit_brownout()
                return 0
            with self._lock:
                self._brownout_since = self._clock()  # re-arm the dwell
        return level

    # -- deadline feasibility ----------------------------------------------

    def predicted_latency(self, tenant: str) -> Optional[float]:
        """Predicted queue-wait + p-``deadline_quantile`` execute latency
        for one more request from ``tenant``, from observed costs. None =
        no execute history yet (cold start admits everything: shedding on
        zero data would reject the traffic that builds the model)."""
        if self._exec_snapshot is None:
            return None
        snap = self._exec_snapshot()
        if snap is None or snap["count"] <= 0 or snap["sum"] <= 0:
            return None
        mean_exec = snap["sum"] / snap["count"]
        p_exec = obs_metrics.histogram_quantile(
            snap["edges"], snap["cumulative"], snap["count"],
            self.deadline_quantile)
        replicas = max(1, self._healthy_replicas())
        # batches/s the engine can drain; approximating one queued request
        # per batch is pessimistic exactly when overloaded (requests stop
        # coalescing once queues build), which is the regime that matters
        batch_rate = replicas / max(mean_exec, 1e-9)
        t = self.tenants[tenant]
        share = t.weight / max(self._total_weight, 1e-9)
        queued = self.scheduler.depths()[tenant]
        depth = sum(queued[c] for c in sched_mod.CLASSES)
        wait = depth / max(batch_rate * share, 1e-9)
        return wait + p_exec

    def _predict_for(self, req, tenant: str) -> Optional[float]:
        """Per-request latency prediction. The per-request cost model (when
        wired) wins over the whole-request histogram: for autoregressive
        decode, whole-request latency distributions misprice long
        generations — the decode engine supplies per-token cost ×
        ``max_new_tokens`` instead (see serving.decode.DecodeCostModel).
        A None or failing cost model falls back to the histogram path, and
        both return None when cold (admit everything; shedding on zero data
        would reject the traffic that builds the model)."""
        if self._request_cost is not None:
            try:
                predicted = self._request_cost(req)
            except Exception:
                predicted = None  # a broken cost model must not shed
            if predicted is not None:
                return float(predicted)
        return self.predicted_latency(tenant)

    # -- the decision ------------------------------------------------------

    def admit(self, req) -> None:
        """Admit ``req`` into the scheduler or raise
        :class:`AdmissionRejected`. Order: tenant identity → brownout →
        deadline feasibility → quota (the cheap/global checks first, the
        per-tenant stateful one last so a shed burns no queue state)."""
        tenant, rcls = req.tenant, req.cls
        if tenant not in self.tenants:
            self._shed(req, "unknown_tenant",
                       f"not one of {sorted(self.tenants)}")
        level = self._brownout_check()
        if level >= 2 or (level == 1 and rcls == sched_mod.BATCH):
            self._shed(req, "brownout",
                       f"level={level} reason={self._brownout_reason}")
        if req.deadline is not None:
            predicted = self._predict_for(req, tenant)
            remaining = req.deadline - self._clock()
            if predicted is not None and predicted > remaining:
                self._shed(
                    req, "deadline_unmeetable",
                    f"predicted {predicted:.4f}s > remaining {remaining:.4f}s")
        reason = self.scheduler.try_put(req)
        if reason is not None:
            self._shed(req, reason)
        self.metrics.record_admit(tenant, rcls)

    def _shed(self, req, reason: str, detail: str = "") -> None:
        self.metrics.record_shed(req.tenant, req.cls, reason)
        fields = dict(reason=reason, tenant=req.tenant, cls=req.cls,
                      engine=self.metrics.engine_label)
        if getattr(req, "trace", None) is not None:
            fields["trace_id"] = req.trace.trace_id
        runlog.emit("admission_shed", **fields)
        raise AdmissionRejected(reason, req.tenant, req.cls, detail)

    # -- readout (/tenants) ------------------------------------------------

    def snapshot(self) -> dict:
        depths = self.scheduler.depths()
        with self._lock:
            brownout = {
                "level": self._brownout_level,
                "since": self._brownout_since,
                "reason": self._brownout_reason,
            }
        return {
            "engine": self.metrics.engine_label,
            "brownout": brownout,
            "batch_min_share": self.scheduler.batch_min_share,
            "tenants": {
                name: {
                    "weight": t.weight,
                    "queue_capacity": t.queue_capacity,
                    "byte_quota": t.byte_quota,
                    "default_class": t.default_class,
                    "queued": depths.get(name, {}),
                    "admitted_total": self.metrics.tenant_admitted(name),
                    "shed_total": self.metrics.tenant_shed(name),
                }
                for name, t in self.tenants.items()
            },
        }


# -- process-wide install (what the exporter's /tenants endpoint serves) -----

_installed_lock = locks.Lock("serving.admission_install")
_installed: List[AdmissionController] = []


def install(controller: AdmissionController) -> AdmissionController:
    """Register a controller for the exporter's ``/tenants`` endpoint."""
    with _installed_lock:
        if controller not in _installed:
            _installed.append(controller)
    return controller


def uninstall(controller: AdmissionController) -> None:
    with _installed_lock:
        if controller in _installed:
            _installed.remove(controller)


def installed_controllers() -> List[AdmissionController]:
    with _installed_lock:
        return list(_installed)

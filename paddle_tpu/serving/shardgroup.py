"""paddle_tpu.serving.shardgroup — tensor-parallel replica groups.

The unit of serving dispatch becomes a **replica group**: an ordered tuple
of devices forming a single-axis ``tp`` submesh that runs ONE pjit'd decode
program spanning ICI collectives, instead of one whole-model replica per
device. The reference stack's analogue was ParallelExecutor's per-GPU SSA
graph + NCCL allreduce rings (``multi_devices_graph_pass.cc:286``); here the
group's layout is declarative — a :class:`GroupLayout` rule table maps every
``transformer_lm`` param name to a ``PartitionSpec`` over the group mesh and
XLA/GSPMD materializes the matching collectives inside the jitted step.

Layout (Megatron-style, heads over ``tp``):

- q/k/v projections column-parallel ``P(None, "tp")`` (their biases
  ``P("tp")``), attention out row-parallel ``P("tp", None)``;
- ffn fc1/gate column-parallel, fc2 row-parallel;
- embeddings, logits projection and layernorms replicated (tiny, and the
  test vocab is deliberately not divisible by tp);
- paged KV arrays ``[L, num_pages, H_kv, page_size, dh]`` sharded on the
  head dim ``P(None, None, "tp", None, None)``.

Every per-shard ``PageAllocator`` geometry is identical — page ids are
global and only heads are split — so refcounts, the radix prefix cache,
CoW and trim are unchanged per shard. Any dim whose size doesn't divide
the tp degree degrades to replicated (same contract as
``parallel.sharding.param_shardings``), so one model definition runs at
any tp that divides its head counts and falls back gracefully otherwise.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce
from paddle_tpu.parallel.mesh import TP_AXIS, partition_devices, tp_submesh
from paddle_tpu.parallel.sharding import ShardingRules, degrade_spec, spec_for
from paddle_tpu.resilience import faults

__all__ = [
    "GroupLayout",
    "GroupStragglerWatch",
    "ReplicaGroup",
    "default_layout",
    "make_groups",
    "probe_members",
]

# Head dim of the paged KV arrays [L, num_pages, H_kv, page_size, dh]
KV_HEAD_DIM = 2


@dataclasses.dataclass(frozen=True)
class ReplicaGroup:
    """An ordered device tuple + its ``tp`` submesh: the unit of dispatch.

    Device order is part of the identity — shard i of every param and KV
    page lives on ``devices[i]``, and the straggler watch reports skew by
    that index."""

    devices: Tuple[jax.Device, ...]
    name: str = ""

    def __post_init__(self):
        enforce(len(self.devices) >= 1, "ReplicaGroup needs >= 1 device")
        object.__setattr__(self, "devices", tuple(self.devices))
        if not self.name:
            object.__setattr__(
                self, "name", "group[" + ",".join(str(d.id) for d in self.devices) + "]"
            )
        object.__setattr__(self, "_mesh", tp_submesh(self.devices))

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def tp(self) -> int:
        return len(self.devices)

    def __len__(self) -> int:
        return len(self.devices)


def make_groups(tp: int, devices: Optional[Sequence] = None) -> List[ReplicaGroup]:
    """Slice the device list into ICI-contiguous replica groups of ``tp``."""
    return [
        ReplicaGroup(devs, name=f"group{i}")
        for i, devs in enumerate(partition_devices(tp, devices))
    ]


# Megatron-style rule table for transformer_lm param names. First match
# wins; anything unmatched is replicated (embeddings, logits, layernorms,
# out/fc2 biases — the row-parallel outputs are full-size after the psum).
_TRANSFORMER_LM_RULES: ShardingRules = (
    ("*/self_attn/q/w", P(None, TP_AXIS)),
    ("*/self_attn/k/w", P(None, TP_AXIS)),
    ("*/self_attn/v/w", P(None, TP_AXIS)),
    ("*/self_attn/q/b", P(TP_AXIS)),
    ("*/self_attn/k/b", P(TP_AXIS)),
    ("*/self_attn/v/b", P(TP_AXIS)),
    ("*/self_attn/out/w", P(TP_AXIS, None)),
    ("*/ffn/fc1/w", P(None, TP_AXIS)),
    ("*/ffn/gate/w", P(None, TP_AXIS)),
    ("*/ffn/fc1/b", P(TP_AXIS)),
    ("*/ffn/gate/b", P(TP_AXIS)),
    ("*/ffn/fc2/w", P(TP_AXIS, None)),
)


@dataclasses.dataclass(frozen=True)
class GroupLayout:
    """PartitionSpecs per param class over a replica group's mesh (the
    SpecLayout pattern: named axes + a spec per parameter family, except
    driven by a first-match rule table over param NAMES so the serving
    path needs no model-code cooperation).

    ``optional`` lists rule patterns allowed to match no parameter — the
    swiglu gate projections exist only in that FFN variant, so their
    rules are not dead on a relu model. Any other zero-match rule is a
    ``shard-dead-rule`` finding in ``analysis.shard_analysis`` (stale
    after a param rename, or a layout for the wrong model family).
    ``kv_rule`` overrides the default head-dim KV-page spec; the static
    analyzer checks it against ``PagedKVCache.geometry()`` — page-id and
    page-offset dims must stay global across the group."""

    tp_axis: str = TP_AXIS
    rules: ShardingRules = _TRANSFORMER_LM_RULES
    optional: Tuple[str, ...] = ("*/ffn/gate/w", "*/ffn/gate/b")
    kv_rule: Optional[P] = None

    def param_spec(self, name: str, shape: Tuple[int, ...], mesh: Mesh) -> P:
        spec = spec_for(name, self.rules, ndim=len(shape))
        return degrade_spec(mesh, spec, shape, name=name)

    def param_sharding(
        self, group: ReplicaGroup, name: str, shape: Tuple[int, ...]
    ) -> NamedSharding:
        return NamedSharding(group.mesh, self.param_spec(name, shape, group.mesh))

    def kv_page_spec(self, shape: Tuple[int, ...], mesh: Mesh) -> P:
        """KV pages sharded along heads; degrades to replicated when the
        kv-head count doesn't divide tp (the same model still serves, just
        without the memory win)."""
        if self.kv_rule is not None:
            return degrade_spec(mesh, self.kv_rule, shape, name="kv_pages")
        dims = [None] * len(shape)
        if len(shape) > KV_HEAD_DIM:
            dims[KV_HEAD_DIM] = self.tp_axis
        return degrade_spec(mesh, P(*dims), shape, name="kv_pages")

    def kv_page_sharding(
        self, group: ReplicaGroup, shape: Tuple[int, ...]
    ) -> NamedSharding:
        return NamedSharding(group.mesh, self.kv_page_spec(shape, group.mesh))

    def replicated(self, group: ReplicaGroup) -> NamedSharding:
        return NamedSharding(group.mesh, P())

    def shard_params(
        self, group: ReplicaGroup, params: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        """device_put every param onto the group mesh under its rule —
        the group-mode analogue of ``parallel.sharding.shard_variables``."""
        return {
            name: jax.device_put(
                v, self.param_sharding(group, name, np.shape(v))
            )
            for name, v in params.items()
        }


def default_layout() -> GroupLayout:
    return GroupLayout()


def probe_members(
    group: ReplicaGroup, *, engine_label: Optional[str] = None, nbytes: int = 1 << 12
) -> Dict[int, float]:
    """Per-member liveness/latency canary: time a small host→device
    transfer to EACH member individually (the jitted step is one fused
    program — it cannot attribute a fault or a stall to a single chip;
    this can). The ``GROUP_MEMBER`` fault point fires per shard so chaos
    can fail or stall exactly one member. Raises whatever the injected
    fault raises — the engine treats any member fault as fatal for the
    whole group."""
    payload = np.zeros(nbytes, np.uint8)
    times: Dict[int, float] = {}
    for i, dev in enumerate(group.devices):
        t0 = time.perf_counter()
        faults.inject(
            faults.GROUP_MEMBER, engine=engine_label, shard=i, device=str(dev)
        )
        jax.device_put(payload, dev).block_until_ready()
        times[i] = time.perf_counter() - t0
    return times


class GroupStragglerWatch:
    """Localize the slow chip INSIDE a group from per-shard probe timings.

    Same windowed spatial-median core as
    :class:`~paddle_tpu.watch.detectors.SkewDetector`, with one change a
    tiny group forces: the baseline for shard i is the median of the
    OTHER shards' recent means (leave-one-out). SkewDetector's spatial
    mode medians over ALL keys, which is right for a fleet of replicas
    but breaks at tp=2 — the 2-element median averages the straggler in,
    bounding the ratio below 2.0 so no sane threshold can ever fire.
    ``observe`` returns ``(worst_skew, flagged_shard)``; skew 1.0 means
    perfectly balanced."""

    def __init__(self, group: ReplicaGroup, *, ratio: float = 4.0,
                 window: int = 32, min_samples: int = 5):
        enforce(ratio > 1.0, f"skew ratio must be > 1.0, got {ratio}")
        enforce(min_samples >= 2,
                f"min_samples must be >= 2, got {min_samples}")
        self._group = group
        self.ratio = float(ratio)
        self.min_samples = int(min_samples)
        self._series: Dict[int, deque] = {
            i: deque(maxlen=window) for i in range(len(group.devices))
        }

    def observe(self, shard_times: Dict[int, float]) -> Tuple[float, Optional[int]]:
        for shard, seconds in shard_times.items():
            if shard in self._series and seconds >= 0:
                self._series[shard].append(float(seconds))
        ready = {i: s for i, s in self._series.items()
                 if len(s) >= self.min_samples}
        if len(ready) < 2:
            return 1.0, None
        means = {i: sum(s) / len(s) for i, s in ready.items()}
        flagged: Optional[int] = None
        worst = 1.0
        for shard in sorted(means):
            peers = [m for i, m in means.items() if i != shard]
            baseline = statistics.median(peers)
            if baseline <= 0:
                continue
            skew = means[shard] / baseline
            if skew > worst:
                worst = skew
                if skew > self.ratio:
                    flagged = shard
        return worst, flagged

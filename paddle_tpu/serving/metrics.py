"""Serving observability: request/batch counters, queue-depth gauge, and a
latency reservoir with percentile readout.

Everything mirrors into the framework-wide counter/gauge registry in
``paddle_tpu.core.profiler`` (``serving.*`` names) so one scrape point sees
the whole process; :meth:`ServingMetrics.snapshot` returns the same data as
a plain dict for tests and the bench CLI.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Dict, Optional

from paddle_tpu.core import profiler as prof

__all__ = ["ServingMetrics"]


def _percentile(sorted_values, q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_values:
        return 0.0
    rank = max(1, math.ceil(q / 100.0 * len(sorted_values)))
    return sorted_values[rank - 1]


class ServingMetrics:
    """Thread-safe counters for one engine instance."""

    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.responses_total = 0
        self.timeouts_total = 0
        self.errors_total = 0
        self.batches_total = 0
        self.rows_total = 0          # real rows dispatched (excl. padding)
        self.padded_rows_total = 0   # zero rows added by bucketing
        self.padded_batches_total = 0  # batches where bucket_b > rows
        self.warmup_executables = 0
        self.dispatch_shapes: set = set()  # distinct (sig, bucket_b) sent
        # replica health (circuit breaker / worker-death accounting)
        self.replica_ejections_total = 0   # breaker trips
        self.replica_recoveries_total = 0  # half-open probes that re-admitted
        self.replica_deaths_total = 0      # worker threads that exited
        self.redispatches_total = 0        # failed batches retried elsewhere
        self._latencies = collections.deque(maxlen=latency_window)

    # -- recorders (called from engine/batcher/worker threads) -------------

    def record_submit(self, rows: int, queue_depth: int) -> None:
        with self._lock:
            self.requests_total += 1
        prof.inc_counter("serving.requests_total")
        prof.set_gauge("serving.queue_depth", queue_depth)

    def record_batch(self, rows: int, bucket_rows: int, sig) -> None:
        with self._lock:
            self.batches_total += 1
            self.rows_total += rows
            self.padded_rows_total += bucket_rows - rows
            if bucket_rows > rows:
                self.padded_batches_total += 1
            self.dispatch_shapes.add((sig, bucket_rows))
        prof.inc_counter("serving.batches_total")
        prof.inc_counter("serving.rows_total", rows)
        prof.set_gauge("serving.last_batch_occupancy", rows / bucket_rows)

    def record_response(self, latency_s: float) -> None:
        with self._lock:
            self.responses_total += 1
            self._latencies.append(latency_s)
        prof.inc_counter("serving.responses_total")

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts_total += 1
        prof.inc_counter("serving.timeouts_total")

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors_total += n
        prof.inc_counter("serving.errors_total", n)

    def record_warmup(self, n: int = 1) -> None:
        with self._lock:
            self.warmup_executables += n
        prof.inc_counter("serving.warmup_executables", n)

    def set_queue_depth(self, depth: int) -> None:
        prof.set_gauge("serving.queue_depth", depth)

    def record_replica_ejection(self) -> None:
        with self._lock:
            self.replica_ejections_total += 1
        prof.inc_counter("serving.replica_ejections_total")

    def record_replica_recovery(self) -> None:
        with self._lock:
            self.replica_recoveries_total += 1
        prof.inc_counter("serving.replica_recoveries_total")

    def record_replica_death(self) -> None:
        with self._lock:
            self.replica_deaths_total += 1
        prof.inc_counter("serving.replica_deaths_total")

    def record_redispatch(self) -> None:
        with self._lock:
            self.redispatches_total += 1
        prof.inc_counter("serving.redispatches_total")

    def set_healthy_replicas(self, n: int) -> None:
        prof.set_gauge("serving.healthy_replicas", n)

    # -- readout -----------------------------------------------------------

    def mean_batch_occupancy(self) -> float:
        """Mean real rows per dispatched batch — > 1 means the dynamic
        batcher is actually coalescing traffic."""
        with self._lock:
            if self.batches_total == 0:
                return 0.0
            return self.rows_total / self.batches_total

    def latency_percentiles(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies)
        return {
            "p50_ms": _percentile(vals, 50) * 1e3,
            "p99_ms": _percentile(vals, 99) * 1e3,
        }

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies)
            snap = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "timeouts_total": self.timeouts_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "rows_total": self.rows_total,
                "padded_rows_total": self.padded_rows_total,
                "padded_batches_total": self.padded_batches_total,
                "warmup_executables": self.warmup_executables,
                "distinct_dispatch_shapes": len(self.dispatch_shapes),
                "replica_ejections_total": self.replica_ejections_total,
                "replica_recoveries_total": self.replica_recoveries_total,
                "replica_deaths_total": self.replica_deaths_total,
                "redispatches_total": self.redispatches_total,
                "mean_batch_occupancy": (
                    self.rows_total / self.batches_total
                    if self.batches_total
                    else 0.0
                ),
            }
        snap["p50_ms"] = _percentile(vals, 50) * 1e3
        snap["p99_ms"] = _percentile(vals, 99) * 1e3
        return snap

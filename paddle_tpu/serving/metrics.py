"""Serving observability: request/batch counters, queue-depth gauge, and a
latency reservoir with percentile readout.

Everything mirrors into the framework-wide registry
(``paddle_tpu.observability.metrics`` via ``core.profiler``) under
``serving.*`` names so one scrape point sees the whole process. Each
engine gets an ``engine`` label (default ``serving0``, ``serving1``, ...)
— two engines in one process no longer collide on the same families, and
``prof.counters()`` still shows the per-name aggregate across engines.
The latency reservoir additionally mirrors into the
``serving.request_latency_seconds`` histogram family, so the Prometheus
scrape carries full latency distributions, not just p50/p99 points.
:meth:`ServingMetrics.snapshot` returns the same data as a plain dict for
tests and the bench CLI.
"""

from __future__ import annotations

import collections
import itertools
import math
import threading
from typing import Dict, Optional

from paddle_tpu.core import locks
from paddle_tpu.core import profiler as prof
from paddle_tpu.observability import metrics as obs_metrics

__all__ = ["ServingMetrics", "DecodeMetrics"]

# distinct default engine labels for every engine built in this process
_ENGINE_SEQ = itertools.count()

# sub-millisecond to 10s — serving latencies, finer than the generic default
_LATENCY_BUCKETS = obs_metrics.exponential_buckets(0.0005, 2.0, 15)


def _percentile(sorted_values, q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_values:
        return 0.0
    rank = max(1, math.ceil(q / 100.0 * len(sorted_values)))
    return sorted_values[rank - 1]


class ServingMetrics:
    """Thread-safe counters for one engine instance."""

    def __init__(self, latency_window: int = 8192,
                 engine_label: Optional[str] = None):
        self._lock = locks.Lock("serving.metrics")
        self.engine_label = engine_label or f"serving{next(_ENGINE_SEQ)}"
        self._labels = {"engine": self.engine_label}
        obs_metrics.default_registry().histogram(
            "serving.request_latency_seconds",
            help="End-to-end request latency (submit to response).",
            buckets=_LATENCY_BUCKETS)
        obs_metrics.default_registry().histogram(
            "serving.batch_occupancy",
            help="Real rows / bucket rows per dispatched batch.",
            buckets=obs_metrics.linear_buckets(0.1, 0.1, 10))
        obs_metrics.default_registry().histogram(
            "serving.replica_exec_seconds",
            help="Per-replica device execute duration per batch.",
            buckets=_LATENCY_BUCKETS)
        obs_metrics.default_registry().histogram(
            "serving.tenant.request_latency_seconds",
            help="End-to-end request latency per tenant and priority class.",
            buckets=_LATENCY_BUCKETS)
        self.requests_total = 0
        self.responses_total = 0
        self.timeouts_total = 0
        self.errors_total = 0
        self.batches_total = 0
        self.rows_total = 0          # real rows dispatched (excl. padding)
        self.padded_rows_total = 0   # zero rows added by bucketing
        self.padded_batches_total = 0  # batches where bucket_b > rows
        self.warmup_executables = 0
        self.dispatch_shapes: set = set()  # distinct (sig, bucket_b) sent
        # replica health (circuit breaker / worker-death accounting)
        self.replica_ejections_total = 0   # breaker trips
        self.replica_recoveries_total = 0  # half-open probes that re-admitted
        self.replica_deaths_total = 0      # worker threads that exited
        self.redispatches_total = 0        # failed batches retried elsewhere
        # multi-tenant admission accounting (serving.tenant.* families)
        self._tenant_admitted: collections.Counter = collections.Counter()
        self._tenant_shed: collections.Counter = collections.Counter()
        self.retries_total = 0                  # submit() retry attempts
        self.retry_budget_exhausted_total = 0   # retries refused by budget
        self._latencies = collections.deque(maxlen=latency_window)

    # -- recorders (called from engine/batcher/worker threads) -------------

    def record_submit(self, rows: int, queue_depth: int) -> None:
        with self._lock:
            self.requests_total += 1
        prof.inc_counter("serving.requests_total", labels=self._labels)
        prof.set_gauge("serving.queue_depth", queue_depth, labels=self._labels)

    def record_batch(self, rows: int, bucket_rows: int, sig) -> None:
        with self._lock:
            self.batches_total += 1
            self.rows_total += rows
            self.padded_rows_total += bucket_rows - rows
            if bucket_rows > rows:
                self.padded_batches_total += 1
            self.dispatch_shapes.add((sig, bucket_rows))
        prof.inc_counter("serving.batches_total", labels=self._labels)
        prof.inc_counter("serving.rows_total", rows, labels=self._labels)
        prof.set_gauge("serving.last_batch_occupancy", rows / bucket_rows,
                       labels=self._labels)
        prof.observe("serving.batch_occupancy", rows / bucket_rows,
                     labels=self._labels)

    def record_response(self, latency_s: float) -> None:
        with self._lock:
            self.responses_total += 1
            self._latencies.append(latency_s)
        prof.inc_counter("serving.responses_total", labels=self._labels)
        prof.observe("serving.request_latency_seconds", latency_s,
                     labels=self._labels)

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts_total += 1
        prof.inc_counter("serving.timeouts_total", labels=self._labels)

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors_total += n
        prof.inc_counter("serving.errors_total", n, labels=self._labels)

    def record_warmup(self, n: int = 1) -> None:
        with self._lock:
            self.warmup_executables += n
        prof.inc_counter("serving.warmup_executables", n, labels=self._labels)

    def set_queue_depth(self, depth: int) -> None:
        prof.set_gauge("serving.queue_depth", depth, labels=self._labels)

    def record_exec(self, replica: int, seconds: float) -> None:
        """Per-replica device execute duration — the series the watch
        layer's per-replica latency anomaly rule subscribes to."""
        prof.observe("serving.replica_exec_seconds", seconds,
                     labels={**self._labels, "replica": str(replica)})

    def record_replica_ejection(self) -> None:
        with self._lock:
            self.replica_ejections_total += 1
        prof.inc_counter("serving.replica_ejections_total", labels=self._labels)

    def record_replica_recovery(self) -> None:
        with self._lock:
            self.replica_recoveries_total += 1
        prof.inc_counter("serving.replica_recoveries_total", labels=self._labels)

    def record_replica_death(self) -> None:
        with self._lock:
            self.replica_deaths_total += 1
        prof.inc_counter("serving.replica_deaths_total", labels=self._labels)

    def record_redispatch(self) -> None:
        with self._lock:
            self.redispatches_total += 1
        prof.inc_counter("serving.redispatches_total", labels=self._labels)

    def set_healthy_replicas(self, n: int) -> None:
        prof.set_gauge("serving.healthy_replicas", n, labels=self._labels)

    # -- multi-tenant admission (serving.tenant.* families) -----------------

    def record_admit(self, tenant: str, cls: str) -> None:
        with self._lock:
            self._tenant_admitted[(tenant, cls)] += 1
        prof.inc_counter("serving.tenant.admitted_total",
                         labels={**self._labels, "tenant": tenant,
                                 "cls": cls})

    def record_shed(self, tenant: str, cls: str, reason: str) -> None:
        with self._lock:
            self._tenant_shed[(tenant, cls, reason)] += 1
        prof.inc_counter("serving.tenant.shed_total",
                         labels={**self._labels, "tenant": tenant,
                                 "cls": cls, "reason": reason})

    def record_tenant_response(self, tenant: str, cls: str,
                               latency_s: float) -> None:
        prof.observe("serving.tenant.request_latency_seconds", latency_s,
                     labels={**self._labels, "tenant": tenant, "cls": cls})

    def set_tenant_depths(self, depths: Dict[str, dict]) -> None:
        """Refresh the per-tenant queue gauges from a scheduler
        :meth:`~paddle_tpu.serving.scheduler.WeightedFairScheduler.depths`
        snapshot."""
        for tenant, d in depths.items():
            for cls, depth in d.items():
                if cls == "bytes":
                    prof.set_gauge(
                        "serving.tenant.queued_bytes", depth,
                        labels={**self._labels, "tenant": tenant})
                else:
                    prof.set_gauge(
                        "serving.tenant.queue_depth", depth,
                        labels={**self._labels, "tenant": tenant,
                                "cls": cls})

    def set_brownout_level(self, level: int) -> None:
        prof.set_gauge("serving.brownout_level", level, labels=self._labels)

    def record_retry(self) -> None:
        with self._lock:
            self.retries_total += 1
        prof.inc_counter("serving.retries_total", labels=self._labels)

    def record_retry_budget_exhausted(self) -> None:
        with self._lock:
            self.retry_budget_exhausted_total += 1
        prof.inc_counter("serving.retry_budget_exhausted",
                         labels=self._labels)

    def tenant_admitted(self, tenant: str) -> int:
        with self._lock:
            return sum(v for (t, _), v in self._tenant_admitted.items()
                       if t == tenant)

    def tenant_shed(self, tenant: str) -> Dict[str, int]:
        """Shed counts for one tenant, keyed by rejection reason."""
        out: Dict[str, int] = {}
        with self._lock:
            for (t, _, reason), v in self._tenant_shed.items():
                if t == tenant:
                    out[reason] = out.get(reason, 0) + v
        return out

    def shed_total(self) -> int:
        with self._lock:
            return sum(self._tenant_shed.values())

    # -- readout -----------------------------------------------------------

    def mean_batch_occupancy(self) -> float:
        """Mean real rows per dispatched batch — > 1 means the dynamic
        batcher is actually coalescing traffic."""
        with self._lock:
            if self.batches_total == 0:
                return 0.0
            return self.rows_total / self.batches_total

    def latency_percentiles(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies)
        return {
            "p50_ms": _percentile(vals, 50) * 1e3,
            "p99_ms": _percentile(vals, 99) * 1e3,
        }

    def latency_quantile(self, q: float) -> Optional[float]:
        """Estimated request-latency ``q``-quantile in SECONDS from the
        ``serving.request_latency_seconds`` histogram (linear interpolation
        within buckets — the same estimator the SLO engine uses). Unlike
        the bounded reservoir behind :meth:`latency_percentiles`, this
        covers every response since engine start. None before any
        response."""
        return obs_metrics.default_registry().quantile(
            "serving.request_latency_seconds", q, labels=self._labels)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies)
            snap = {
                "engine": self.engine_label,
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "timeouts_total": self.timeouts_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "rows_total": self.rows_total,
                "padded_rows_total": self.padded_rows_total,
                "padded_batches_total": self.padded_batches_total,
                "warmup_executables": self.warmup_executables,
                "distinct_dispatch_shapes": len(self.dispatch_shapes),
                "replica_ejections_total": self.replica_ejections_total,
                "replica_recoveries_total": self.replica_recoveries_total,
                "replica_deaths_total": self.replica_deaths_total,
                "redispatches_total": self.redispatches_total,
                "admitted_total": sum(self._tenant_admitted.values()),
                "shed_total": sum(self._tenant_shed.values()),
                "retries_total": self.retries_total,
                "retry_budget_exhausted_total":
                    self.retry_budget_exhausted_total,
                "mean_batch_occupancy": (
                    self.rows_total / self.batches_total
                    if self.batches_total
                    else 0.0
                ),
            }
        snap["p50_ms"] = _percentile(vals, 50) * 1e3
        snap["p99_ms"] = _percentile(vals, 99) * 1e3
        return snap


class DecodeMetrics:
    """Counters/gauges for one continuous-batching decode engine
    (``serving.decode.DecodeEngine``) under ``serving.decode.*`` families.
    Same registry/labeling idiom as :class:`ServingMetrics`: each engine
    gets an ``engine`` label, histograms register up front, ``snapshot``
    returns a plain dict for tests and the bench CLI."""

    def __init__(self, engine_label: Optional[str] = None):
        self._lock = locks.Lock("serving.decode_metrics")
        self.engine_label = engine_label or f"decode{next(_ENGINE_SEQ)}"
        self._labels = {"engine": self.engine_label}
        reg = obs_metrics.default_registry()
        reg.histogram(
            "serving.decode.step_seconds",
            help="Wall time of one jitted decode iteration (all slots).",
            buckets=_LATENCY_BUCKETS)
        reg.histogram(
            "serving.decode.prefill_chunk_seconds",
            help="Wall time of one prefill chunk.",
            buckets=_LATENCY_BUCKETS)
        reg.histogram(
            "serving.decode.batch_occupancy",
            help="Active slots / max slots per decode iteration.",
            buckets=obs_metrics.linear_buckets(0.1, 0.1, 10))
        reg.histogram(
            "serving.decode.request_latency_seconds",
            help="End-to-end decode request latency (submit to last token).",
            buckets=_LATENCY_BUCKETS)
        reg.histogram(
            "serving.host_tier.promote_seconds",
            help="Wall time to promote one host-tier page into the radix "
                 "tree (CRC verify + device implant + insert).",
            buckets=_LATENCY_BUCKETS)
        reg.histogram(
            "serving.decode.ttft_seconds",
            help="Submit to first generated token per request (queue wait "
                 "+ prefill), by request class.",
            buckets=_LATENCY_BUCKETS)
        reg.histogram(
            "serving.decode.tpot_seconds",
            help="Per-token latency after the first, by request class. "
                 "Speculation-aware: a verify step landing n tokens books "
                 "n samples, so spec on/off distributions are comparable.",
            buckets=obs_metrics.exponential_buckets(0.0001, 2.0, 15))
        self.requests_total = 0
        self.responses_total = 0
        self.tokens_total = 0          # generated tokens across all requests
        self.prefill_chunks_total = 0
        self.steps_total = 0           # decode iterations run
        self.admitted_total = 0        # requests that got a slot
        self.evicted_total = 0         # finished/cancelled slots released
        self.preempted_total = 0       # evicted on page exhaustion, resumable
        self.resumed_total = 0         # preempted requests re-admitted
        self.cancelled_total = 0
        self.timeouts_total = 0
        self.errors_total = 0
        # zero-loss recovery accounting (serving.recovery.* families)
        self.step_faults_total = 0       # poisoned decode/prefill iterations
        self.recovered_total = 0         # requests re-admitted after a fault
        self.migrated_total = 0          # requests drained to another engine
        self.retries_exhausted_total = 0  # requests past their retry budget
        self.journal_records_total = 0   # WAL records appended
        self.journal_replayed_total = 0  # requests resumed from the journal
        # speculative decoding (serving.decode.spec_* families)
        self.verify_steps_total = 0       # draft-and-verify iterations run
        self.spec_tokens_total = 0        # tokens appended by verify steps
        self.spec_drafts_proposed_total = 0  # draft tokens scored
        self.spec_drafts_accepted_total = 0  # draft tokens accepted
        # prefix cache (serving.decode.prefix_* / cow_* families)
        self.prompt_tokens_total = 0      # prompt tokens across admissions
        self.prefix_hit_tokens_total = 0  # prompt tokens served from cache
        self.prefix_saved_chunks_total = 0  # prefill chunks skipped outright
        self.cow_copies_total = 0         # copy-on-write page copies
        # hierarchical KV host tier (serving.host_tier.* families)
        self.host_tier_hits_total = 0     # admissions whose continuation
        #                                   the host tier held (promote queued)
        self.host_promoted_pages_total = 0  # pages implanted tree-ward
        self.host_demoted_pages_total = 0   # pages written through to host
        self.host_quarantined_total = 0     # CRC-failed host pages dropped
        self.host_backpressure_total = 0    # demotes that forced LRU eviction
        # disaggregated prefill/decode (serving.disagg.* families)
        self.handoffs_out_total = 0       # prefilled requests published
        self.handoffs_in_total = 0        # handed-off requests adopted
        # tp replica groups (serving.group.* families)
        self.group_member_faults_total = 0  # member canary faults (ejections)
        self.shard_stragglers_total = 0     # probes that flagged a slow shard
        # tenant-quota admission accounting (serving.tenant.* families)
        self._tenant_admitted: collections.Counter = collections.Counter()
        self._tenant_shed: collections.Counter = collections.Counter()
        # token-latency waterfall rollup (serving.decode.ttft/tpot families)
        self.ttft_observed_total = 0
        self.tpot_samples_total = 0

    def record_submit(self) -> None:
        with self._lock:
            self.requests_total += 1
        prof.inc_counter("serving.decode.requests_total", labels=self._labels)

    def record_slot_admit(self) -> None:
        """A request got a decode slot (iteration-level admission; distinct
        from :meth:`record_admit`, the tenant-quota admission below)."""
        with self._lock:
            self.admitted_total += 1
        prof.inc_counter("serving.decode.admitted_total", labels=self._labels)

    # -- multi-tenant admission interface (the AdmissionController talks to
    # whichever engine's metrics object it was built with; same contract as
    # ServingMetrics' serving.tenant.* family) ------------------------------

    def record_admit(self, tenant: str, cls: str) -> None:
        with self._lock:
            self._tenant_admitted[(tenant, cls)] += 1
        prof.inc_counter("serving.tenant.admitted_total",
                         labels={**self._labels, "tenant": tenant,
                                 "cls": cls})

    def record_shed(self, tenant: str, cls: str, reason: str) -> None:
        with self._lock:
            self._tenant_shed[(tenant, cls, reason)] += 1
        prof.inc_counter("serving.tenant.shed_total",
                         labels={**self._labels, "tenant": tenant,
                                 "cls": cls, "reason": reason})

    def record_tenant_response(self, tenant: str, cls: str,
                               latency_s: float) -> None:
        prof.observe("serving.tenant.request_latency_seconds", latency_s,
                     labels={**self._labels, "tenant": tenant, "cls": cls})

    def set_tenant_depths(self, depths: Dict[str, dict]) -> None:
        for tenant, d in depths.items():
            for cls, depth in d.items():
                if cls == "bytes":
                    prof.set_gauge(
                        "serving.tenant.queued_bytes", depth,
                        labels={**self._labels, "tenant": tenant})
                else:
                    prof.set_gauge(
                        "serving.tenant.queue_depth", depth,
                        labels={**self._labels, "tenant": tenant,
                                "cls": cls})

    def set_brownout_level(self, level: int) -> None:
        prof.set_gauge("serving.brownout_level", level, labels=self._labels)

    def tenant_admitted(self, tenant: str) -> int:
        with self._lock:
            return sum(v for (t, _), v in self._tenant_admitted.items()
                       if t == tenant)

    def tenant_shed(self, tenant: str) -> Dict[str, int]:
        out: Dict[str, int] = {}
        with self._lock:
            for (t, _, reason), v in self._tenant_shed.items():
                if t == tenant:
                    out[reason] = out.get(reason, 0) + v
        return out

    def shed_total(self) -> int:
        with self._lock:
            return sum(self._tenant_shed.values())

    def record_evict(self, reason: str) -> None:
        with self._lock:
            self.evicted_total += 1
        prof.inc_counter("serving.decode.evicted_total",
                         labels={**self._labels, "reason": reason})

    def record_preempt(self) -> None:
        with self._lock:
            self.preempted_total += 1
        prof.inc_counter("serving.decode.preempted_total",
                         labels=self._labels)

    def record_resume(self) -> None:
        with self._lock:
            self.resumed_total += 1
        prof.inc_counter("serving.decode.resumed_total", labels=self._labels)

    def record_cancel(self) -> None:
        with self._lock:
            self.cancelled_total += 1
        prof.inc_counter("serving.decode.cancelled_total",
                         labels=self._labels)

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts_total += 1
        prof.inc_counter("serving.decode.timeouts_total", labels=self._labels)

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors_total += n
        prof.inc_counter("serving.decode.errors_total", n,
                         labels=self._labels)

    def record_step(self, active: int, max_slots: int,
                    seconds: float, new_tokens: int) -> None:
        with self._lock:
            self.steps_total += 1
            self.tokens_total += new_tokens
        prof.inc_counter("serving.decode.steps_total", labels=self._labels)
        prof.inc_counter("serving.decode.tokens_total", new_tokens,
                         labels=self._labels)
        prof.observe("serving.decode.step_seconds", seconds,
                     labels=self._labels)
        prof.observe("serving.decode.batch_occupancy",
                     active / max(max_slots, 1), labels=self._labels)

    def record_prefill_chunk(self, seconds: float) -> None:
        with self._lock:
            self.prefill_chunks_total += 1
        prof.inc_counter("serving.decode.prefill_chunks_total",
                         labels=self._labels)
        prof.observe("serving.decode.prefill_chunk_seconds", seconds,
                     labels=self._labels)

    def record_response(self, latency_s: float) -> None:
        with self._lock:
            self.responses_total += 1
        prof.inc_counter("serving.decode.responses_total",
                         labels=self._labels)
        prof.observe("serving.decode.request_latency_seconds", latency_s,
                     labels=self._labels)

    # -- token-latency waterfall rollup (ttft/tpot families) -----------------

    def record_ttft(self, seconds: float, cls: str = "default") -> None:
        """One request's time-to-first-token (booked by the waterfall on
        the iteration that produced the first generated token)."""
        with self._lock:
            self.ttft_observed_total += 1
        prof.observe("serving.decode.ttft_seconds", seconds,
                     labels={**self._labels, "cls": cls or "default"})

    def record_tpot(self, samples, cls: str = "default") -> None:
        """Book per-token latency samples — one per generated token after
        the first; a multi-token verify iteration passes several equal
        samples (see tracing/waterfall.py)."""
        if not samples:
            return
        with self._lock:
            self.tpot_samples_total += len(samples)
        labels = {**self._labels, "cls": cls or "default"}
        for s in samples:
            prof.observe("serving.decode.tpot_seconds", s, labels=labels)

    # -- speculative decoding (serving.decode.spec_* families) ---------------

    def record_verify_step(self, active: int, max_slots: int, seconds: float,
                           new_tokens: int, drafts_proposed: int,
                           drafts_accepted: int) -> None:
        """One draft-and-verify iteration: counts like a decode step (it
        advances every participating slot at least one token) plus the
        speculation ledger. ``serving.decode.spec_accept_rate`` is the
        cumulative accepted/proposed draft-token ratio — the series the
        watch layer's acceptance-collapse rule subscribes to."""
        self.record_step(active, max_slots, seconds, new_tokens)
        with self._lock:
            self.verify_steps_total += 1
            self.spec_tokens_total += new_tokens
            self.spec_drafts_proposed_total += drafts_proposed
            self.spec_drafts_accepted_total += drafts_accepted
            proposed = self.spec_drafts_proposed_total
            rate = (self.spec_drafts_accepted_total / proposed
                    if proposed else 0.0)
        prof.inc_counter("serving.decode.verify_steps_total",
                         labels=self._labels)
        prof.inc_counter("serving.decode.spec_tokens_total", new_tokens,
                         labels=self._labels)
        prof.set_gauge("serving.decode.spec_accept_rate", rate,
                       labels=self._labels)

    def spec_accept_rate(self) -> float:
        with self._lock:
            if not self.spec_drafts_proposed_total:
                return 0.0
            return (self.spec_drafts_accepted_total
                    / self.spec_drafts_proposed_total)

    def accepted_tokens_per_verify_step(self) -> float:
        with self._lock:
            if not self.verify_steps_total:
                return 0.0
            return self.spec_tokens_total / self.verify_steps_total

    # -- prefix cache (serving.decode.prefix_* families) ---------------------

    def record_prompt_tokens(self, n: int) -> None:
        with self._lock:
            self.prompt_tokens_total += n
        prof.inc_counter("serving.decode.prompt_tokens_total", n,
                         labels=self._labels)

    def record_prefix_hit(self, hit_tokens: int, saved_chunks: int) -> None:
        with self._lock:
            self.prefix_hit_tokens_total += hit_tokens
            self.prefix_saved_chunks_total += saved_chunks
        prof.inc_counter("serving.decode.prefix_hit_tokens_total", hit_tokens,
                         labels=self._labels)

    def record_cow(self, n: int = 1) -> None:
        with self._lock:
            self.cow_copies_total += n
        prof.inc_counter("serving.decode.cow_copies_total", n,
                         labels=self._labels)

    def prefix_saved_frac(self) -> float:
        """Fraction of admitted prompt tokens whose prefill was served from
        the prefix cache — the bench's ``prefix_prefill_tokens_saved_frac``."""
        with self._lock:
            if not self.prompt_tokens_total:
                return 0.0
            return self.prefix_hit_tokens_total / self.prompt_tokens_total

    # -- hierarchical KV host tier (serving.host_tier.* families) ------------

    def record_host_hit(self) -> None:
        """An admission's radix miss had its continuation resident in the
        host tier — a promote job was enqueued (the request itself
        prefills as usual; the NEXT hit lands in HBM)."""
        with self._lock:
            self.host_tier_hits_total += 1
        prof.inc_counter("serving.host_tier.hits_total", labels=self._labels)

    def record_host_promote(self, seconds: float) -> None:
        """One host page promoted into the radix tree (CRC verify +
        device implant + tree insert), timed for the p99-neutrality
        gate: promotion is budgeted per loop iteration, so this
        histogram bounds what it can cost a decode step."""
        with self._lock:
            self.host_promoted_pages_total += 1
        prof.inc_counter("serving.host_tier.promoted_pages_total",
                         labels=self._labels)
        prof.observe("serving.host_tier.promote_seconds", seconds,
                     labels=self._labels)

    def record_host_demote(self, pages: int) -> None:
        with self._lock:
            self.host_demoted_pages_total += pages
        prof.inc_counter("serving.host_tier.demoted_pages_total", pages,
                         labels=self._labels)

    def record_host_quarantine(self, n: int = 1) -> None:
        """A host page failed CRC verification at promote time and was
        quarantined — the request re-prefills token-exactly instead."""
        with self._lock:
            self.host_quarantined_total += n
        prof.inc_counter("serving.host_tier.quarantined_total", n,
                         labels=self._labels)

    def record_host_backpressure(self, n: int = 1) -> None:
        """A demote pushed the pool past its byte budget and forced LRU
        eviction. The gauge mirror is what the watch layer's
        demote-backpressure rule subscribes to: a sustained climb means
        the fleet's warm working set outgrew host RAM."""
        with self._lock:
            self.host_backpressure_total += n
            total = self.host_backpressure_total
        prof.inc_counter("serving.host_tier.backpressure_total", n,
                         labels=self._labels)
        prof.set_gauge("serving.host_tier.demote_backpressure", total,
                       labels=self._labels)

    def set_host_tier_bytes(self, used: int, budget: int) -> None:
        prof.set_gauge("serving.host_tier.bytes_used", used,
                       labels=self._labels)
        prof.set_gauge("serving.host_tier.bytes_budget", budget,
                       labels=self._labels)

    # -- disaggregated prefill/decode (serving.disagg.* families) ------------

    def record_handoff_out(self) -> None:
        """This engine finished a prefill and published the request's KV
        pages to the router's handoff sink (prefill-worker role)."""
        with self._lock:
            self.handoffs_out_total += 1
        prof.inc_counter("serving.disagg.handoffs_out_total",
                         labels=self._labels)

    def record_handoff_in(self) -> None:
        """This engine adopted a handed-off request's KV pages straight
        into its decode loop (decode-worker role)."""
        with self._lock:
            self.handoffs_in_total += 1
        prof.inc_counter("serving.disagg.handoffs_in_total",
                         labels=self._labels)

    # -- tp replica groups (serving.group.* families) ------------------------

    def record_member_fault(self) -> None:
        """A per-member canary probe raised — the whole group is being
        ejected (breaker trip + migration); counted once per probe pass."""
        with self._lock:
            self.group_member_faults_total += 1
        prof.inc_counter("serving.group.member_faults_total",
                         labels=self._labels)

    def record_shard_straggler(self) -> None:
        """The straggler watch localized a slow chip inside the group."""
        with self._lock:
            self.shard_stragglers_total += 1
        prof.inc_counter("serving.group.shard_stragglers_total",
                         labels=self._labels)

    def set_shard_skew(self, skew: float) -> None:
        """Worst shard's recent probe-time mean over the median shard mean
        (1.0 = perfectly balanced) — the watch layer's localization signal."""
        prof.set_gauge("serving.group.shard_skew", skew, labels=self._labels)

    def set_shard_probe_seconds(self, shard: int, seconds: float) -> None:
        prof.set_gauge("serving.group.shard_probe_seconds", seconds,
                       labels={**self._labels, "shard": str(shard)})

    def set_load(self, load: float) -> None:
        """Live routing-load signal (active slots + queued/parked work) —
        what :meth:`DecodeFleet._pick` ranks engines by; refreshed every
        loop iteration and at submit time."""
        prof.set_gauge("serving.decode.load", load, labels=self._labels)

    def set_queue_depth(self, depth: int) -> None:
        prof.set_gauge("serving.decode.queue_depth", depth,
                       labels=self._labels)

    # -- zero-loss recovery (serving.recovery.* families) --------------------

    def record_step_fault(self) -> None:
        with self._lock:
            self.step_faults_total += 1
        prof.inc_counter("serving.recovery.step_faults_total",
                         labels=self._labels)

    def record_recover(self, n: int = 1) -> None:
        with self._lock:
            self.recovered_total += n
        prof.inc_counter("serving.recovery.recovered_total", n,
                         labels=self._labels)

    def record_migrate(self, n: int = 1) -> None:
        with self._lock:
            self.migrated_total += n
        prof.inc_counter("serving.recovery.migrated_total", n,
                         labels=self._labels)

    def record_retries_exhausted(self) -> None:
        with self._lock:
            self.retries_exhausted_total += 1
        prof.inc_counter("serving.recovery.retries_exhausted_total",
                         labels=self._labels)

    def record_journal_records(self, n: int = 1) -> None:
        with self._lock:
            self.journal_records_total += n
        prof.inc_counter("serving.recovery.journal_records_total", n,
                         labels=self._labels)

    def record_journal_replayed(self, n: int = 1) -> None:
        with self._lock:
            self.journal_replayed_total += n
        prof.inc_counter("serving.recovery.journal_replayed_total", n,
                         labels=self._labels)

    def set_consecutive_faults(self, n: int) -> None:
        """Consecutive faulted iterations on this engine — the series the
        watch layer's unhealthy-engine rule subscribes to; resets to 0 on
        every clean iteration."""
        prof.set_gauge("serving.recovery.consecutive_faults", n,
                       labels=self._labels)

    def set_pages(self, in_use: int, free: int) -> None:
        prof.set_gauge("serving.decode.pages_in_use", in_use,
                       labels=self._labels)
        prof.set_gauge("serving.decode.pages_free", free, labels=self._labels)

    def set_active_slots(self, n: int) -> None:
        prof.set_gauge("serving.decode.active_slots", n, labels=self._labels)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "engine": self.engine_label,
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "tokens_total": self.tokens_total,
                "prefill_chunks_total": self.prefill_chunks_total,
                "steps_total": self.steps_total,
                "admitted_total": self.admitted_total,
                "evicted_total": self.evicted_total,
                "preempted_total": self.preempted_total,
                "resumed_total": self.resumed_total,
                "cancelled_total": self.cancelled_total,
                "timeouts_total": self.timeouts_total,
                "errors_total": self.errors_total,
                "step_faults_total": self.step_faults_total,
                "recovered_total": self.recovered_total,
                "migrated_total": self.migrated_total,
                "retries_exhausted_total": self.retries_exhausted_total,
                "journal_records_total": self.journal_records_total,
                "journal_replayed_total": self.journal_replayed_total,
                "verify_steps_total": self.verify_steps_total,
                "spec_tokens_total": self.spec_tokens_total,
                "spec_drafts_proposed_total": self.spec_drafts_proposed_total,
                "spec_drafts_accepted_total": self.spec_drafts_accepted_total,
                "spec_accept_rate": (
                    self.spec_drafts_accepted_total
                    / self.spec_drafts_proposed_total
                    if self.spec_drafts_proposed_total else 0.0),
                "prompt_tokens_total": self.prompt_tokens_total,
                "prefix_hit_tokens_total": self.prefix_hit_tokens_total,
                "prefix_saved_chunks_total": self.prefix_saved_chunks_total,
                "cow_copies_total": self.cow_copies_total,
                "host_tier_hits_total": self.host_tier_hits_total,
                "host_promoted_pages_total": self.host_promoted_pages_total,
                "host_demoted_pages_total": self.host_demoted_pages_total,
                "host_quarantined_total": self.host_quarantined_total,
                "host_backpressure_total": self.host_backpressure_total,
                "handoffs_out_total": self.handoffs_out_total,
                "handoffs_in_total": self.handoffs_in_total,
                "group_member_faults_total": self.group_member_faults_total,
                "shard_stragglers_total": self.shard_stragglers_total,
                "ttft_observed_total": self.ttft_observed_total,
                "tpot_samples_total": self.tpot_samples_total,
                "mean_step_occupancy": (
                    self.tokens_total / self.steps_total
                    if self.steps_total else 0.0),
            }

"""Radix prefix cache: share prompt-prefix KV pages across requests.

At serving scale most prompts open with the same system prompt / few-shot
preamble, yet PR 9's engine prefills every request from token zero. This
module keeps a token-keyed radix tree over *physical pages* of the paged
KV cache: each tree node owns one ``page_size``-token chunk of some
previously-prefilled prompt and holds one :class:`PageAllocator` reference
on the physical page containing that chunk's K/V. A new request walks the
tree with its prompt tokens; every matched node is a page of prefill it
can skip, adopted into the slot's page table via
:meth:`PagedKVCache.adopt_pages` (which takes a second ref — the page is
now shared between the tree and the slot).

Granularity is deliberately page-level, matching the cache's unit of
allocation: a partial-page hit would require sub-page masking in the
jitted step, which would break the fixed-shape discipline. The tree
therefore only ever holds *fully-written, immutable* pages — the engine
inserts ``len(seq) // page_size`` pages when a prompt finishes prefill,
never the trailing partial page.

Sharing is copy-on-write. A slot writes into an adopted page only when a
prefill continuation chunk straddles the hit boundary; the engine then
calls :meth:`PagedKVCache.private_copy` and re-writes the straddled span
into the private page. Tree refs are dropped by :meth:`evict` (LRU,
leaf-first, so a prefix is never orphaned from its extension) and
:meth:`clear` (engine teardown — after which ``assert_no_leaks`` holds
again). Evicting a page some slot still maps is safe: the allocator
refcount keeps the page alive until the last slot releases it.

Like the allocator, this is host-side state touched only by the engine's
single loop thread — no locking.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.serving.kv_cache import PageAllocator

__all__ = ["RadixPrefixCache"]


class _Node:
    """One cached page: ``key`` is the page's token chunk, ``page`` the
    physical page id the tree holds a ref on. ``digest`` is the running
    CRC32 of the full token prefix this node terminates (chained from
    the parent's digest) — the unit of the compact prefix digest the
    fleet's prefix-aware routing matches against."""

    __slots__ = ("key", "page", "parent", "children", "last_used",
                 "digest")

    def __init__(self, key: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0
        self.digest = 0


class RadixPrefixCache:
    """Token-prefix radix tree over refcounted KV pages.

    ``max_pages`` bounds how many pages the tree may pin; inserts beyond
    the bound evict least-recently-used leaves first. ``None`` leaves the
    tree unbounded — the engine still evicts on allocator pressure before
    resorting to preemption.
    """

    def __init__(self, allocator: PageAllocator, page_size: int,
                 max_pages: Optional[int] = None):
        enforce(page_size >= 1, f"page_size must be >= 1, got {page_size}")
        self.allocator = allocator
        self.page_size = int(page_size)
        self.max_pages = None if max_pages is None else int(max_pages)
        self._root = _Node((), -1, None)
        self._nodes: List[_Node] = []  # all non-root nodes, for evict scans
        self._tick = 0
        # per-node prefix digests (see _Node.digest) and a version stamp
        # bumped on every membership change — the engine republishes its
        # routing digest only when this moved
        self._digests: Set[int] = set()
        self.digest_version = 0
        # counters surfaced through DecodeMetrics / bench
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.inserts = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # -- readout -----------------------------------------------------------

    @property
    def num_pages(self) -> int:
        return len(self._nodes)

    def digests(self) -> frozenset:
        """Immutable snapshot of the per-prefix digests currently cached
        (one per node — the page-aligned token prefix it terminates).
        The engine publishes this for prefix-aware fleet routing; take a
        fresh snapshot after ``digest_version`` moves."""
        return frozenset(self._digests)

    def stats(self) -> Dict[str, int]:
        return {
            "pages": self.num_pages,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_tokens": self.hit_tokens,
            "inserts": self.inserts,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }

    # -- core --------------------------------------------------------------

    def match(self, tokens: Sequence[int],
              max_pages: Optional[int] = None) -> List[int]:
        """Longest page-granular cached prefix of ``tokens``: the physical
        page ids, in logical order. Touches the matched path for LRU."""
        self.lookups += 1
        ps = self.page_size
        limit = len(tokens) // ps
        if max_pages is not None:
            limit = min(limit, max_pages)
        self._tick += 1
        node = self._root
        pages: List[int] = []
        for i in range(limit):
            key = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick
            pages.append(child.page)
            node = child
        if pages:
            self.hits += 1
            self.hit_tokens += len(pages) * ps
        return pages

    def peek(self, tokens: Sequence[int],
             max_pages: Optional[int] = None) -> List[int]:
        """:meth:`match` without the stat bumps or LRU touch — internal
        probes (e.g. the host-tier promote apply path re-checking current
        tree depth) must not inflate hit-rate counters or keep a prefix
        artificially warm."""
        ps = self.page_size
        limit = len(tokens) // ps
        if max_pages is not None:
            limit = min(limit, max_pages)
        node = self._root
        pages: List[int] = []
        for i in range(limit):
            key = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                break
            pages.append(child.page)
            node = child
        return pages

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Record ``pages`` (the slot's first ``len(pages)`` logical pages,
        fully written with the K/V of ``tokens``) under their token path.
        Chunks already present are left as-is — dedup falls out of the
        walk, so re-inserting a shared prefix never double-refs. Returns
        the number of *new* pages the tree took a reference on."""
        ps = self.page_size
        enforce(len(tokens) >= len(pages) * ps,
                f"insert: {len(pages)} pages need {len(pages) * ps} tokens, "
                f"got {len(tokens)}")
        self.inserts += 1
        self._tick += 1
        node = self._root
        added = 0
        for i, page in enumerate(pages):
            key = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                self.allocator.ref([page])
                child = _Node(key, int(page), node)
                child.digest = zlib.crc32(
                    np.asarray(key, np.int32).tobytes(),
                    node.digest) & 0xFFFFFFFF
                node.children[key] = child
                self._nodes.append(child)
                self._digests.add(child.digest)
                added += 1
            child.last_used = self._tick
            node = child
        self.inserted_pages += added
        if added:
            self.digest_version += 1
        if self.max_pages is not None and self.num_pages > self.max_pages:
            self.evict(pages_needed=0,
                       max_evictions=self.num_pages - self.max_pages)
        return added

    def evict(self, pages_needed: int = 1,
              max_evictions: Optional[int] = None) -> int:
        """Drop LRU leaves until ``pages_needed`` pages have actually
        returned to the allocator's free list (a leaf some slot still maps
        frees no capacity — its refcount stays positive) or the tree is
        empty. ``max_evictions`` instead bounds the number of leaves
        dropped regardless of freed capacity (size-cap trimming). Returns
        the number of pages returned to the free list."""
        freed = 0
        dropped = 0
        while self._nodes:
            if max_evictions is not None and dropped >= max_evictions:
                break
            if max_evictions is None and freed >= pages_needed:
                break
            leaf = min((n for n in self._nodes if not n.children),
                       key=lambda n: n.last_used, default=None)
            if leaf is None:  # cannot happen: a finite tree has leaves
                break
            before = self.allocator.num_free
            self.allocator.free([leaf.page])
            freed += self.allocator.num_free - before
            dropped += 1
            leaf.parent.children.pop(leaf.key, None)
            self._nodes.remove(leaf)
            self._digests.discard(leaf.digest)
        self.evicted_pages += dropped
        if dropped:
            self.digest_version += 1
        return freed

    def clear(self) -> int:
        """Drop every tree reference (engine teardown). Returns the number
        of nodes dropped. Pages still mapped by live slots survive until
        those slots release."""
        n = len(self._nodes)
        for node in self._nodes:
            self.allocator.free([node.page])
        self._nodes.clear()
        self._root.children.clear()
        self._digests.clear()
        if n:
            self.digest_version += 1
        self.evicted_pages += n
        return n

"""Zero-loss decode: request recovery, durable journal, engine migration.

PR 9's continuous-batching engine treated any decode-step fault as fatal
to every in-flight request, even though its own preempt/resume path
already proves a generation is reconstructible token-exactly from
``prompt + generated``. This module finishes that story — the MapReduce/
GFS insight (re-execute from durable state instead of gang-failing)
applied to autoregressive serving. Three nested safety rings:

1. **Step-fault recovery** (innermost, in ``serving.decode``): a failed
   jitted iteration poisons only that iteration's KV writes. The engine
   quarantines the batch — every slot released, every live request
   re-admitted through the proven resume path — under a per-request
   retry budget with decorrelated-jitter backoff. Deterministic poison
   surfaces a typed :class:`RetriesExhausted` instead of looping.
2. **Cross-engine migration**: K consecutive faulted iterations declare
   the engine unhealthy — its ``CircuitBreaker`` trips, live requests
   drain into host-side :class:`RescuePacket`\\ s, and a
   :class:`DecodeFleet` resubmits them on a healthy engine where greedy
   decode continues token-exactly. Half-open probing re-admits the
   engine after cooldown.
3. **Durable journal** (outermost, survives the process): an append-only
   :class:`RequestJournal` WAL — CRC per record, torn-tail tolerant,
   batched fsync off the step path — records admission and every
   generated token. :func:`replay_journal` reconstructs state after a
   restart; :func:`resume_incomplete` resubmits unfinished requests, and
   idempotent request ids let clients dedupe tokens already delivered.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.core import locks
from paddle_tpu.core import logging as ptlog
from paddle_tpu.core.enforce import enforce
from paddle_tpu.observability import runlog
from paddle_tpu.resilience.circuit import CLOSED

__all__ = [
    "DecodeFleet",
    "EngineUnhealthy",
    "ReplayedRequest",
    "RequestJournal",
    "RescuePacket",
    "RetriesExhausted",
    "replay_journal",
    "resume_incomplete",
]


class RetriesExhausted(RuntimeError):
    """A request burned through its recovery budget — the fault follows
    it across quarantine cycles, so it is the poison (or rides a dead
    device with nowhere to migrate). Carries the request id so clients
    can correlate with journal/runlog records."""

    def __init__(self, message: str, request_id: Optional[str] = None):
        super().__init__(message)
        self.request_id = request_id


class EngineUnhealthy(RuntimeError):
    """No healthy engine could take the work (fleet exhausted, or the
    engine was killed)."""


@dataclasses.dataclass
class RescuePacket:
    """Everything needed to continue one generation on another engine:
    pure host-side state (the KV cache is rebuilt by re-prefill, which
    the preempt/resume path proves token-exact). ``handle`` is the
    client's original future — migration repoints it at the adopting
    engine's request so ``result()``/``cancel()`` keep working; None
    (journal replay: the old process's futures died with it) makes the
    adopter mint a fresh handle."""

    rid: str
    prompt: np.ndarray
    mnt: int
    generated: List[int]
    tenant: str = "default"
    cls: str = "interactive"
    deadline: Optional[float] = None
    t_submit: float = 0.0
    n_preemptions: int = 0
    handle: Optional[Any] = None
    trace: Optional[Any] = None
    cancelled: bool = False


# -- the durable request journal (WAL) --------------------------------------

_J_ADMIT = "admit"
_J_TOK = "tok"
_J_FIN = "fin"
# disaggregated handoff (serving.disagg): a prefill worker published the
# request's KV pages toward a decode worker ("hof", full request snapshot
# — authoritative like an admit record), and the receiving decode worker
# acknowledged adoption ("ack"). A crash between the two leaves the
# request unfinished in replay, so resume_incomplete re-prefills it —
# a handoff in flight is never a lost request.
_J_HOF = "hof"
_J_ACK = "ack"


def _encode_record(obj: Dict[str, Any]) -> bytes:
    payload = json.dumps(obj, separators=(",", ":"), sort_keys=True)
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x}|{payload}\n".encode("utf-8")


def _decode_record(line: bytes) -> Optional[Dict[str, Any]]:
    """One journal line -> record dict, or None when the line is torn or
    corrupt (bad CRC, truncated json, missing separator)."""
    try:
        text = line.decode("utf-8")
        crc_hex, payload = text.split("|", 1)
        payload = payload.rstrip("\n")
        if int(crc_hex, 16) != (zlib.crc32(payload.encode("utf-8"))
                                & 0xFFFFFFFF):
            return None
        obj = json.loads(payload)
        return obj if isinstance(obj, dict) else None
    except (ValueError, UnicodeDecodeError):
        return None


class RequestJournal:
    """Append-only WAL of request admissions, generated tokens, and
    terminal outcomes. Same durability discipline as
    ``observability.runlog``: one self-validating record per line
    (``<crc32-hex>|<compact-json>``), written append-only so a crash can
    only tear the final line — :func:`replay_journal` stops at the first
    bad record and trusts everything before it.

    fsync policy: records are buffered through the OS and fsync'd every
    ``fsync_every`` appends (and on :meth:`flush`/:meth:`close`), keeping
    the syscall off the per-token hot path. The window between fsyncs is
    the only durability gap — at most ``fsync_every`` tokens re-decode
    after a crash, which re-prefill makes token-exact anyway.

    ``compact_bytes`` bounds WAL growth: once the file exceeds it, the
    journal is compacted — every request replayed, finished ones dropped,
    and only incomplete ones rewritten (as authoritative admit snapshots
    carrying their generated prefix) into a fresh segment published
    atomically (tmp + fsync + ``os.replace`` + directory fsync). Replay
    over a compacted journal is indistinguishable from replay over the
    full history. None = never compact (the pre-PR-15 contract)."""

    def __init__(self, path: str, fsync_every: int = 16,
                 compact_bytes: Optional[int] = None):
        enforce(fsync_every >= 1,
                f"fsync_every must be >= 1, got {fsync_every}")
        enforce(compact_bytes is None or compact_bytes >= 1,
                f"compact_bytes must be >= 1, got {compact_bytes}")
        self.path = path
        self.fsync_every = int(fsync_every)
        self.compact_bytes = compact_bytes
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        self._lock = locks.Lock("serving.request_journal")
        self._unsynced = 0
        self._bytes = os.path.getsize(path)
        self.records_total = 0
        self.compactions_total = 0

    def _append(self, obj: Dict[str, Any]) -> None:
        data = _encode_record(obj)
        need_sync = False
        need_compact = False
        with self._lock:
            if self._f.closed:
                return  # journal detached mid-flight (engine killed)
            self._f.write(data)
            self.records_total += 1
            self._unsynced += 1
            self._bytes += len(data)
            if self._unsynced >= self.fsync_every:
                self._unsynced = 0
                need_sync = True
            if (self.compact_bytes is not None
                    and self._bytes >= self.compact_bytes):
                need_compact = True
        if need_sync:
            self._sync()
        if need_compact:
            self.compact()

    def _sync(self) -> None:
        """flush+fsync OUTSIDE the append lock: fsync covers every byte
        written before the call, so a concurrent append only widens the
        sync, never narrows it — and the ms-scale syscall no longer stalls
        other writer threads behind the disk (BufferedWriter serializes
        the flush internally)."""
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except (ValueError, OSError):
            pass  # journal closed mid-flight; close() already synced

    def log_admit(self, rid: str, prompt: np.ndarray, mnt: int,
                  gen_prefix: List[int], tenant: str, cls: str,
                  trace: Optional[str] = None) -> None:
        """Request accepted (or adopted with an already-generated prefix
        after migration/replay — ``gen_prefix`` keeps the journal
        self-contained without rewriting token records). ``trace`` is the
        request's W3C traceparent, journaled so a post-crash replay
        resumes under the ORIGINAL trace id instead of minting a fresh
        one — the fleet trace survives the process."""
        rec = {
            "k": _J_ADMIT, "rid": rid,
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
            "mnt": int(mnt), "gen": [int(t) for t in gen_prefix],
            "tenant": tenant, "cls": cls,
        }
        if trace is not None:
            rec["tp"] = trace
        self._append(rec)

    def log_token(self, rid: str, tok: int) -> None:
        self._append({"k": _J_TOK, "rid": rid, "t": int(tok)})

    def log_finish(self, rid: str, reason: str) -> None:
        self._append({"k": _J_FIN, "rid": rid, "reason": reason})

    def log_handoff(self, rid: str, prompt: np.ndarray, mnt: int,
                    gen_prefix: List[int], tenant: str, cls: str,
                    src: str, dst: Optional[str],
                    trace: Optional[str] = None) -> None:
        """A prefill worker published this request's KV pages toward
        ``dst``. The record carries the full request snapshot (like an
        admit record) so replay of THIS journal alone can re-prefill an
        unacked handoff — durability does not depend on the source
        worker surviving the transfer. ``trace`` keeps the traceparent
        durable alongside it (same contract as :meth:`log_admit`)."""
        rec = {
            "k": _J_HOF, "rid": rid,
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
            "mnt": int(mnt), "gen": [int(t) for t in gen_prefix],
            "tenant": tenant, "cls": cls, "src": src, "dst": dst,
        }
        if trace is not None:
            rec["tp"] = trace
        self._append(rec)
        self.flush()  # the handoff record must be durable before transfer

    def log_handoff_ack(self, rid: str, dst: str) -> None:
        """The receiving decode worker validated and adopted the pages."""
        self._append({"k": _J_ACK, "rid": rid, "dst": dst})

    def compact(self) -> Dict[str, int]:
        """Rewrite the WAL into a fresh segment containing only incomplete
        requests (each as one authoritative admit snapshot carrying its
        generated prefix); finished requests and their token records are
        dropped. The new segment is published atomically — written to a
        temp file, fsync'd, ``os.replace``'d over the journal, and the
        directory entry fsync'd — so a crash at ANY point leaves either
        the old segment or the complete new one, never a mix. The journal
        lock is held throughout: a compaction is rare (size-triggered)
        and concurrent appends must not land in the segment being
        replaced. Torn-tail safe by construction: replay stops at the
        first corrupt record, so compaction preserves exactly the state a
        post-crash replay would recover. Returns
        ``{"kept": .., "dropped": .., "bytes": ..}``."""
        with self._lock:
            if self._f.closed:
                return {"kept": 0, "dropped": 0, "bytes": 0}
            try:
                self._f.flush()
                os.fsync(self._f.fileno())  # lint: allow — rare, must be atomic vs appends
            except (ValueError, OSError):
                return {"kept": 0, "dropped": 0, "bytes": 0}
            replayed = replay_journal(self.path)
            tmp = f"{self.path}.compact.{os.getpid()}"
            kept = 0
            with open(tmp, "wb") as f:  # lint: allow — rare, must be atomic vs appends
                for rid, rr in replayed.items():
                    if rr.finished:
                        continue
                    snap = {
                        "k": _J_ADMIT, "rid": rid,
                        "prompt": [int(t) for t in rr.prompt],
                        "mnt": int(rr.mnt),
                        "gen": [int(t) for t in rr.generated],
                        "tenant": rr.tenant, "cls": rr.cls,
                    }
                    if rr.trace is not None:
                        snap["tp"] = rr.trace
                    f.write(_encode_record(snap))
                    kept += 1
                f.flush()
                os.fsync(f.fileno())  # lint: allow — rare, must be atomic vs appends
            old = self._f
            os.replace(tmp, self.path)  # lint: allow — rare, must be atomic vs appends
            dpath = os.path.dirname(os.path.abspath(self.path)) or "."
            dfd = os.open(dpath, os.O_RDONLY)
            try:
                os.fsync(dfd)  # lint: allow — rare, must be atomic vs appends
            finally:
                os.close(dfd)
            old.close()
            self._f = open(self.path, "ab")  # lint: allow — rare, must be atomic vs appends
            self._bytes = os.path.getsize(self.path)
            self._unsynced = 0
            self.compactions_total += 1
            dropped = len(replayed) - kept
            nbytes = self._bytes
        runlog.emit("journal_compacted", path=self.path, kept=kept,
                    dropped=dropped, bytes=nbytes)
        return {"kept": kept, "dropped": dropped, "bytes": nbytes}

    def flush(self) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._unsynced = 0
        self._sync()

    def close(self) -> None:
        self._sync()
        with self._lock:
            if not self._f.closed:
                self._f.close()


@dataclasses.dataclass
class ReplayedRequest:
    """One request reconstructed from the journal. ``handed_off``/
    ``acked`` expose the disaggregated-handoff state: a request that was
    handed off but never acked was in flight between workers at the
    crash — it is NOT finished, so :func:`resume_incomplete` re-prefills
    it (the zero-loss handoff contract)."""

    rid: str
    prompt: np.ndarray
    mnt: int
    generated: List[int]
    tenant: str = "default"
    cls: str = "interactive"
    finished: bool = False
    reason: Optional[str] = None
    handed_off: bool = False
    acked: bool = False
    trace: Optional[str] = None  # W3C traceparent from the admit/hof record


def replay_journal(path: str) -> Dict[str, ReplayedRequest]:
    """Reconstruct request state from a journal file, in admission order.
    Torn-tail tolerant: reading stops at the first corrupt record (a
    crash mid-append can only damage the tail; anything after a bad
    record is untrusted). A re-``admit`` of a known rid (migration across
    engines sharing a journal, or an adopted prefix) resets that
    request's token prefix to the record's ``gen`` — admission records
    are authoritative snapshots, token records are increments."""
    out: Dict[str, ReplayedRequest] = {}
    if not os.path.exists(path):
        return out
    n_bad = 0
    with open(path, "rb") as f:
        for line in f:
            rec = _decode_record(line)
            if rec is None:
                n_bad += 1
                break  # torn tail: trust nothing past the first bad record
            kind, rid = rec.get("k"), rec.get("rid")
            if kind == _J_ADMIT:
                out[rid] = ReplayedRequest(
                    rid=rid,
                    prompt=np.asarray(rec.get("prompt", []), np.int32),
                    mnt=int(rec.get("mnt", 0)),
                    generated=[int(t) for t in rec.get("gen", [])],
                    tenant=rec.get("tenant", "default"),
                    cls=rec.get("cls", "interactive"),
                    trace=rec.get("tp"),
                )
            elif kind == _J_TOK and rid in out:
                out[rid].generated.append(int(rec.get("t", 0)))
            elif kind == _J_FIN and rid in out:
                out[rid].finished = True
                out[rid].reason = rec.get("reason")
            elif kind == _J_HOF:
                # authoritative snapshot at publish time, like an admit —
                # a prefill worker may hand off a request this journal
                # never saw admitted (per-worker journals)
                rr = out.get(rid)
                if rr is None:
                    rr = out[rid] = ReplayedRequest(
                        rid=rid, prompt=np.asarray(
                            rec.get("prompt", []), np.int32),
                        mnt=int(rec.get("mnt", 0)), generated=[],
                        tenant=rec.get("tenant", "default"),
                        cls=rec.get("cls", "interactive"))
                rr.generated = [int(t) for t in rec.get("gen", [])]
                rr.handed_off = True
                rr.acked = False
                if rec.get("tp") is not None:
                    rr.trace = rec.get("tp")
            elif kind == _J_ACK and rid in out:
                out[rid].acked = True
    if n_bad:
        ptlog.warning("journal %s: stopped at a torn/corrupt record "
                      "(%d request(s) recovered before it)", path, len(out))
    return out


def _trace_from_traceparent(tracing_mod, header: Optional[str]):
    """Journaled traceparent -> SpanContext, or None when the record
    predates trace journaling or carries a malformed header (a corrupt
    trace must never block replay of an otherwise-valid request)."""
    if not header:
        return None
    try:
        return tracing_mod.SpanContext.from_traceparent(header)
    except Exception:
        return None


def resume_incomplete(engine, path: str) -> Dict[str, Tuple[Any, int]]:
    """Resubmit every journaled-but-unfinished request on ``engine``
    (typically a fresh process over the same journal file). Returns
    ``rid -> (handle, n_delivered)`` where ``n_delivered`` is how many
    tokens the journal proves were already produced — the idempotent-id
    dedup contract: the resumed output's first ``n_delivered`` tokens are
    exactly the ones a client may already have received, so a delivery
    layer replays ``tokens[n_delivered:]`` only."""
    from paddle_tpu import tracing

    replayed = replay_journal(path)
    out: Dict[str, Tuple[Any, int]] = {}
    for rid, rr in replayed.items():
        if rr.finished:
            continue
        packet = RescuePacket(
            rid=rid, prompt=rr.prompt, mnt=rr.mnt,
            generated=list(rr.generated), tenant=rr.tenant, cls=rr.cls,
            t_submit=time.monotonic(),
            trace=_trace_from_traceparent(tracing, rr.trace),
        )
        handle = engine.adopt_rescue(packet)
        out[rid] = (handle, len(rr.generated))
    engine.metrics.record_journal_replayed(len(out))
    runlog.emit("journal_replay", engine=engine.metrics.engine_label,
                path=path, resumed=len(out),
                finished=len(replayed) - len(out))
    return out


# -- cross-engine migration --------------------------------------------------

class DecodeFleet:
    """A set of ``DecodeEngine``\\ s behind one submit surface, with
    health-aware routing and rescue. Each engine keeps its own
    ``CircuitBreaker``; routing picks the least-loaded CLOSED breaker
    (live slots + queued/parked depth, ``DecodeEngine.load()``) and
    spends at most one half-open probe per pick on a cooled-down OPEN
    one, so a recovered device earns its traffic back one request at a
    time. When an engine declares itself unhealthy it drains its live
    requests into :class:`RescuePacket`\\ s and hands them here —
    :meth:`_rescue` re-places each on a healthy peer with the client's
    original handle intact."""

    def __init__(self, engines: List[Any]):
        enforce(len(engines) >= 1, "DecodeFleet needs at least one engine")
        self.engines = list(engines)
        self._rr = 0
        self._lock = locks.Lock("serving.decode_fleet")
        # engines mid drain-and-convert (serving.disagg): excluded from
        # routing while their graceful drain runs
        self._draining: set = set()
        self.rescued_total = 0
        self.rescue_failed_total = 0
        for eng in self.engines:
            eng._rescue_sink = self._rescue

    @classmethod
    def from_groups(cls, variables, model_cfg, groups, *,
                    layout=None, config=None, decode=None,
                    **engine_kwargs) -> "DecodeFleet":
        """Build a fleet with one group-backed engine per
        :class:`~paddle_tpu.serving.shardgroup.ReplicaGroup` — the
        pod-scale shape where the routing unit is a tp submesh, not a
        device. Engine labels default to the group names so breaker
        trips, migrations and shard-skew gauges attribute to a group."""
        # imported here: decode.py imports this module's RescuePacket
        from paddle_tpu.serving.decode import DecodeEngine
        from paddle_tpu.serving.engine import ServingConfig
        engines = []
        for g in groups:
            sc = dataclasses.replace(
                config if config is not None else ServingConfig(),
                engine_label=g.name)
            engines.append(DecodeEngine(
                variables, model_cfg, config=sc, decode=decode,
                group=g, layout=layout, **engine_kwargs))
        return cls(engines)

    def _order(self, candidates: Optional[List[Any]] = None) -> List[Any]:
        """Rotating view over ``candidates`` (default: every engine) —
        keeps half-open probes fair when several breakers cool down at
        once; the load ranking below is order-independent."""
        engines = list(self.engines if candidates is None else candidates)
        with self._lock:
            k = self._rr
            self._rr += 1
        n = len(engines)
        return [engines[(k + i) % n] for i in range(n)] if n else []

    def _pick(self, exclude: Optional[Any] = None,
              candidates: Optional[List[Any]] = None,
              prompt: Optional[Any] = None) -> Optional[Any]:
        order = [e for e in self._order(candidates)
                 if e is not exclude and not e.closed
                 and id(e) not in self._draining]
        # spend a half-open probe the moment one is available — even with
        # healthy engines around, one risked request is how an ejected
        # engine earns its capacity back (a failed probe just re-opens
        # the breaker, and recovery/migration makes the request itself
        # zero-loss). allow() takes the single probe token atomically.
        healthy = []
        for eng in order:
            if eng.breaker.state == CLOSED:
                healthy.append(eng)
            elif eng.breaker.retry_in() == 0.0 and eng.breaker.allow():
                return eng
        if not healthy:
            return None
        # least-loaded over CLOSED breakers: a saturated engine stops
        # receiving new work while a peer has capacity. Ties break on the
        # engine's stable fleet index, NOT the rotated order — the rotation
        # exists for half-open-probe fairness above, but letting it leak
        # into the load ranking made equal-load placement depend on how
        # many picks had ever happened, so identical traffic replayed onto
        # different engines run-to-run.
        pos = {id(e): i for i, e in enumerate(self.engines)}
        n = len(self.engines)
        # prefix-aware routing: rank by the longest cached prefix of the
        # prompt first (engines publish compact per-prefix digest sets
        # when DecodeConfig.prefix_digest is on; others match depth 0),
        # then least-loaded, then stable index. A digest is advisory —
        # worst case the match is stale and the engine just prefills, so
        # routing optimality degrades but never correctness.
        depth = self._match_depth_fn(prompt) if prompt is not None else None
        if depth is not None:
            return min(healthy, key=lambda e: (-depth(e), e.load(),
                                               pos.get(id(e), n)))
        return min(healthy, key=lambda e: (e.load(), pos.get(id(e), n)))

    @staticmethod
    def _match_depth_fn(prompt) -> Optional[Any]:
        """Cached-prefix depth scorer for one prompt, or None when no
        digest chain applies. Digest chains are memoized per page size —
        a homogeneous fleet computes the CRC chain once per submit."""
        from paddle_tpu.serving.host_tier import prefix_digests
        memo: Dict[int, List[int]] = {}

        def depth(eng) -> int:
            match = getattr(eng, "prefix_match_depth", None)
            dconf = getattr(eng, "decode_config", None)
            if match is None or dconf is None:
                return 0
            ps = dconf.page_size
            if ps not in memo:
                memo[ps] = prefix_digests(prompt, ps)
            return match(memo[ps])

        return depth

    def submit(self, prompt, max_new_tokens: int, **kwargs):
        eng = self._pick(prompt=prompt)
        if eng is None:
            raise EngineUnhealthy(
                "no healthy decode engine (all breakers open or cooling)")
        return eng.submit(prompt, max_new_tokens, **kwargs)

    def _rescue(self, src, packets: List[RescuePacket]) -> int:
        """Re-place drained requests anywhere but ``src``. A packet with
        no healthy destination fails its handle with
        :class:`EngineUnhealthy` — zero-loss holds as long as one healthy
        engine exists."""
        adopted = 0
        for packet in packets:
            dst = self._pick(exclude=src)
            if dst is None:
                self.rescue_failed_total += 1
                if packet.handle is not None:
                    packet.handle._fail(EngineUnhealthy(
                        f"request {packet.rid}: engine "
                        f"{src.metrics.engine_label} unhealthy and no "
                        f"healthy engine to migrate to"))
                continue
            dst.adopt_rescue(packet, from_engine=src.metrics.engine_label)
            adopted += 1
            self.rescued_total += 1
        return adopted

    def snapshot(self) -> Dict[str, Any]:
        return {
            "engines": [
                {"engine": e.metrics.engine_label,
                 "breaker": e.breaker.snapshot(),
                 "closed": e.closed}
                for e in self.engines
            ],
            "rescued_total": self.rescued_total,
            "rescue_failed_total": self.rescue_failed_total,
        }

    def close(self, timeout: Optional[float] = None) -> List[str]:
        unjoined: List[str] = []
        for eng in self.engines:
            unjoined.extend(eng.close(timeout))
        return unjoined

    def __enter__(self) -> "DecodeFleet":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        self.close()
        return False

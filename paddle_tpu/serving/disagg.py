"""Disaggregated prefill/decode serving: role-split worker fleets with
durable KV-page handoff and SLO-driven elastic rebalancing.

PR 9 split chunked prefill from decode *inside* one engine, but they
still share a worker: a compute-bound prefill storm steals loop
iterations from latency-bound decode. This module disaggregates the two
phases onto separate workers — the reference framework's trainer/pserver
role split, made elastic — so prefill load cannot move decode latency:

- **Prefill workers** run ``paged_prefill_chunk`` to completion, then
  publish the request's KV pages instead of decoding
  (``DecodeEngine._publish_handoff``).
- **Decode workers** adopt published pages straight into their decode
  loop (``DecodeEngine.adopt_handoff``) and continue from ``cur_len``
  without re-prefilling.
- The :class:`DisaggRouter` (a :class:`DecodeFleet`) connects them.
  In-process the pages move device-to-device through
  :mod:`paddle_tpu.parallel.collective` gather/scatter; across processes
  they travel as a :class:`HandoffPayload` wire blob with a CRC per page
  — a receiver rejects torn transfers (:class:`HandoffCorrupt`) instead
  of adopting garbage KV state.

**Durability.** The handoff window is the only new place a request could
be lost, so it is journaled like everything else: a ``hof`` record
(full request snapshot, fsync'd BEFORE the transfer) in the shared
:class:`~paddle_tpu.serving.recovery.RequestJournal`, and an ``ack``
record once the receiver adopted the pages. A prefill worker dying
mid-transfer leaves ``hof`` without ``ack`` — replay resumes the request
by re-prefilling on a surviving worker, token-exact, the same contract
as the PR 11 rescue ladder. A torn or corrupt payload degrades the same
way at adoption time. Zero-loss holds as long as one worker survives.

**Elasticity.** The prefill:decode worker ratio is not hand-picked: an
:class:`Autoscaler` consumes the ``watch`` SLO burn rate of interactive
decode p99 plus queue-depth anomaly signals and **drain-and-converts**
workers between roles at safe boundaries — graceful drain
(``DecodeEngine.close``), role flip, re-warm from the persistent warmup
manifest (``DecodeConfig(warmup=False, prewarm=True)``).
"""

from __future__ import annotations

import dataclasses
import json
import struct
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from paddle_tpu import tracing
from paddle_tpu.core import logging as ptlog
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.enforce import enforce, enforce_in
from paddle_tpu.observability import runlog
from paddle_tpu.resilience import faults
from paddle_tpu.serving.recovery import (
    DecodeFleet,
    EngineUnhealthy,
    RequestJournal,
    RescuePacket,
)

__all__ = [
    "PREFILL",
    "DECODE",
    "HandoffCorrupt",
    "HandoffPayload",
    "DisaggRouter",
    "Autoscaler",
    "AutoscalerConfig",
]

PREFILL = "prefill"
DECODE = "decode"
_ROLES = (PREFILL, DECODE)

# wire format version tag for serialized handoffs
_MAGIC = b"PTKV1\n"
_HDR = struct.Struct("<II")  # header length, header crc32


class HandoffCorrupt(RuntimeError):
    """A serialized handoff payload failed validation (truncated buffer,
    header or page CRC mismatch). The receiver must NOT adopt any of it —
    the request re-prefills from its journaled host state instead."""


def _trace_from_header(header: Optional[str]):
    """Wire traceparent -> SpanContext. Version-tolerant on both axes:
    an absent key (old writer) and a malformed value both decode to None
    — trace context is advisory and must never fail an otherwise-valid
    handoff."""
    if not header:
        return None
    try:
        return tracing.SpanContext.from_traceparent(header)
    except Exception:
        return None


@dataclasses.dataclass
class HandoffPayload:
    """One prefilled request in transit between workers: host-side
    request state (the :class:`RescuePacket` fields) plus the KV pages
    the prefill worker produced. ``cur_len`` positions are covered by the
    pages; ``last_tok`` (= ``generated[-1]``) is the token whose KV write
    is still pending — exactly the mid-decode state the adopting engine's
    step loop expects. ``handle`` is process-local and never serialized;
    :meth:`from_bytes` leaves it None for the caller to re-attach.
    ``trace`` (a :class:`~paddle_tpu.tracing.SpanContext`) DOES ride the
    wire — as a W3C traceparent string inside the CRC'd header — so the
    adopting worker's spans parent under the original request trace
    across processes. Decode is version-tolerant: a payload without the
    key (pre-fleet-observability writer) adopts with ``trace=None``."""

    rid: str
    prompt: np.ndarray
    generated: List[int]
    mnt: int
    cur_len: int
    last_tok: int
    page_size: int
    k_pages: List[np.ndarray]
    v_pages: List[np.ndarray]
    tenant: str = "default"
    cls: str = "interactive"
    deadline: Optional[float] = None
    t_submit: float = 0.0
    n_preemptions: int = 0
    src: str = ""
    handle: Optional[Any] = None
    trace: Optional[Any] = None
    # tp degree of the group that GATHERED the pages (1 = single device).
    # Pages on the wire are always full logical pages, but an adopter
    # with a different degree ran a different partitioned program, so it
    # rejects the pages and re-prefills (serving.decode._admit_handoffs)
    tp_degree: int = 1

    def to_bytes(self) -> bytes:
        """Serialize for cross-process transfer: a CRC-protected JSON
        header (request state + page geometry + one CRC per page blob)
        followed by the raw page bytes. Same self-validating discipline
        as the journal's records — corruption is detected, never
        adopted."""
        blobs = [np.ascontiguousarray(p).tobytes()
                 for p in list(self.k_pages) + list(self.v_pages)]
        shape = list(self.k_pages[0].shape) if self.k_pages else []
        dtype = str(self.k_pages[0].dtype) if self.k_pages else "float32"
        header = {
            "rid": self.rid,
            "prompt": [int(t) for t in
                       np.asarray(self.prompt).reshape(-1)],
            "generated": [int(t) for t in self.generated],
            "mnt": int(self.mnt),
            "cur_len": int(self.cur_len),
            "last_tok": int(self.last_tok),
            "page_size": int(self.page_size),
            "tenant": self.tenant,
            "cls": self.cls,
            "deadline": self.deadline,
            "t_submit": float(self.t_submit),
            "n_preemptions": int(self.n_preemptions),
            "src": self.src,
            "tp_degree": int(self.tp_degree),
            "trace": (self.trace.to_traceparent()
                      if self.trace is not None else None),
            "n_pages": len(self.k_pages),
            "shape": shape,
            "dtype": dtype,
            "page_crcs": [zlib.crc32(b) & 0xFFFFFFFF for b in blobs],
        }
        hjson = json.dumps(header, separators=(",", ":"),
                           sort_keys=True).encode("utf-8")
        parts = [_MAGIC,
                 _HDR.pack(len(hjson), zlib.crc32(hjson) & 0xFFFFFFFF),
                 hjson]
        parts.extend(blobs)
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "HandoffPayload":
        """Parse + validate a wire blob. Raises :class:`HandoffCorrupt`
        on any inconsistency — a torn transfer must be rejected whole,
        not partially adopted."""
        if not data.startswith(_MAGIC):
            raise HandoffCorrupt("bad magic: not a handoff payload")
        off = len(_MAGIC)
        if len(data) < off + _HDR.size:
            raise HandoffCorrupt("truncated header prefix")
        hlen, hcrc = _HDR.unpack_from(data, off)
        off += _HDR.size
        hjson = data[off:off + hlen]
        if len(hjson) != hlen:
            raise HandoffCorrupt("truncated header")
        if (zlib.crc32(hjson) & 0xFFFFFFFF) != hcrc:
            raise HandoffCorrupt("header CRC mismatch")
        off += hlen
        try:
            h = json.loads(hjson.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise HandoffCorrupt(f"header undecodable: {e}") from None
        n_pages = int(h["n_pages"])
        shape = tuple(int(d) for d in h["shape"])
        dtype = np.dtype(h["dtype"])
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        crcs = h["page_crcs"]
        if len(crcs) != 2 * n_pages:
            raise HandoffCorrupt("page CRC count mismatch")
        if len(data) - off != 2 * n_pages * nbytes:
            raise HandoffCorrupt(
                f"torn transfer: expected {2 * n_pages * nbytes} page "
                f"bytes, got {len(data) - off}")
        pages: List[np.ndarray] = []
        for i in range(2 * n_pages):
            blob = data[off + i * nbytes:off + (i + 1) * nbytes]
            if (zlib.crc32(blob) & 0xFFFFFFFF) != int(crcs[i]):
                raise HandoffCorrupt(f"page {i} CRC mismatch")
            pages.append(np.frombuffer(blob, dtype=dtype).reshape(shape))
        return cls(
            rid=h["rid"],
            prompt=np.asarray(h["prompt"], np.int32),
            generated=[int(t) for t in h["generated"]],
            mnt=int(h["mnt"]), cur_len=int(h["cur_len"]),
            last_tok=int(h["last_tok"]), page_size=int(h["page_size"]),
            k_pages=pages[:n_pages], v_pages=pages[n_pages:],
            tenant=h.get("tenant", "default"),
            cls=h.get("cls", "interactive"),
            deadline=h.get("deadline"),
            t_submit=float(h.get("t_submit", 0.0)),
            n_preemptions=int(h.get("n_preemptions", 0)),
            src=h.get("src", ""),
            tp_degree=int(h.get("tp_degree", 1)),
            trace=_trace_from_header(h.get("trace")),
        )

    def to_rescue_packet(self) -> RescuePacket:
        """The re-prefill fallback: everything but the pages, in the
        shape :meth:`DecodeEngine.adopt_rescue` already speaks."""
        return RescuePacket(
            rid=self.rid, prompt=self.prompt, mnt=self.mnt,
            generated=list(self.generated), tenant=self.tenant,
            cls=self.cls, deadline=self.deadline, t_submit=self.t_submit,
            n_preemptions=self.n_preemptions, handle=self.handle,
            trace=self.trace)


class DisaggRouter(DecodeFleet):
    """A :class:`DecodeFleet` whose engines play roles. ``submit`` routes
    new requests to prefill-role workers (least-loaded, breaker-aware —
    the inherited ``_pick`` over a role-filtered candidate set); when a
    prefill worker finishes a request's prefill it publishes the KV
    pages through :meth:`_handoff`, which journals the transfer, moves
    the pages (device or serialized transport), and hands the request to
    a decode-role worker.

    Failure ladder at the handoff boundary, worst to best outcome still
    being a completed request:

    1. transfer + adoption succeed → decode continues on the adopted
       pages (no re-prefill; ``ack`` journaled);
    2. transfer torn/corrupt or adoption fails → the request re-prefills
       on a decode worker via the PR 11 rescue path (token-exact);
    3. no healthy decode worker → the publishing engine keeps the
       request and decodes it locally (degraded but zero-loss);
    4. the prefill worker dies mid-transfer → the journal's unacked
       ``hof`` record resumes it on a surviving worker
       (``resume_incomplete``).

    ``journal`` (or ``journal_path``) installs one WAL SHARED by the
    router and every journal-less engine, so a single replay file covers
    the whole fleet including the handoff window. ``factory(role)``
    builds replacement engines for :meth:`convert`; build them with
    ``DecodeConfig(warmup=False, prewarm=True)`` so a converted worker
    re-warms from the persistent warmup manifest instead of recompiling
    blind."""

    def __init__(
        self,
        engines: List[Any],
        roles: List[str],
        *,
        transport: str = "device",
        journal: Optional[RequestJournal] = None,
        journal_path: Optional[str] = None,
        factory: Optional[Callable[[str], Any]] = None,
        convert_drain_timeout_s: float = 10.0,
    ):
        super().__init__(engines)
        enforce(len(roles) == len(engines),
                f"{len(engines)} engines but {len(roles)} roles")
        for r in roles:
            enforce_in(r, _ROLES, "worker role")
        enforce(DECODE in roles,
                "DisaggRouter needs at least one decode-role worker")
        enforce_in(transport, ("device", "serialized"), "handoff transport")
        self.transport = transport
        self.factory = factory
        self.convert_drain_timeout_s = float(convert_drain_timeout_s)
        self._roles: Dict[int, str] = {
            id(e): r for e, r in zip(self.engines, roles)}
        self._journal = journal
        self._journal_owned = False
        if journal is None and journal_path:
            self._journal = RequestJournal(journal_path)
            self._journal_owned = True
        self.handoffs_total = 0
        self.handoff_rejects_total = 0
        self.handoff_reprefills_total = 0
        self.conversions_total = 0
        for eng in self.engines:
            self._wire(eng, self._roles[id(eng)])

    def _wire(self, eng, role: str) -> None:
        """Attach one engine to the router's plumbing for its role."""
        eng._rescue_sink = self._rescue
        if self._journal is not None and eng._journal is None:
            eng._journal = self._journal
            eng._journal_owned = False
        eng._handoff_sink = self._handoff if role == PREFILL else None

    # -- role bookkeeping --------------------------------------------------

    def role(self, eng) -> str:
        return self._roles.get(id(eng), DECODE)

    def workers(self, role: str) -> List[Any]:
        return [e for e in self.engines if self._roles.get(id(e)) == role]

    @property
    def n_prefill(self) -> int:
        return sum(1 for e in self.workers(PREFILL) if not e.closed)

    @property
    def n_decode(self) -> int:
        return sum(1 for e in self.workers(DECODE) if not e.closed)

    def queue_depths(self) -> Dict[str, float]:
        """Live work per role (the Autoscaler's queue-depth signal)."""
        return {
            role: float(sum(e.load() for e in self.workers(role)
                            if not e.closed))
            for role in _ROLES
        }

    # -- request intake ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kwargs):
        """Route to the healthy prefill-role worker with the longest
        cached prefix of ``prompt`` (least-loaded tiebreak — see
        ``DecodeFleet._pick``); with none available (all converted away,
        breakers open), any healthy worker takes the request end-to-end —
        degraded, never down."""
        eng = self._pick(candidates=self.workers(PREFILL), prompt=prompt)
        if eng is None:
            eng = self._pick(prompt=prompt)
        if eng is None:
            raise EngineUnhealthy(
                "no healthy worker (all breakers open or draining)")
        return eng.submit(prompt, max_new_tokens, **kwargs)

    # -- the handoff path (runs on the prefill worker's loop thread) -------

    def _handoff(self, src, payload: HandoffPayload) -> None:
        """Move one prefilled request from ``src`` to a decode worker.
        Raises when nothing could take it — the publisher then resumes
        the request locally (rung 3 of the ladder)."""
        if self._journal is not None:
            # durable intent BEFORE the transfer: a crash from here on
            # leaves an unacked hof record that replay re-prefills from
            self._journal.log_handoff(
                payload.rid, payload.prompt, payload.mnt,
                payload.generated, payload.tenant, payload.cls,
                src=src.metrics.engine_label, dst=None,
                trace=(payload.trace.to_traceparent()
                       if payload.trace is not None else None))
        dst = self._pick(exclude=src, candidates=self.workers(DECODE))
        if dst is None:
            raise EngineUnhealthy(
                f"request {payload.rid}: no healthy decode-role worker "
                f"to adopt the handoff")
        t0_transfer = time.perf_counter()
        try:
            faults.inject(faults.DISAGG_HANDOFF, rid=payload.rid,
                          src=src.metrics.engine_label,
                          dst=dst.metrics.engine_label)
            if self.transport == "serialized":
                recv = HandoffPayload.from_bytes(payload.to_bytes())
                # the handle is process-local, never on the wire; the
                # trace context round-trips inside the CRC'd header
                recv.handle = payload.handle
                payload = recv
            dst.adopt_handoff(payload,
                              from_engine=src.metrics.engine_label)
        except Exception as e:
            # rung 2: reject the pages (torn transfer, corrupt payload,
            # dst refused) and re-prefill on a decode worker instead —
            # token-exact from prompt + generated, the rescue contract
            self.handoff_rejects_total += 1
            prof.inc_counter("serving.disagg.handoff_rejects")
            runlog.emit("handoff_rejected", rid=payload.rid,
                        error=repr(e), src=src.metrics.engine_label)
            ptlog.warning("handoff of %s rejected (%r); re-prefilling",
                          payload.rid, e)
            dst2 = self._pick(exclude=src, candidates=self.workers(DECODE))
            if dst2 is None:
                raise EngineUnhealthy(
                    f"request {payload.rid}: handoff rejected and no "
                    f"decode-role worker left to re-prefill on") from e
            dst2.adopt_rescue(payload.to_rescue_packet(),
                              from_engine=src.metrics.engine_label)
            self.handoff_reprefills_total += 1
            return
        if self._journal is not None:
            try:
                self._journal.log_handoff_ack(
                    payload.rid, dst.metrics.engine_label)
            except Exception as e:
                # adoption already happened; an unacked hof at worst
                # re-resumes an already-running request on replay
                ptlog.warning("handoff ack journaling failed: %r", e)
        self.handoffs_total += 1
        prof.inc_counter("serving.disagg.handoffs")
        if payload.trace is not None:
            tracing.record_span(
                "serving.handoff.transfer", t0_transfer,
                time.perf_counter(), parent=payload.trace,
                engine=src.metrics.engine_label,
                dst=dst.metrics.engine_label, rid=payload.rid,
                transport=self.transport)

    # -- drain-and-convert -------------------------------------------------

    def convert(self, engine, to_role: str,
                timeout: Optional[float] = None):
        """Drain-and-convert one worker to the other role at a safe
        boundary: exclude it from routing, gracefully drain it
        (``close`` runs every accepted request to completion — or, past
        the deadline, completes them with partial tokens rather than
        hanging), then swap in a factory-built replacement wearing the
        new role. The replacement re-warms via the persistent warmup
        manifest when built with ``warmup=False, prewarm=True``.
        ``engine`` is an engine object or its label. Returns the
        replacement engine."""
        enforce(self.factory is not None,
                "DisaggRouter.convert needs a factory(role) callable")
        enforce_in(to_role, _ROLES, "worker role")
        eng = engine
        if isinstance(engine, str):
            eng = next((e for e in self.engines
                        if e.metrics.engine_label == engine), None)
            enforce(eng is not None, f"no worker labeled {engine!r}")
        from_role = self._roles[id(eng)]
        if from_role == to_role and not eng.closed:
            return eng
        self._draining.add(id(eng))
        try:
            eng.close(timeout if timeout is not None
                      else self.convert_drain_timeout_s)
            new = self.factory(to_role)
            self._wire(new, to_role)
            with self._lock:
                i = self.engines.index(eng)
                self.engines[i] = new
            self._roles.pop(id(eng), None)
            self._roles[id(new)] = to_role
        finally:
            self._draining.discard(id(eng))
        self.conversions_total += 1
        prof.inc_counter("serving.disagg.conversions",
                         labels={"to_role": to_role})
        runlog.emit("worker_converted", engine=eng.metrics.engine_label,
                    from_role=from_role, to_role=to_role,
                    new_engine=new.metrics.engine_label)
        return new

    # -- introspection / shutdown ------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        snap = super().snapshot()
        for entry, eng in zip(snap["engines"], self.engines):
            entry["role"] = self._roles.get(id(eng), DECODE)
            entry["load"] = eng.load()
        snap.update({
            "transport": self.transport,
            "handoffs_total": self.handoffs_total,
            "handoff_rejects_total": self.handoff_rejects_total,
            "handoff_reprefills_total": self.handoff_reprefills_total,
            "conversions_total": self.conversions_total,
        })
        return snap

    def close(self, timeout: Optional[float] = None) -> List[str]:
        unjoined = super().close(timeout)
        if self._journal is not None and self._journal_owned:
            self._journal.close()
        return unjoined


# -- SLO-driven autoscaling ---------------------------------------------------

@dataclasses.dataclass
class AutoscalerConfig:
    """Policy knobs for :class:`Autoscaler`. The decision core
    (:meth:`Autoscaler.decide`) is pure over these — see its docstring
    for the rule table."""

    # the watch SLO whose burn rate stands for "interactive decode p99
    # is suffering" (e.g. one of serving_slos()); None = no SLO feed
    slo_name: Optional[str] = None
    # long-window burn rate above which decode needs capacity NOW
    burn_threshold: float = 1.0
    # prefill backlog (router.queue_depths()["prefill"]) treated as a
    # spike even without a detector flag
    spike_depth: float = 8.0
    # both roles at or below this depth = the fleet is idle
    idle_depth: float = 0.0
    # never convert below these per-role floors
    min_prefill: int = 1
    min_decode: int = 1
    # idle convergence target for the prefill side
    floor_prefill: int = 1
    # minimum seconds between conversions (drain + re-warm are not free)
    cooldown_s: float = 30.0


class Autoscaler:
    """Rebalances a :class:`DisaggRouter`'s prefill:decode ratio from
    measured load — the GDP/placement direction from the paper trail
    applied to serving roles, replacing fluid's hand-assigned
    trainer/pserver split.

    Rules, in priority order (:meth:`decide` is pure and unit-testable;
    :meth:`tick` feeds it live signals and applies the action):

    1. decode SLO burning (burn rate > ``burn_threshold``) and a prefill
       worker to spare → ``scale_decode`` (convert prefill → decode);
    2. prefill backlog spiking (EWMA anomaly or depth >
       ``spike_depth``) while the decode SLO is healthy and a decode
       worker to spare → ``scale_prefill``;
    3. fleet idle → converge the prefill side toward
       ``floor_prefill``.

    Conversions are rate-limited by ``cooldown_s``: a drain-and-convert
    costs a drain plus a manifest re-warm, so the scaler must not
    thrash on one noisy window."""

    SCALE_DECODE = "scale_decode"
    SCALE_PREFILL = "scale_prefill"

    def __init__(self, router: DisaggRouter,
                 config: Optional[AutoscalerConfig] = None,
                 slo_engine=None, detector=None,
                 clock=time.monotonic):
        self.router = router
        self.config = config or AutoscalerConfig()
        self.slo_engine = slo_engine
        if detector is None:
            from paddle_tpu.watch.detectors import EwmaDetector

            detector = EwmaDetector(alpha=0.2, z_threshold=6.0,
                                    min_samples=16)
        self.detector = detector
        self._clock = clock
        self._last_action_ts = -1e18
        self.actions_total: Dict[str, int] = {}

    def decide(
        self,
        *,
        burn_rate: Optional[float],
        prefill_depth: float,
        decode_depth: float,
        n_prefill: int,
        n_decode: int,
        queue_spike: bool = False,
    ) -> Optional[str]:
        """The pure decision core: signals in, action (or None) out.
        Never consults clocks, the router, or the SLO engine — tests
        drive every branch directly."""
        cfg = self.config
        burning = (burn_rate is not None
                   and burn_rate > cfg.burn_threshold)
        if burning and n_prefill > cfg.min_prefill:
            return self.SCALE_DECODE
        spike = queue_spike or prefill_depth > cfg.spike_depth
        if spike and not burning and n_decode > cfg.min_decode:
            return self.SCALE_PREFILL
        idle = (not burning and prefill_depth <= cfg.idle_depth
                and decode_depth <= cfg.idle_depth)
        if idle:
            if (n_prefill > cfg.floor_prefill
                    and n_prefill > cfg.min_prefill):
                return self.SCALE_DECODE
            if (n_prefill < cfg.floor_prefill
                    and n_decode > cfg.min_decode):
                return self.SCALE_PREFILL
        return None

    def _burn_rate(self) -> Optional[float]:
        if self.slo_engine is None or not self.config.slo_name:
            return None
        for st in self.slo_engine.status():
            if st.get("name") == self.config.slo_name:
                return st.get("burn_rate")
        return None

    def tick(self) -> Optional[str]:
        """Read live signals, decide, and apply (convert one worker).
        Returns the action taken, or None (healthy / cooling down / no
        donor)."""
        now = self._clock()
        if now - self._last_action_ts < self.config.cooldown_s:
            return None
        depths = self.router.queue_depths()
        pd, dd = depths[PREFILL], depths[DECODE]
        res = self.detector.observe("disagg.prefill_depth", pd)
        action = self.decide(
            burn_rate=self._burn_rate(), prefill_depth=pd,
            decode_depth=dd, n_prefill=self.router.n_prefill,
            n_decode=self.router.n_decode,
            queue_spike=bool(res is not None and res.flagged))
        if action is None:
            return None
        donor_role = (PREFILL if action == self.SCALE_DECODE else DECODE)
        to_role = DECODE if donor_role == PREFILL else PREFILL
        donors = [e for e in self.router.workers(donor_role)
                  if not e.closed]
        if not donors:
            return None
        donor = min(donors, key=lambda e: e.load())
        try:
            self.router.convert(donor, to_role)
        except Exception as e:
            ptlog.warning("autoscale %s failed: %r", action, e)
            return None
        self._last_action_ts = now
        self.actions_total[action] = self.actions_total.get(action, 0) + 1
        prof.inc_counter("serving.disagg.autoscale_actions",
                         labels={"action": action})
        runlog.emit("autoscale", action=action,
                    donor=donor.metrics.engine_label,
                    prefill_depth=pd, decode_depth=dd,
                    n_prefill=self.router.n_prefill,
                    n_decode=self.router.n_decode)
        return action

"""Program/parameter framework — the TPU-native replacement for Fluid's
Program/Block/Operator graph builder.

Reference: ``python/paddle/fluid/framework.py:207,496,923`` (Program/Block/
Operator/Variable/Parameter), ``python/paddle/fluid/layer_helper.py`` (param
creation plumbing), ``paddle/fluid/framework/program_desc.h:30`` (ProgramDesc).

Design: instead of appending OpDescs to a mutable program, a model is a plain
Python function that calls layer functions; :func:`build` wraps it into a
:class:`Model` with pure ``init``/``apply`` functions suitable for ``jax.jit``
/ ``pjit``. Parameters are named leaves in a flat dict pytree — the name
hierarchy (``name_scope``) mirrors Fluid's block/parameter naming so that
checkpoints and param-sharding rules can address parameters by name. Mutable
non-trainable state (e.g. BatchNorm moving stats, reference
``operators/batch_norm_op.cc``) lives in a separate "state" collection and is
threaded functionally: ``apply`` returns ``(output, new_state)``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Callable, Dict, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtypes as dtypes_mod
from paddle_tpu.core import unique_name
from paddle_tpu.core.enforce import EnforceError, enforce


class ParamAttr:
    """Per-parameter attributes (reference ``python/paddle/fluid/param_attr.py``):
    name, initializer, regularizer, trainable, learning-rate multiplier, plus a
    TPU-native addition: a logical sharding spec (tuple of mesh-axis names or
    None per dim) consumed by ``paddle_tpu.parallel``."""

    def __init__(
        self,
        name: Optional[str] = None,
        initializer=None,
        regularizer=None,
        trainable: bool = True,
        learning_rate: float = 1.0,
        sharding: Optional[Tuple[Optional[str], ...]] = None,
    ):
        self.name = name
        self.initializer = initializer
        self.regularizer = regularizer
        self.trainable = trainable
        self.learning_rate = learning_rate
        self.sharding = sharding

    @staticmethod
    def to_attr(attr: Union["ParamAttr", str, bool, None]) -> "ParamAttr":
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return ParamAttr(trainable=False)
        return ParamAttr()


class WeightNormParamAttr(ParamAttr):
    """Weight normalization (Salimans & Kingma; reference
    ``param_attr.py WeightNormParamAttr``): the effective weight is
    ``w = g * v / ||v||`` with direction ``v`` and per-output-slice
    magnitude ``g`` as the trainable parameters. ``dim`` is the axis kept
    by the norm (the output dim; None = one global scalar g).

    Divergence from the reference noted: ``g`` initializes to 1 (so the
    initial effective weight is the normalized direction) rather than to
    ``||v_init||`` — the reparameterized training dynamics, which are the
    point of weight norm, are identical."""

    def __init__(self, dim: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


@dataclasses.dataclass
class ParamInfo:
    """Static metadata recorded at creation time for each parameter."""

    name: str
    shape: Tuple[int, ...]
    dtype: np.dtype
    trainable: bool = True
    learning_rate: float = 1.0
    regularizer: Any = None
    sharding: Optional[Tuple[Optional[str], ...]] = None


class Variables(NamedTuple):
    """The full variable set of a model: trainable params + mutable state."""

    params: Dict[str, jax.Array]
    state: Dict[str, jax.Array]


class _Frame:
    def __init__(self, mode: str, params, state, rng, is_train: bool):
        assert mode in ("init", "apply")
        self.mode = mode
        self.params: Dict[str, jax.Array] = params
        self.state: Dict[str, jax.Array] = state
        self.new_state: Dict[str, jax.Array] = {}
        self.param_info: Dict[str, ParamInfo] = {}
        self.rng = rng
        self.rng_counter = 0
        self.is_train = is_train
        self.name_stack: list[str] = []
        self.generator = unique_name.Generator()
        # analysis hooks: params actually read this trace (create_parameter /
        # gather_layer_params) and cross-scope update_state fallbacks — the
        # model linter reads these off Model.apply's last trace
        self.param_reads: set = set()
        self.cross_scope_updates: set = set()


_tls = threading.local()


def _current_frame() -> _Frame:
    frame = getattr(_tls, "frame", None)
    if frame is None:
        raise EnforceError(
            "no active framework frame: layer functions that create parameters "
            "must run inside Model.init/Model.apply (wrap your network with "
            "paddle_tpu.build)"
        )
    return frame


def in_frame() -> bool:
    return getattr(_tls, "frame", None) is not None


def is_training() -> bool:
    """Whether the current trace is a training-mode apply (dropout/BN switch)."""
    return _current_frame().is_train


def is_initializing() -> bool:
    """Whether the current trace is Model.init (parameter creation).
    Transform wrappers that re-trace their body (jax.checkpoint) must be
    skipped here — param initializer outputs created inside the inner trace
    would escape it as leaked tracers."""
    return _current_frame().mode == "init"


@contextlib.contextmanager
def overlay_frame(params: Dict[str, jax.Array], rng=None):
    """Run the body under a FRESH apply-mode frame backed by ``params``.

    The scan-over-layers mechanism (``models/transformer_lm.py``
    ``_scan_lm_blocks``): the caller stacks the per-layer parameter arrays,
    and inside ``lax.scan`` the block body traces once against
    template-named entries of this overlay. Requires an enclosing frame
    (inherits its train flag); state-creating layers (BN moving stats) are
    not supported inside an overlay — the overlay's new_state is asserted
    empty on exit."""
    prev = _current_frame()
    frame = _Frame("apply", params, {}, rng, prev.is_train)
    _tls.frame = frame
    try:
        yield frame
        # checked on CLEAN exit only: raising from a finally would replace
        # an in-flight body exception with this secondary one
        if frame.new_state:
            raise EnforceError(
                "overlay_frame body produced mutable state "
                f"({sorted(frame.new_state)}); stateful layers cannot run "
                "under scan-over-layers"
            )
    finally:
        _tls.frame = prev


def stack_layer_params(params: Dict[str, jax.Array], n_layers: int, name_of,
                       prefix: str = ""):
    """Frame-independent core of layer stacking: collect the per-layer
    parameter arrays of ``n_layers`` structurally-identical layers from a
    flat ``params`` dict into {suffix: [L, ...]}, validating that every
    layer has layer 0's full suffix set (structured error instead of a
    bare KeyError on a cfg/checkpoint layer-count mismatch)."""
    # single pass over params: bucket every key's suffix set under its
    # layer-name head (O(len(params)), not O(n_layers * len(params)))
    names = [name_of(i) for i in range(n_layers)]
    name_set = set(names)
    multi_seg = [n for n in names if "/" in n]  # rare: scoped layer names
    plen = len(prefix)
    per_layer: Dict[str, set] = {}
    for k in params:
        if prefix and not k.startswith(prefix):
            continue
        head, sep, suf = k[plen:].partition("/")
        if sep and head in name_set:
            per_layer.setdefault(head, set()).add(suf)
        elif sep and multi_seg:
            # fall back for name_of values containing '/' (e.g.
            # 'blocks/layer_0'): match the longest known name prefix
            rest = k[plen:]
            for nm in multi_seg:
                if rest.startswith(nm + "/"):
                    per_layer.setdefault(nm, set()).add(rest[len(nm) + 1:])
                    break
    base = per_layer.get(names[0], set())
    if not base:
        raise EnforceError(f"no {prefix}{names[0]}/* params found")
    suffixes = sorted(base)
    for i, nm in enumerate(names):
        got = per_layer.get(nm, set())
        missing = sorted(base - got)
        if missing:
            raise EnforceError(
                f"parameter '{prefix}{nm}/{missing[0]}' not found in "
                f"provided params; expected {n_layers} identical layers "
                "— model structure must match between init and apply"
            )
        # ...and the reverse: a layer carrying suffixes layer 0 lacks (e.g.
        # a MoE checkpoint restored under a dense cfg) must be reported, not
        # silently ignored
        extra = sorted(got - base)
        if extra:
            raise EnforceError(
                f"layer {i} has parameter suffixes not present in layer 0: "
                f"{extra}; all {n_layers} layers must be structurally "
                "identical to stack"
            )
    return {
        s: jnp.stack(
            [params[f"{prefix}{name_of(i)}/{s}"] for i in range(n_layers)]
        )
        for s in suffixes
    }


def gather_layer_params(n_layers: int, name_of):
    """Stack the current frame's per-layer params (the shared front half of
    scan-over-layers and pipeline stacking) — see :func:`stack_layer_params`."""
    frame = _current_frame()
    prefix = "/".join(frame.name_stack)
    prefix = prefix + "/" if prefix else ""
    stacked = stack_layer_params(frame.params, n_layers, name_of, prefix)
    # scanned layers read params without create_parameter; record the reads
    # so model_lint's unused-param check sees through scan-over-layers
    for i in range(n_layers):
        for s in stacked:
            frame.param_reads.add(f"{prefix}{name_of(i)}/{s}")
    return stacked


def scan_layer_stack(x, n_layers: int, name_of, template: str, body,
                     remat: bool = False, with_aux: bool = False):
    """Run ``n_layers`` identical layers as ONE ``lax.scan`` over stacked
    per-layer params (the canonical TPU depth pattern: the body appears
    once in the traced program, so per-instance kernel compilation and
    program size stay O(1) in depth).

    ``name_of(i)`` returns the unrolled layer scope name (``"layer_3"``);
    params under ``<scope>/<name_of(0)>/...`` must exist for every layer
    with identical suffix sets/shapes. ``body(x, scope_name) -> x`` must be
    layer-index-agnostic; it re-traces once under an :func:`overlay_frame`
    that maps ``<template>/...`` to the scanned parameter slice.
    Loop-invariant tensors ride as closure constants. With ``remat`` the
    body runs under ``jax.checkpoint`` (activation memory O(one layer)).
    Dropout draws per-layer pre-split keys, so the stream differs from the
    unrolled loop's frame sequence (loss statistics unaffected).

    ``with_aux``: body returns ``(x, aux)`` (e.g. MoE router load-balance
    loss); the call then returns ``(x, summed_aux)``.
    """
    frame = _current_frame()
    xs = {"p": gather_layer_params(n_layers, name_of)}
    if frame.rng is not None:
        xs["k"] = jax.random.split(next_rng_key(), n_layers)

    def scan_body(carry, sl):
        overlay = {f"{template}/{s}": v for s, v in sl["p"].items()}
        with overlay_frame(overlay, rng=sl.get("k")):
            out = body(carry, template)
        if with_aux:
            return out[0], out[1]
        return out, None

    call = jax.checkpoint(scan_body) if remat else scan_body
    x, ys = jax.lax.scan(call, x, xs)
    if with_aux:
        return x, jnp.sum(ys)
    return x


@contextlib.contextmanager
def name_scope(prefix: str):
    """Hierarchical name scope (fluid.name_scope parity, ``framework.py`` tail).
    Scope names are uniquified per frame so loops create block_0, block_1, ..."""
    frame = _current_frame()
    scoped = frame.generator.generate("/".join(frame.name_stack + [prefix]))
    leaf = scoped.rsplit("/", 1)[-1] if "/" in scoped else scoped
    frame.name_stack.append(leaf)
    try:
        yield
    finally:
        frame.name_stack.pop()


def _full_name(frame: _Frame, key: str, given: Optional[str]) -> str:
    if given is not None:
        base = "/".join(frame.name_stack + [given])
        return base
    return frame.generator.generate("/".join(frame.name_stack + [key]))


def _weight_norm_parameter(shape, dtype, name, attr: "WeightNormParamAttr", default_initializer):
    """Create the (v, g) pair behind a WeightNormParamAttr and return the
    effective weight ``g * v / ||v||`` (norm over all axes except ``dim``)."""
    from paddle_tpu import initializer as init_mod

    base = attr.name or name or "param"
    v_attr = ParamAttr(
        initializer=attr.initializer, regularizer=attr.regularizer,
        trainable=attr.trainable, learning_rate=attr.learning_rate,
        sharding=attr.sharding,
    )
    v = create_parameter(shape, dtype, name=f"{base}_v", attr=v_attr,
                         default_initializer=default_initializer)
    ndim = len(shape)
    if attr.dim is None:
        g_shape: Tuple[int, ...] = ()
        axes = tuple(range(ndim))
        bshape = (1,) * ndim
    else:
        if not (-ndim <= attr.dim < ndim):
            raise EnforceError(
                f"WeightNormParamAttr dim={attr.dim} out of range for a "
                f"rank-{ndim} parameter"
            )
        dim = attr.dim % ndim
        g_shape = (shape[dim],)
        axes = tuple(a for a in range(ndim) if a != dim)
        bshape = tuple(shape[d] if d == dim else 1 for d in range(ndim))
    g = create_parameter(
        g_shape, dtype, name=f"{base}_g",
        attr=ParamAttr(trainable=attr.trainable, learning_rate=attr.learning_rate),
        default_initializer=init_mod.Constant(1.0),
    )
    norm = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes, keepdims=True) + 1e-12)
    w = jnp.reshape(g.astype(jnp.float32), bshape) * v.astype(jnp.float32) / norm
    return w.astype(v.dtype)


def next_rng_key() -> jax.Array:
    """Fold a fresh PRNG key off the frame key (dropout, random ops)."""
    frame = _current_frame()
    if frame.rng is None:
        raise EnforceError(
            "this program uses randomness (dropout/random ops): pass rng= to "
            "Model.init/Model.apply"
        )
    frame.rng_counter += 1
    return jax.random.fold_in(frame.rng, frame.rng_counter)


def create_parameter(
    shape: Sequence[int],
    dtype="float32",
    name: Optional[str] = None,
    attr: Union[ParamAttr, str, None] = None,
    default_initializer=None,
) -> jax.Array:
    """Create (init mode) or fetch (apply mode) a named parameter.

    Mirrors LayerHelper.create_parameter (reference
    ``python/paddle/fluid/layer_helper.py``): resolves ParamAttr, applies the
    default initializer (Xavier for weights unless overridden), records
    regularizer/lr-mult metadata for the optimizer.
    """
    from paddle_tpu import initializer as init_mod

    frame = _current_frame()
    attr = ParamAttr.to_attr(attr)
    if isinstance(attr, WeightNormParamAttr):
        return _weight_norm_parameter(shape, dtype, name, attr, default_initializer)
    np_dtype = dtypes_mod.convert(dtype)
    full = _full_name(frame, "param", attr.name or name)
    shape = tuple(int(s) for s in shape)

    info = ParamInfo(
        name=full,
        shape=shape,
        dtype=np_dtype,
        trainable=attr.trainable,
        learning_rate=attr.learning_rate,
        regularizer=attr.regularizer,
        sharding=attr.sharding,
    )
    frame.param_info[full] = info

    if frame.mode == "init":
        if full in frame.params:
            raise EnforceError(f"duplicate parameter name {full!r}")
        initializer = attr.initializer or default_initializer or init_mod.Xavier()
        frame.rng_counter += 1
        if frame.rng is not None:
            key = jax.random.fold_in(frame.rng, frame.rng_counter)
        else:
            # no rng given: still break symmetry between parameters by
            # folding the creation counter into the flag-seeded key
            from paddle_tpu.core import config as _cfg

            key = jax.random.fold_in(
                jax.random.PRNGKey(_cfg.flags().seed), frame.rng_counter
            )
        frame.params[full] = initializer(key, shape, np_dtype)
        return frame.params[full]
    if full not in frame.params:
        raise EnforceError(
            f"parameter {full!r} not found in provided params; model structure "
            "must match between init and apply"
        )
    frame.param_reads.add(full)
    value = frame.params[full]
    if tuple(value.shape) != shape:
        raise EnforceError(
            f"parameter {full!r} shape mismatch: created with {shape}, got {tuple(value.shape)}"
        )
    return value


def create_state(
    name: str,
    shape: Sequence[int],
    dtype="float32",
    init: Optional[Callable[[Tuple[int, ...], Any], jax.Array]] = None,
) -> jax.Array:
    """Create/fetch a mutable (non-trainable) state entry, e.g. BN moving mean."""
    frame = _current_frame()
    np_dtype = dtypes_mod.convert(dtype)
    full = _full_name(frame, name, name)
    shape = tuple(int(s) for s in shape)
    if frame.mode == "init":
        value = (init or (lambda s, d: jnp.zeros(s, d)))(shape, np_dtype)
        frame.state[full] = value
        return value
    if full not in frame.state:
        raise EnforceError(f"state {full!r} missing from provided state dict")
    return frame.new_state.get(full, frame.state[full])


def update_state(name: str, value) -> None:
    """Record a new value for a state entry, addressed by the same local name
    (within the same name_scope) it was created with.

    A bare name that misses in the current scope falls back to the root
    name — which can silently update a DIFFERENT layer's state when names
    collide across scopes. The fallback still works (compat), but it now
    emits a once-per-key warning and is recorded on the frame so
    ``paddle_tpu.analysis.model_lint`` surfaces it as a diagnostic."""
    frame = _current_frame()
    scoped = "/".join(frame.name_stack + [name])
    full = scoped if (scoped in frame.state or scoped in frame.new_state) else name
    if full is name and scoped != name and name in frame.state:
        from paddle_tpu.core import logging as ptlog

        frame.cross_scope_updates.add((scoped, name))
        ptlog.warn_once(
            ("update_state-cross-scope", scoped),
            "update_state(%r): no state entry at scope %r; falling back to the "
            "root-level name %r — a cross-scope state update resolves by "
            "accident when names collide. Address state from within the "
            "name_scope that created it.",
            name, scoped, name,
        )
    if frame.mode == "init":
        if full not in frame.state:
            raise EnforceError(f"unknown state {name!r} (create_state first)")
        return  # init keeps the initial value
    if full not in frame.state:
        raise EnforceError(f"unknown state {name!r} (create_state first)")
    frame.new_state[full] = value


class Model:
    """A built program: pure ``init`` and ``apply`` (jit/pjit-compatible).

    Replaces the (ProgramDesc → Executor) pair: ``apply`` traced under
    ``jax.jit`` becomes the single compiled XLA executable that the reference
    ran as a per-op interpreter loop (``framework/executor.cc:354``).
    """

    def __init__(self, fn: Callable, name: Optional[str] = None):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "model")
        self.param_info: Dict[str, ParamInfo] = {}
        self._last_param_info: Dict[str, ParamInfo] = {}
        self._last_param_reads: frozenset = frozenset()
        self._last_state_updates: frozenset = frozenset()
        self._last_cross_scope_updates: frozenset = frozenset()

    def init(self, rng: Optional[jax.Array] = None, *args, **kwargs) -> Variables:
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        frame = _Frame("init", params={}, state={}, rng=rng, is_train=True)
        prev = getattr(_tls, "frame", None)
        _tls.frame = frame
        try:
            with unique_name.guard(frame.generator):
                self._fn(*args, **kwargs)
        finally:
            _tls.frame = prev
        self.param_info = frame.param_info
        return Variables(params=frame.params, state=frame.state)

    def apply(
        self,
        variables: Union[Variables, Dict[str, jax.Array], Tuple],
        *args,
        rng: Optional[jax.Array] = None,
        is_train: bool = False,
        **kwargs,
    ):
        """Run the program. Returns ``(output, new_state)``."""
        if isinstance(variables, Variables):
            params, state = variables.params, variables.state
        elif isinstance(variables, tuple) and len(variables) == 2:
            params, state = variables
        else:
            params, state = variables, {}
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        frame = _Frame("apply", params=params, state=state, rng=rng, is_train=is_train)
        prev = getattr(_tls, "frame", None)
        _tls.frame = frame
        try:
            with unique_name.guard(frame.generator):
                out = self._fn(*args, **kwargs)
        finally:
            _tls.frame = prev
        if not self.param_info:
            self.param_info = frame.param_info
        # trace introspection for paddle_tpu.analysis.model_lint: what the
        # last apply actually touched (python side effects survive tracing,
        # so these are populated even under jax.eval_shape)
        self._last_param_info = frame.param_info
        self._last_param_reads = frozenset(frame.param_reads)
        self._last_state_updates = frozenset(frame.new_state)
        self._last_cross_scope_updates = frozenset(frame.cross_scope_updates)
        new_state = dict(state)
        new_state.update(frame.new_state)
        return out, new_state


def build(fn: Callable, name: Optional[str] = None) -> Model:
    """Wrap a layer-calling function into a Model (the transform)."""
    return Model(fn, name=name)

"""Parameter initializers.

Reference: ``python/paddle/fluid/initializer.py`` (Constant/Uniform/Normal/
TruncatedNormal/Xavier/MSRA/Bilinear, implemented there as startup-program
init *ops*). TPU-native: pure functions ``(key, shape, dtype) -> array``
evaluated inside ``Model.init`` — the whole init is one compiled program
rather than a startup ProgramDesc.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _fan_in_out(shape: Tuple[int, ...]) -> Tuple[int, int]:
    # Convention matches the reference (initializer.py _compute_fans): for
    # conv weights [H, W, Cin, Cout] (our NHWC layout) receptive field
    # multiplies both fans; for matrices [in, out].
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


class Initializer:
    def __call__(self, key: jax.Array, shape: Sequence[int], dtype) -> jax.Array:
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=jnp.float32, minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def __call__(self, key, shape, dtype):
        return (self.loc + self.scale * jax.random.normal(key, shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def __call__(self, key, shape, dtype):
        x = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.float32)
        return (self.loc + self.scale * x).astype(dtype)


class Xavier(Initializer):
    """Glorot init (reference XavierInitializer): uniform or normal scaled by
    fan_in+fan_out."""

    def __init__(self, uniform: bool = True, fan_in: Optional[int] = None, fan_out: Optional[int] = None):
        self.uniform = uniform
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, key, shape, dtype):
        fin, fout = _fan_in_out(tuple(shape))
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        if self.uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            x = jax.random.uniform(key, shape, dtype=jnp.float32, minval=-limit, maxval=limit)
        else:
            std = math.sqrt(2.0 / (fin + fout))
            x = std * jax.random.normal(key, shape, dtype=jnp.float32)
        return x.astype(dtype)


class MSRA(Initializer):
    """He init (reference MSRAInitializer), fan_in scaled."""

    def __init__(self, uniform: bool = True, fan_in: Optional[int] = None):
        self.uniform = uniform
        self.fan_in = fan_in

    def __call__(self, key, shape, dtype):
        fin, _ = _fan_in_out(tuple(shape))
        fin = self.fan_in or fin
        if self.uniform:
            limit = math.sqrt(6.0 / fin)
            x = jax.random.uniform(key, shape, dtype=jnp.float32, minval=-limit, maxval=limit)
        else:
            std = math.sqrt(2.0 / fin)
            x = std * jax.random.normal(key, shape, dtype=jnp.float32)
        return x.astype(dtype)


class Bilinear(Initializer):
    """Bilinear upsampling kernel for conv_transpose (reference
    BilinearInitializer) — weight shape [H, W, Cin, Cout] NHWC."""

    def __call__(self, key, shape, dtype):
        shape = tuple(shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D conv weight")
        h, w = shape[0], shape[1]
        f = math.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        grid_h = np.arange(h)
        grid_w = np.arange(w)
        filt = (1 - np.abs(grid_h / f - c))[:, None] * (1 - np.abs(grid_w / f - c))[None, :]
        weight = np.zeros(shape, dtype=np.float32)
        for i in range(min(shape[2], shape[3])):
            weight[:, :, i, i] = filt
        return jnp.asarray(weight, dtype=dtype)


# Fluid-style aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = Xavier
MSRAInitializer = MSRA
BilinearInitializer = Bilinear


# -- init placement hints (reference initializer.py:32-66) -------------------
_force_init_on_cpu = False


def force_init_on_cpu() -> bool:
    """Reference ``initializer.py:32``: query the init-on-CPU flag. On TPU
    the flag is a hint only — initializer MATH is identical everywhere and
    XLA owns placement; jit-traced init folds into the compiled program
    regardless of host-side device context."""
    return _force_init_on_cpu


import contextlib as _contextlib


@_contextlib.contextmanager
def init_on_cpu():
    """Reference ``initializer.py:49``: run initializers under the CPU-init
    hint (see :func:`force_init_on_cpu` for TPU semantics)."""
    global _force_init_on_cpu
    prev = _force_init_on_cpu
    _force_init_on_cpu = True
    try:
        yield
    finally:
        _force_init_on_cpu = prev

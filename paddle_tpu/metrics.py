"""Python-side metric accumulators.

Reference: ``python/paddle/fluid/metrics.py`` (MetricBase/Accuracy/
CompositeMetric/ChunkEvaluator/EditDistance/Auc) — host-side accumulators fed
by fetched per-batch values; the per-batch values themselves come from metric
ops (``operators/accuracy_op.cc``, ``auc_op.cc``), which here are the
functional ops in ``paddle_tpu.ops.nn``.
"""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name: str = ""):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """Weighted running accuracy (reference metrics.Accuracy)."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("no updates to Accuracy metric")
        return self.value / self.weight


class Average(MetricBase):
    """Running mean of a scalar stream (e.g. loss); reference average.py
    WeightedAverage."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0.0

    def update(self, value, weight=1.0):
        self.total += float(np.sum(value)) * float(weight)
        self.count += float(weight)

    def eval(self):
        return self.total / max(self.count, 1e-12)


class Precision(MetricBase):
    """Binary precision = tp / (tp + fp) over accumulated batches
    (reference ``metrics.py:208`` Precision)."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        pred_pos = np.rint(preds).astype(np.int64) == 1
        label_pos = labels.astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & label_pos))
        self.fp += int(np.sum(pred_pos & ~label_pos))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    """Binary recall = tp / (tp + fn) over accumulated batches
    (reference ``metrics.py:255`` Recall)."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        pred_pos = np.rint(preds).astype(np.int64) == 1
        label_pos = labels.astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & label_pos))
        self.fn += int(np.sum(~pred_pos & label_pos))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class ChunkEvaluator(MetricBase):
    """Accumulates ``ops.chunk_eval`` per-batch counts into pass-level
    precision/recall/F1 (reference ``metrics.py:355`` ChunkEvaluator)."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.sum(num_infer_chunks))
        self.num_label_chunks += int(np.sum(num_label_chunks))
        self.num_correct_chunks += int(np.sum(num_correct_chunks))

    def eval(self):
        precision = (
            self.num_correct_chunks / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            self.num_correct_chunks / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks
            else 0.0
        )
        return precision, recall, f1


class DetectionMAP(MetricBase):
    """Weighted running mean of per-batch ``ops.detection_map`` values
    (reference ``metrics.py:481`` DetectionMAP)."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.sum(value))
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("no updates to DetectionMAP metric")
        return self.value / self.weight


class EditDistance(MetricBase):
    def __init__(self, name: str = ""):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        avg = self.total_distance / max(self.seq_num, 1)
        err_rate = self.instance_error / max(self.seq_num, 1)
        return avg, err_rate


class Auc(MetricBase):
    """Streaming ROC-AUC by thresholded confusion counts (reference
    ``auc_op.cc`` + metrics.Auc)."""

    def __init__(self, name: str = "", num_thresholds: int = 4095):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self.tp = np.zeros(self.num_thresholds + 1, np.int64)
        self.fp = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1).astype(bool)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip((pos_prob * self.num_thresholds).astype(np.int64), 0, self.num_thresholds)
        # vectorized: tp[t] = #{i : idx_i >= t, label_i} = reversed-cumsum of
        # per-threshold counts
        pos_counts = np.bincount(idx[labels], minlength=self.num_thresholds + 1)
        neg_counts = np.bincount(idx[~labels], minlength=self.num_thresholds + 1)
        self.tp += np.cumsum(pos_counts[::-1])[::-1]
        self.fp += np.cumsum(neg_counts[::-1])[::-1]

    def eval(self):
        total_pos = self.tp[0]
        total_neg = self.fp[0]
        tpr = self.tp / max(total_pos, 1)
        fpr = self.fp / max(total_neg, 1)
        # integrate over descending thresholds
        trapz = getattr(np, "trapezoid", None) or np.trapz
        return float(abs(trapz(tpr, fpr)))


class CompositeMetric(MetricBase):
    def __init__(self, name: str = ""):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric: MetricBase):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args):
        for m, a in zip(self._metrics, args):
            m.update(*a if isinstance(a, tuple) else (a,))

    def eval(self):
        return [m.eval() for m in self._metrics]

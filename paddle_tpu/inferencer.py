"""High-level Inferencer (reference ``python/paddle/fluid/inferencer.py``:
Inferencer(infer_func, param_path, place) loads trained params and serves
``infer(feed)`` through a prepared executor).

TPU-native: the infer function is built into a :class:`Model`, params load
from a ``save_params`` directory, and inference dispatches through the
shared :class:`paddle_tpu.executor.Executor` compile cache — the same
cache the serving engine's AOT-warmed buckets live in, so a one-shot
``infer`` and engine traffic never compile the same program twice. For
sustained concurrent traffic, :meth:`as_engine` upgrades this one-shot
client into a :class:`paddle_tpu.serving.ServingEngine`."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax

from paddle_tpu import io as io_mod
from paddle_tpu.core.enforce import enforce
from paddle_tpu.executor import Executor
from paddle_tpu.framework import Model, Variables, build

__all__ = ["Inferencer"]


class Inferencer:
    def __init__(
        self,
        infer_func: Callable,
        param_path: str,
        place=None,
        feed_order: Optional[Sequence[Any]] = None,
    ):
        """``feed_order``: optional FeedSpec list (or slot-name list) fixing
        the positional order dict feeds are unpacked in — the reference's
        feed-target names. Without it, dict feeds fall back to insertion
        order."""
        model = infer_func() if _is_builder(infer_func) else infer_func
        self.model = model if isinstance(model, Model) else build(model)
        self.variables = io_mod.load_params(param_path)
        self.place = place
        self.feed_order = (
            [getattr(s, "name", s) for s in feed_order] if feed_order else None
        )
        self._exe = Executor(place)

        def _fwd(variables, *args):
            out, _ = self.model.apply(variables, *args, is_train=False)
            return out

        self._fwd = _fwd

    def _ordered(self, feed: dict) -> list:
        if self.feed_order is None:
            return list(feed.values())  # legacy: raw insertion order
        missing = [n for n in self.feed_order if n not in feed]
        enforce(not missing, f"feed missing slots {missing}")
        return [feed[n] for n in self.feed_order]

    def infer(self, inputs):
        """Run inference on positional inputs (list/tuple, or a {name: value}
        dict — unpacked in ``feed_order`` when given, else insertion
        order). Batched arrays pass straight through."""
        if isinstance(inputs, dict):
            inputs = self._ordered(inputs)
        enforce(isinstance(inputs, (list, tuple)), "inputs must be a sequence or dict")
        compiled = self._exe.prepare(self._fwd, key=("inferencer", id(self)))
        return compiled(self.variables, *[jax.numpy.asarray(a) for a in inputs])

    @property
    def executor(self) -> Executor:
        """The compile-cache-owning executor (shared with serving warmup
        when an engine is built from this inferencer's model)."""
        return self._exe

    def as_engine(self, feed_specs, config=None):
        """Upgrade to a dynamically-batched serving engine (the Inferencer
        is the one-shot client; the engine is the production path)."""
        from paddle_tpu.serving import ServingEngine

        return ServingEngine(
            self.model,
            self.variables,
            feed_specs,
            config=config,
            place=self.place,
        )


def _is_builder(fn: Callable) -> bool:
    """Reference infer_funcs take no args and build the net via layer calls;
    plain net fns take the input tensors. Distinguish by arity."""
    import inspect

    try:
        return len(inspect.signature(fn).parameters) == 0
    except (TypeError, ValueError):
        return False

"""High-level Inferencer (reference ``python/paddle/fluid/inferencer.py``:
Inferencer(infer_func, param_path, place) loads trained params and serves
``infer(feed)`` through a prepared executor).

TPU-native: the infer function is built into a :class:`Model`, params load
from a ``save_params`` directory, and inference is one jitted apply."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax

from paddle_tpu import io as io_mod
from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import Model, Variables, build

__all__ = ["Inferencer"]


class Inferencer:
    def __init__(self, infer_func: Callable, param_path: str, place=None):
        model = infer_func() if _is_builder(infer_func) else infer_func
        self.model = model if isinstance(model, Model) else build(model)
        self.variables = io_mod.load_params(param_path)
        self.place = place
        self._jitted = None

    def infer(self, inputs: Sequence[Any]):
        """Run inference on positional inputs (list/tuple, or the reference's
        {name: value} dict — values are taken in insertion order)."""
        if isinstance(inputs, dict):
            inputs = list(inputs.values())
        enforce(isinstance(inputs, (list, tuple)), "inputs must be a sequence or dict")
        if self._jitted is None:
            from paddle_tpu.core import config as _cfg

            _cfg.apply_compile_cache()

            def fwd(variables, *args):
                out, _ = self.model.apply(variables, *args, is_train=False)
                return out

            self._jitted = jax.jit(fwd)
        return self._jitted(self.variables, *[jax.numpy.asarray(a) for a in inputs])


def _is_builder(fn: Callable) -> bool:
    """Reference infer_funcs take no args and build the net via layer calls;
    plain net fns take the input tensors. Distinguish by arity."""
    import inspect

    try:
        return len(inspect.signature(fn).parameters) == 0
    except (TypeError, ValueError):
        return False

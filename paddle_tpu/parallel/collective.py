"""Collective communication primitives.

Reference: the op-handle collectives —
``details/all_reduce_op_handle.cc:48`` (grouped ncclAllReduce),
``details/reduce_op_handle.cc`` (reduce-to-one-device),
``details/broadcast_op_handle.cc`` (ncclBcast),
``operators/nccl/nccl_op.cc`` raw collective ops.

TPU-native: thin, named wrappers over lax collectives. These only have
meaning inside shard_map/pmap-style per-device code; under plain pjit with
NamedSharding annotations XLA inserts the equivalent collectives itself —
prefer that. Provided for explicit SPMD kernels (ring attention, custom
reductions) and API parity.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax


def all_reduce(x, axis_name: str, op: str = "sum"):
    """AllReduceOpHandle parity."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def broadcast(x, axis_name: str, root: int = 0):
    """BroadcastOpHandle parity: every member takes root's value."""
    idx = lax.axis_index(axis_name)
    masked = jax.numpy.where(idx == root, x, jax.numpy.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, axis_name: str, perm):
    """Ring/shift primitive (basis for ring attention / pipeline bubbles)."""
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return lax.axis_size(axis_name)


# -- KV-page transfer primitives (serving.disagg) ---------------------------
#
# Disaggregated prefill/decode handoff moves one request's KV pages from a
# prefill worker's page arrays into a decode worker's. When both workers
# live in one process these run jitted on-device (a gather/scatter per
# page — no host round-trip); across processes the gathered pages are
# serialized with per-page CRCs (serving.disagg.HandoffPayload). Page
# arrays are ``[L, num_pages, H_kv, page_size, dh]``; one page is the
# fixed-shape ``[L, H_kv, page_size, dh]`` slice, so both ops compile
# exactly once per engine geometry.

def gather_kv_page(pages, page_id):
    """Extract one physical page from a paged KV array (device-side)."""
    return pages[:, page_id]


def scatter_kv_page(pages, page_id, page):
    """Implant one page payload at ``page_id`` in a paged KV array
    (device-side; the functional update donates into the engine's
    running page arrays)."""
    return pages.at[:, page_id].set(page)

"""Parallelism: device meshes, shardings, collectives, data/model parallel.

TPU-native replacement for the reference multi-device stack —
``framework/parallel_executor.cc:134`` (ParallelExecutor),
``framework/details/multi_devices_graph_pass.cc:286`` (SSA graph builder),
``platform/nccl_helper.h:81`` (NCCLContextMap) and the gen_nccl_id gRPC
bootstrap (``operators/gen_nccl_id_op.cc:31``).

Here parallelism is declarative: a ``jax.sharding.Mesh`` over ICI/DCN, param/
batch shardings as NamedSharding annotations, and XLA-compiled collectives
(psum/all_gather/reduce_scatter/ppermute) instead of scheduled op handles.
Multi-host bootstrap is ``jax.distributed.initialize`` (the JAX coordination
service) instead of ncclUniqueId exchange over gRPC.
"""

from paddle_tpu.parallel.mesh import (
    make_mesh,
    default_mesh,
    initialize_distributed,
    partition_devices,
    tp_submesh,
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    PIPE_AXIS,
    EXPERT_AXIS,
    TP_AXIS,
)
from paddle_tpu.parallel import collective
from paddle_tpu.parallel.sharding import (
    degrade_spec,
    param_shardings,
    replicated,
    batch_sharding,
    shard_variables,
    spec_for,
)
from paddle_tpu.parallel.data_parallel import DataParallel
from paddle_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    split_microbatches,
)
from paddle_tpu.parallel.moe import moe_ffn, switch_gate, top2_gate, MoEOutput

__all__ = [
    "make_mesh",
    "default_mesh",
    "initialize_distributed",
    "partition_devices",
    "tp_submesh",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "PIPE_AXIS",
    "EXPERT_AXIS",
    "TP_AXIS",
    "collective",
    "degrade_spec",
    "param_shardings",
    "replicated",
    "batch_sharding",
    "shard_variables",
    "spec_for",
    "DataParallel",
    "pipeline_apply",
    "stack_stage_params",
    "split_microbatches",
    "moe_ffn",
    "switch_gate",
    "top2_gate",
    "MoEOutput",
]

"""Mixture-of-Experts with expert parallelism over an ``expert`` mesh axis.

No reference counterpart (SURVEY.md §2.4: parallelism in the reference is
DP + parameter server only) — this is a post-parity TPU extension using the
GShard/Switch dense-dispatch pattern: top-k gating builds a
[tokens, experts, capacity] dispatch tensor, expert FFNs run batched with
their parameters sharded along the ``expert`` axis, and the two dispatch
einsums become all_to_all exchanges when compiled over the mesh.

Everything is fixed-shape: per-expert token capacity bounds the routed
tokens; overflow tokens are dropped (standard Switch behavior) and the
auxiliary load-balancing loss pushes the router toward uniform occupancy.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import ParamAttr, create_parameter, name_scope
from paddle_tpu.parallel import mesh as mesh_mod

__all__ = ["switch_gate", "top2_gate", "moe_ffn", "MoEOutput"]


class MoEOutput(NamedTuple):
    output: jax.Array
    aux_loss: jax.Array  # load-balancing loss (add to the model loss)


def switch_gate(
    logits: jax.Array, capacity: int,
    token_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 (Switch) routing. ``logits``: [N, E]. Returns
    ``(dispatch [N, E, C] bool, combine [N, E, C] float, aux_loss)``.

    Position within each expert's buffer is the token's rank among tokens
    routed to that expert; ranks >= capacity are dropped. ``token_mask``
    ([N], 1 = real token): masked (padding) tokens are excluded from
    routing entirely — they consume no expert capacity and do not enter
    the load-balance statistics.
    """
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [N]
    expert_mask = jax.nn.one_hot(expert_idx, E, dtype=probs.dtype)  # [N, E]
    # an all-ones mask reproduces the dense jnp.mean statistics exactly
    tm = (jnp.ones((N,), probs.dtype) if token_mask is None
          else token_mask.astype(probs.dtype))
    expert_mask = expert_mask * tm[:, None]
    n_real = jnp.maximum(jnp.sum(tm), 1.0)
    density = jnp.sum(expert_mask, axis=0) / n_real
    density_proxy = jnp.sum(probs * tm[:, None], axis=0) / n_real
    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e
    aux_loss = E * jnp.sum(density * density_proxy)

    # position of each token in its expert's buffer — integer cumsum:
    # a float cumsum stops representing counts exactly (e.g. bf16 past 256)
    # and colliding buffer positions silently merge tokens
    mask_i = expert_mask.astype(jnp.int32)
    pos_in_expert = (jnp.cumsum(mask_i, axis=0) - 1) * mask_i  # [N, E]
    pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32)  # [N]
    keep = pos < capacity
    gate = jnp.max(probs * expert_mask, axis=-1) * keep  # [N]

    dispatch = (
        expert_mask.astype(bool)
        & keep[:, None]
    )[..., None] & (jax.nn.one_hot(pos, capacity, dtype=jnp.int32).astype(bool))[:, None, :]
    combine = gate[:, None, None] * dispatch.astype(probs.dtype)
    return dispatch, combine, aux_loss


def top2_gate(
    logits: jax.Array, capacity: int,
    token_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-2 (GShard) routing: each token goes to its two highest-prob
    experts, gate weights renormalized over the pair; second-choice tokens
    queue AFTER all first choices in each expert's buffer (GShard's
    priority rule), so overflow drops second choices first. Same
    ``(dispatch [N,E,C], combine [N,E,C], aux_loss)`` and ``token_mask``
    contract as :func:`switch_gate`."""
    N, E = logits.shape
    enforce(E >= 2, f"top2_gate needs >= 2 experts, got {E}")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    idx1 = jnp.argmax(probs, axis=-1)  # [N]
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    # saturated softmax: probs2 can be exactly zero everywhere — argmax then
    # points at expert 0 and a phantom zero-gate route would eat a real
    # capacity slot there; drop the second route entirely in that case
    has2 = (jnp.max(probs2, axis=-1) > 0).astype(probs.dtype)
    mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype) * has2[:, None]
    # an all-ones mask reproduces the dense jnp.mean statistics exactly
    tm = (jnp.ones((N,), probs.dtype) if token_mask is None
          else token_mask.astype(probs.dtype))
    mask1 = mask1 * tm[:, None]
    mask2 = mask2 * tm[:, None]
    n_real = jnp.maximum(jnp.sum(tm), 1.0)
    # aux loss uses FIRST-choice density (GShard eq. for l_aux)
    density = jnp.sum(mask1, axis=0) / n_real
    density_proxy = jnp.sum(probs * tm[:, None], axis=0) / n_real
    aux_loss = E * jnp.sum(density * density_proxy)

    # renormalized pair gates
    g1 = jnp.sum(probs * mask1, axis=-1)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    # buffer positions: first choices rank first, then second choices
    m1_i = mask1.astype(jnp.int32)
    m2_i = mask2.astype(jnp.int32)
    pos1 = (jnp.cumsum(m1_i, axis=0) - 1) * m1_i  # [N, E]
    count1 = jnp.sum(m1_i, axis=0, keepdims=True)  # [1, E]
    pos2 = (jnp.cumsum(m2_i, axis=0) - 1) * m2_i + count1 * m2_i

    def one_route(mask_i, pos_ne, gate):
        pos = jnp.sum(pos_ne, axis=-1).astype(jnp.int32)  # [N]
        keep = pos < capacity
        dispatch = (
            mask_i.astype(bool) & keep[:, None]
        )[..., None] & (
            jax.nn.one_hot(pos, capacity, dtype=jnp.int32).astype(bool)
        )[:, None, :]
        combine = (gate * keep)[:, None, None] * dispatch.astype(probs.dtype)
        return dispatch, combine

    d1, c1 = one_route(m1_i, pos1, g1)
    d2, c2 = one_route(m2_i, pos2, g2)
    return d1 | d2, c1 + c2, aux_loss


# router table: (gate_fn, dispatched routes per token) — capacity scales
# with the route count, so new routers declare it here
_ROUTERS = {"top1": (switch_gate, 1), "switch": (switch_gate, 1), "top2": (top2_gate, 2)}


def moe_ffn(
    x: jax.Array,
    num_experts: int,
    d_ff: int,
    capacity_factor: float = 1.25,
    act=jax.nn.relu,
    name: Optional[str] = None,
    router: str = "top1",
    token_mask: Optional[jax.Array] = None,
) -> MoEOutput:
    """Expert-parallel FFN layer: ``x`` [B, T, D] (or [N, D]) through
    ``num_experts`` independent two-layer FFNs selected by a router —
    ``router='top1'`` (Switch) or ``'top2'`` (GShard pair dispatch).

    Per-expert weights are created as [E, D, d_ff] / [E, d_ff, D] with
    sharding ('expert', None, None) — under a mesh with an ``expert`` axis
    the dispatch einsums compile to all_to_all over ICI.

    ``token_mask`` (same leading shape as ``x`` minus the feature dim,
    1 = real token): ragged batches — padding tokens are excluded from
    routing (no expert capacity consumed, no load-balance contribution)
    and their output rows are zero.
    """
    enforce(router in _ROUTERS, f"unknown router {router!r}; known: {sorted(_ROUTERS)}")
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
        if token_mask is not None and token_mask.ndim == 1:
            token_mask = token_mask[None]
    B, T, D = x.shape
    N = B * T
    tokens = x.reshape(N, D)
    flat_mask = None if token_mask is None else token_mask.reshape(N)
    gate_fn, routes = _ROUTERS[router]
    capacity = max(1, int(math.ceil(routes * N / num_experts * capacity_factor)))

    with name_scope(name or "moe"):
        wg = create_parameter([D, num_experts], x.dtype, name="w_gate")
        w_in = create_parameter(
            [num_experts, D, d_ff], x.dtype, name="w_in",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None, None)),
        )
        b_in = create_parameter(
            [num_experts, d_ff], x.dtype, name="b_in",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None)),
        )
        w_out = create_parameter(
            [num_experts, d_ff, D], x.dtype, name="w_out",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None, None)),
        )
        b_out = create_parameter(
            [num_experts, D], x.dtype, name="b_out",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None)),
        )

    logits = jnp.matmul(tokens, wg, preferred_element_type=jnp.float32)
    dispatch, combine, aux = gate_fn(
        logits.astype(jnp.float32), capacity, token_mask=flat_mask
    )

    # dispatch: [N, E, C] × [N, D] → expert inputs [E, C, D] (all_to_all #1)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), tokens)
    h = act(jnp.einsum("ecd,edf->ecf", expert_in, w_in) + b_in[:, None, :])
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_out) + b_out[:, None, :]
    # combine: [N, E, C] × [E, C, D] → [N, D] (all_to_all #2 + weighted sum)
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)

    out = out.reshape(B, T, D)
    if squeeze:
        out = out[0]
    return MoEOutput(output=out, aux_loss=aux.astype(jnp.float32))

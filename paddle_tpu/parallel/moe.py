"""Mixture-of-Experts with expert parallelism over an ``expert`` mesh axis.

No reference counterpart (SURVEY.md §2.4: parallelism in the reference is
DP + parameter server only) — this is a post-parity TPU extension using the
GShard/Switch dense-dispatch pattern: top-k gating builds a
[tokens, experts, capacity] dispatch tensor, expert FFNs run batched with
their parameters sharded along the ``expert`` axis, and the two dispatch
einsums become all_to_all exchanges when compiled over the mesh.

Everything is fixed-shape: per-expert token capacity bounds the routed
tokens; overflow tokens are dropped (standard Switch behavior) and the
auxiliary load-balancing loss pushes the router toward uniform occupancy.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import ParamAttr, create_parameter, name_scope
from paddle_tpu.parallel import mesh as mesh_mod

__all__ = ["switch_gate", "moe_ffn", "MoEOutput"]


class MoEOutput(NamedTuple):
    output: jax.Array
    aux_loss: jax.Array  # load-balancing loss (add to the model loss)


def switch_gate(
    logits: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 (Switch) routing. ``logits``: [N, E]. Returns
    ``(dispatch [N, E, C] bool, combine [N, E, C] float, aux_loss)``.

    Position within each expert's buffer is the token's rank among tokens
    routed to that expert; ranks >= capacity are dropped.
    """
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [N]
    expert_mask = jax.nn.one_hot(expert_idx, E, dtype=probs.dtype)  # [N, E]

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e
    density = jnp.mean(expert_mask, axis=0)  # fraction routed per expert
    density_proxy = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(density * density_proxy)

    # position of each token in its expert's buffer — integer cumsum:
    # a float cumsum stops representing counts exactly (e.g. bf16 past 256)
    # and colliding buffer positions silently merge tokens
    mask_i = expert_mask.astype(jnp.int32)
    pos_in_expert = (jnp.cumsum(mask_i, axis=0) - 1) * mask_i  # [N, E]
    pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32)  # [N]
    keep = pos < capacity
    gate = jnp.max(probs * expert_mask, axis=-1) * keep  # [N]

    dispatch = (
        expert_mask.astype(bool)
        & keep[:, None]
    )[..., None] & (jax.nn.one_hot(pos, capacity, dtype=jnp.int32).astype(bool))[:, None, :]
    combine = gate[:, None, None] * dispatch.astype(probs.dtype)
    return dispatch, combine, aux_loss


def moe_ffn(
    x: jax.Array,
    num_experts: int,
    d_ff: int,
    capacity_factor: float = 1.25,
    act=jax.nn.relu,
    name: Optional[str] = None,
) -> MoEOutput:
    """Expert-parallel FFN layer: ``x`` [B, T, D] (or [N, D]) through
    ``num_experts`` independent two-layer FFNs selected by a Switch router.

    Per-expert weights are created as [E, D, d_ff] / [E, d_ff, D] with
    sharding ('expert', None, None) — under a mesh with an ``expert`` axis
    the dispatch einsums compile to all_to_all over ICI.
    """
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    B, T, D = x.shape
    N = B * T
    tokens = x.reshape(N, D)
    capacity = max(1, int(math.ceil(N / num_experts * capacity_factor)))

    with name_scope(name or "moe"):
        wg = create_parameter([D, num_experts], x.dtype, name="w_gate")
        w_in = create_parameter(
            [num_experts, D, d_ff], x.dtype, name="w_in",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None, None)),
        )
        b_in = create_parameter(
            [num_experts, d_ff], x.dtype, name="b_in",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None)),
        )
        w_out = create_parameter(
            [num_experts, d_ff, D], x.dtype, name="w_out",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None, None)),
        )
        b_out = create_parameter(
            [num_experts, D], x.dtype, name="b_out",
            attr=ParamAttr(sharding=(mesh_mod.EXPERT_AXIS, None)),
        )

    logits = jnp.matmul(tokens, wg, preferred_element_type=jnp.float32)
    dispatch, combine, aux = switch_gate(logits.astype(jnp.float32), capacity)

    # dispatch: [N, E, C] × [N, D] → expert inputs [E, C, D] (all_to_all #1)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), tokens)
    h = act(jnp.einsum("ecd,edf->ecf", expert_in, w_in) + b_in[:, None, :])
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_out) + b_out[:, None, :]
    # combine: [N, E, C] × [E, C, D] → [N, D] (all_to_all #2 + weighted sum)
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)

    out = out.reshape(B, T, D)
    if squeeze:
        out = out[0]
    return MoEOutput(output=out, aux_loss=aux.astype(jnp.float32))

"""Pipeline parallelism: GPipe-style microbatching over a ``pipe`` mesh axis.

No reference counterpart — the reference's only cross-device strategies are
data parallelism and the parameter server (SURVEY.md §2.4); pipeline
parallelism is a post-parity TPU extension. Design: each device along the
``pipe`` axis owns one stage's parameters; microbatch activations flow
stage-to-stage with ``ppermute`` over the ICI ring inside a ``shard_map``,
the standard TPU pipelining pattern (cf. the scaling-book recipe: shift
buffers with collective-permute, overlap bubbles with n_micro >> n_stages).

The whole schedule is one ``lax.scan`` — XLA overlaps the ppermute with the
next step's stage compute where possible. Differentiable end-to-end: the
transpose of ppermute is the reverse permute, so ``jax.grad`` yields the
1F1B-equivalent backward schedule automatically.

Memory profile: plain GPipe-by-scan keeps every scan step's stage
activations live through the autodiff backward — training memory grows with
``n_micro + n_stages``, which defeats microbatching's purpose at scale.
``pipeline_apply(remat=True)`` wraps each step in ``jax.checkpoint``: the
backward recomputes one step's activations at a time, so live activation
memory is O(one microbatch through one stage) + the scan carries — the
1F1B memory profile — at the standard ~1.33x recompute FLOPs cost.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.core.enforce import enforce
from paddle_tpu.parallel import mesh as mesh_mod

__all__ = ["pipeline_apply", "stack_stage_params", "split_microbatches"]


def stack_stage_params(stage_params: Sequence):
    """Stack per-stage param pytrees along a new leading 'stage' axis:
    the stacked tree is sharded P('pipe', ...) so each pipe device holds
    exactly its own stage's weights."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def split_microbatches(x: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] → [n_micro, B/n_micro, ...]."""
    enforce(
        x.shape[0] % n_micro == 0,
        f"batch {x.shape[0]} not divisible into {n_micro} microbatches",
    )
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    microbatches: jax.Array,
    mesh: Mesh,
    axis: str = mesh_mod.PIPE_AXIS,
    remat: bool = False,
):
    """Run ``y_mb = stage_{S-1}(...stage_0(x_mb))`` for each microbatch with
    stages laid out along the ``axis`` mesh dimension.

    ``stage_fn(params_one_stage, x) -> y`` must be shape-preserving across
    stages (equal widths — pad stages to a common width otherwise, the usual
    pipeline constraint). ``stacked_params`` leaves are [S, ...] (see
    :func:`stack_stage_params`); ``microbatches`` is [n_micro, mb, ...].
    Returns [n_micro, mb, ...] outputs.

    ``remat=True`` checkpoints each scan step: backward activation memory
    stays O(one step) instead of O(n_micro + n_stages) — the 1F1B memory
    profile (see module docstring).
    """
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    n_steps = n_micro + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    run_stage = jax.checkpoint(stage_fn) if remat else stage_fn

    def spmd(params, mbs):
        # per-device view: params leaves [1, ...] (own stage), mbs [n_micro, mb, ...]
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        mb_shape = mbs.shape[1:]

        def step(carry, t):
            cur, outs = carry
            # stage 0 ingests microbatch t (others use the shifted-in value)
            feed = mbs[jnp.minimum(t, n_micro - 1)]
            x = jnp.where(stage == 0, feed, cur)
            y = run_stage(params, x)
            # the last stage completes microbatch t-(S-1) at step t
            done_idx = t - (n_stages - 1)
            is_done = (stage == n_stages - 1) & (done_idx >= 0)
            outs = jax.lax.cond(
                is_done,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(done_idx, 0), 0
                ),
                lambda o: o,
                outs,
            )
            nxt = jax.lax.ppermute(y, axis, fwd)
            return (nxt, outs), None

        init = (
            jnp.zeros(mb_shape, microbatches.dtype),
            jnp.zeros((n_micro,) + mb_shape, microbatches.dtype),
        )
        (_, outs), _ = jax.lax.scan(step, init, jnp.arange(n_steps))
        # outs is populated only on the last stage; psum of the masked value
        # replicates it to every pipe rank (all other ranks contribute zeros)
        outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    from paddle_tpu.core.compat import shard_map

    # microbatch rows shard over the non-pipe axes (params stay replicated
    # there): pipeline composes with data parallelism instead of every
    # data-rank redundantly recomputing the full pipeline
    other_axes = tuple(
        a for a in mesh.axis_names if a != axis and mesh.shape[a] > 1
    )
    other_size = 1
    for a in other_axes:
        other_size *= mesh.shape[a]
    enforce(
        microbatches.shape[1] % other_size == 0,
        f"microbatch size {microbatches.shape[1]} not divisible by the "
        f"non-pipe mesh axes {other_axes} (size {other_size})",
    )
    mb_spec = P(None, other_axes if other_axes else None)
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    return shard_map(
        spmd,
        mesh=mesh,
        in_specs=(param_spec, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )(stacked_params, microbatches)

"""DataParallel — the ParallelExecutor replacement.

Reference: ``fluid.ParallelExecutor`` (``python/paddle/fluid/parallel_executor.py:32``,
C++ ``framework/parallel_executor.cc:134``): replicate the program per GPU,
scale the loss grad by 1/N, allreduce every gradient over NCCL, run via a
threaded SSA-graph executor, split the feed minibatch per device.

TPU-native: ONE pjit-compiled train step over a Mesh. The global batch is
sharded on the ``data`` axis (the per-device split of
``FeedTensorsIntoLocalScopes``), params/optimizer state follow their sharding
specs (replicated by default; model-parallel if annotated), and XLA inserts
the mean-gradient all-reduce over ICI automatically — no op handles, no
ready-queue scheduler, no NCCL group guard.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import Model, Variables
from paddle_tpu.optimizer import Optimizer, OptState, StepOutput
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.sharding import param_shardings, replicated, shard_variables


class DataParallel:
    """Data-parallel (optionally model-parallel-annotated) trainer driver.

    Usage:
        dp = DataParallel(model, optimizer, mesh=make_mesh(data=-1))
        variables, opt_state = dp.init(rng, *example_batch)
        out = dp.step(variables, opt_state, *batch)   # compiled once
    """

    def __init__(
        self,
        model: Model,
        optimizer: Optimizer,
        mesh: Optional[Mesh] = None,
        batch_axis: str = mesh_mod.DATA_AXIS,
        loss_index: int = 0,
        donate: bool = True,
        batch_specs: Optional[Sequence[Optional[P]]] = None,
        zero_shard_optimizer: bool = False,
    ):
        """``batch_specs``: optional per-batch-arg PartitionSpecs overriding
        the default leading-dim data sharding — e.g. shard the sequence dim of
        token inputs over the ``seq`` axis: ``P('data', 'seq')`` (sequence
        parallelism; the activation sharding the reference never had).

        ``zero_shard_optimizer`` (ZeRO-1, TPU-native form): optimizer slot
        buffers of replicated params are declared sharded over the data axis
        (leading dim, where divisible) in the step's in/out_shardings — the
        SPMD partitioner then materializes the reduce-scatter/all-gather
        pattern, cutting optimizer-state HBM by the data-axis size. The
        reference's Reduce+Broadcast BuildStrategy
        (``multi_devices_graph_pass.cc:397-446``) solved the same problem by
        placing each param's update on one owner device."""
        from paddle_tpu.core import config as _cfg

        _cfg.apply_compile_cache()
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else mesh_mod.default_mesh()
        self.batch_axis = batch_axis
        self.loss_index = loss_index
        self.donate = donate
        self.batch_specs = tuple(batch_specs) if batch_specs is not None else None
        self.zero_shard_optimizer = zero_shard_optimizer
        self._step_fn = None
        self._eval_fn = None
        self._ragged_step_fns: dict = {}
        enforce(
            batch_axis in self.mesh.axis_names,
            f"batch axis {batch_axis!r} not in mesh axes {self.mesh.axis_names}",
        )

    # -- setup --------------------------------------------------------------
    def init(self, rng, *example_batch, variables: Optional[Variables] = None) -> Tuple[Variables, OptState]:
        """Initialize (or adopt) variables + optimizer state and place them
        on the mesh (BCastParamsToDevices parity)."""
        if variables is None:
            variables = self.model.init(rng, *example_batch)
        variables = shard_variables(self.mesh, variables, self.model.param_info)
        opt_state = self.optimizer.create_state(variables.params)
        # slots share their param's sharding (or the ZeRO-1 data sharding);
        # step counter replicated
        _, opt_sh = self._state_shardings(variables, opt_state)
        slots = {
            s: {k: jax.device_put(v, opt_sh.slots[s][k]) for k, v in d.items()}
            for s, d in opt_state.slots.items()
        }
        opt_state = OptState(
            step=jax.device_put(opt_state.step, replicated(self.mesh)), slots=slots
        )
        return variables, opt_state

    def _batch_shardings(self, batch: Sequence[Any]):
        if self.batch_specs is not None:
            enforce(
                len(self.batch_specs) == len(batch),
                f"batch_specs has {len(self.batch_specs)} entries for {len(batch)} batch args",
            )
            return tuple(
                NamedSharding(self.mesh, spec if spec is not None else P())
                for spec in self.batch_specs
            )
        return tuple(
            NamedSharding(self.mesh, P(self.batch_axis, *([None] * (jax.numpy.ndim(b) - 1))))
            for b in batch
        )

    def batch_divisible(self, *batch) -> bool:
        """True iff EVERY arg's leading dim divides its own dim-0 shard
        extent (per-arg, mirroring ``_validate_batch`` — a replicated side
        input must not veto the sharded args, and vice versa)."""
        for b, s in zip(batch, self._batch_shardings(batch)):
            shape = jax.numpy.shape(b)
            if not shape:
                continue
            axes = s.spec[0] if len(s.spec) else None
            if shape[0] % self._spec_dim_size(axes) != 0:
                return False
        return True

    def leading_multiple(self, *batch) -> int:
        """The multiple every arg's leading dim must divide to shard on this
        mesh: LCM over each arg's ACTUAL dim-0 sharding extents (batch_specs
        may shard dim 0 over several axes, e.g. P(('data','seq'))) — not the
        data-axis size alone."""
        mult = 1
        for s in self._batch_shardings(batch):
            axes = s.spec[0] if len(s.spec) else None
            mult = math.lcm(mult, self._spec_dim_size(axes))
        return mult

    def _spec_dim_size(self, axes) -> int:
        """Total mesh extent a spec entry shards one dim over (1 if None)."""
        if axes is None:
            return 1
        size = 1
        for a in (axes if isinstance(axes, tuple) else (axes,)):
            size *= self.mesh.shape[a]
        return size

    def _validate_batch(self, batch, shards):
        """Friendly divisibility check of each arg dim against the mesh-axis
        sizes its spec shards it over (beats XLA's uneven-sharding error)."""
        for b, s in zip(batch, shards):
            shape = jax.numpy.shape(b)
            for dim, axes in enumerate(s.spec[: len(shape)]):
                if axes is None:
                    continue
                size = self._spec_dim_size(axes)
                enforce(
                    shape[dim] % size == 0,
                    f"batch arg dim {dim} of size {shape[dim]} not divisible by "
                    f"mesh axes {axes} (size {size}) (static shapes: drop or "
                    "pad the last partial batch)",
                )

    def put_batch(self, *batch):
        """Shard a global host batch across the mesh (the per-device feed
        split of ParallelExecutor.run, parallel_executor.py:173)."""
        shards = self._batch_shardings(batch)
        self._validate_batch(batch, shards)
        return tuple(jax.device_put(b, s) for b, s in zip(batch, shards))

    def pad_batch(self, *batch, to: Optional[int] = None):
        """Pad a (possibly ragged) batch's leading dim up to ``to`` — or the
        next data-axis multiple — by repeating the final row; returns
        ``(padded_batch, valid_mask)`` with a float32 [B_padded] mask that is
        1 for real rows, 0 for padding.

        The TPU-shaped replacement for the reference's data_balance op
        (``details/data_balance_op_handle.cc:154``, inserted at
        ``multi_devices_graph_pass.cc:553-557``), which rebalanced uneven
        per-device splits so every sample trains/evals exactly once: static
        shapes forbid ragged shards, so pad + mask instead and thread the
        mask into the metric (``Trainer.evaluate``). Padding repeats a real
        row (never zeros) so the padded forward stays numerically tame.

        Passing ``to`` = the regular batch size keeps the final batch the
        same shape as every other batch — no extra eval_step compile."""
        import numpy as np

        n = int(jax.numpy.shape(batch[0])[0])
        for b in batch[1:]:
            enforce(
                int(jax.numpy.shape(b)[0]) == n,
                "pad_batch: all batch args must share the leading dim",
            )
        mult = self.leading_multiple(*batch)
        target = to if to is not None else -(-n // mult) * mult
        enforce(
            target >= n and target % mult == 0,
            f"pad_batch: target {target} must be >= batch size {n} and "
            f"divisible by the leading-dim shard multiple {mult} (LCM of "
            "each arg's dim-0 sharding extents)",
        )
        mask = np.zeros((target,), np.float32)
        mask[:n] = 1.0
        if target == n:
            return batch, mask
        padded = tuple(
            np.concatenate(
                [np.asarray(b), np.repeat(np.asarray(b)[-1:], target - n, axis=0)]
            )
            for b in batch
        )
        return padded, mask

    def _state_shardings(self, variables: Variables, opt_state: OptState):
        """Sharding pytrees matching (variables, opt_state): params/slots per
        their annotated specs, everything else replicated. With
        ``zero_shard_optimizer``, slots of replicated params get a leading-dim
        ``data`` sharding instead (ZeRO-1)."""
        p_sh = param_shardings(self.mesh, self.model.param_info, variables.params)
        rep = replicated(self.mesh)

        def slot_sharding(name, slot_val):
            base = p_sh[name]
            actually_sharded = any(a is not None for a in base.spec)
            if not self.zero_shard_optimizer or actually_sharded:
                return base  # model-parallel params keep their own sharding
            n_data = self.mesh.shape[self.batch_axis]
            shape = jax.numpy.shape(slot_val)
            # first dim divisible by the data-axis size carries the shard
            # (a flattened 1/N split is not expressible as a dim sharding)
            for dim, size in enumerate(shape):
                if size % n_data == 0 and size >= n_data:
                    dims = [None] * len(shape)
                    dims[dim] = self.batch_axis
                    return NamedSharding(self.mesh, P(*dims))
            return base

        var_sh = Variables(
            dict(p_sh), jax.tree_util.tree_map(lambda _: rep, variables.state)
        )
        opt_sh = OptState(
            step=rep,
            slots={
                s: {k: slot_sharding(k, v) for k, v in d.items()}
                for s, d in opt_state.slots.items()
            },
        )
        return var_sh, opt_sh

    # -- compiled steps -----------------------------------------------------
    def _build_step_fn(self, variables, opt_state, batch_shardings, donate):
        """Shared jit construction for step/step_ragged: only the batch
        placement and donation differ between the two."""
        raw = self.optimizer.minimize(self.model, loss_index=self.loss_index)

        def positional(variables, opt_state, rng, *b):
            return raw(variables, opt_state, *b, rng=rng)

        var_sh, opt_sh = self._state_shardings(variables, opt_state)
        rep = replicated(self.mesh)
        in_sh = (var_sh, opt_sh, rep) + tuple(batch_shardings)
        # pin outputs too: without this XLA may propagate a different
        # sharding onto updated params (e.g. expert-sharded router
        # weights) and the NEXT step's declared in_shardings would
        # reject them. loss/outputs/finite replicate — FetchOpHandle
        # gathered per-device outputs the same way (fetch_op_handle.cc)
        out_sh = StepOutput(var_sh, opt_sh, rep, rep, rep)
        return jax.jit(
            positional, donate_argnums=donate, in_shardings=in_sh,
            out_shardings=out_sh,
        )

    def step(self, variables: Variables, opt_state: OptState, *batch, rng=None) -> StepOutput:
        """One compiled data-parallel train step. The jit carries explicit
        ``in_shardings`` built from ``batch_specs`` (default: leading-dim
        ``data`` sharding), so a raw host-numpy batch is fed SHARDED across
        the mesh — not silently replicated — matching the per-device feed
        split of ``FeedTensorsIntoLocalScopes``
        (``framework/parallel_executor.cc:330``). ``put_batch`` first is still
        the efficient path (it also validates divisibility)."""
        if self._step_fn is None:
            self._step_fn = self._build_step_fn(
                variables, opt_state, self._batch_shardings(batch),
                donate=(0, 1) if self.donate else (),
            )
        self._validate_batch(batch, self._batch_shardings(batch))
        with self.mesh:
            return self._step_fn(variables, opt_state, rng, *batch)

    # distinct ragged tail shapes a variable-batch reader may produce; the
    # FIFO bound keeps a bucketed reader from accreting compiled steps
    _RAGGED_CACHE_MAX = 8

    def step_ragged(self, variables: Variables, opt_state: OptState, *batch, rng=None) -> StepOutput:
        """Train step for a batch whose leading dim does NOT divide the
        mesh: the batch is fed REPLICATED (every device computes the whole
        small batch redundantly) while params/opt state keep their mesh
        shardings, so the update is numerically identical to a single-device
        step on that batch and the training state never leaves the mesh.

        This completes data_balance parity on the TRAIN side (the reference
        trains on every sample, ``details/data_balance_op_handle.cc:154``):
        ``Trainer.train(..., allow_ragged=True)`` routes the final partial
        batch here. Cost: one extra compile per distinct ragged shape
        (typically one — the dataset's tail size; at most
        ``_RAGGED_CACHE_MAX`` retained) and redundant compute for that
        single batch per epoch; the steady-state path is untouched.
        No donation: the step-fn cache is keyed per shape, and donated
        buffers from a rarely-used variant would invalidate the caller's
        arrays for the common path."""
        key = tuple(jax.numpy.shape(b) for b in batch)
        if key not in self._ragged_step_fns:
            if len(self._ragged_step_fns) >= self._RAGGED_CACHE_MAX:
                self._ragged_step_fns.pop(next(iter(self._ragged_step_fns)))
            rep = replicated(self.mesh)
            self._ragged_step_fns[key] = self._build_step_fn(
                variables, opt_state, tuple(rep for _ in batch), donate=(),
            )
        with self.mesh:
            return self._ragged_step_fns[key](variables, opt_state, rng, *batch)

    def eval_step(self, variables: Variables, *batch, rng=None):
        if self._eval_fn is None:

            def raw(variables, rng, *b):
                out, _ = self.model.apply(variables, *b, rng=rng, is_train=False)
                return out

            var_sh, _ = self._state_shardings(
                variables, OptState(step=jax.numpy.zeros(()), slots={})
            )
            in_sh = (var_sh, replicated(self.mesh)) + self._batch_shardings(batch)
            self._eval_fn = jax.jit(raw, in_shardings=in_sh)
        with self.mesh:
            return self._eval_fn(variables, rng, *batch)

    # -- elastic resize ------------------------------------------------------
    def resize(self, devices: Sequence) -> Mesh:
        """Elastic mesh shrink/regrow: rebuild this driver's mesh over
        ``devices`` — the batch axis absorbs the count change, other axes
        keep their sizes (``mesh.remesh``) — and drop every compiled step
        fn: their in/out_shardings are bound to the old mesh, so the next
        ``step``/``step_ragged``/``eval_step`` re-jits against the new one
        (batch shardings re-derive from the new mesh automatically). The
        caller re-places the training state: restore from a snapshot /
        checkpoint on shrink (the lost device's buffers are gone), or
        :meth:`place_state` on regrow (every source buffer still lives)."""
        devices = list(devices)
        enforce(bool(devices), "resize needs at least one device")
        self.mesh = mesh_mod.remesh(self.mesh, devices, resize_axis=self.batch_axis)
        self._step_fn = None
        self._eval_fn = None
        self._ragged_step_fns.clear()
        return self.mesh

    def state_template(self, variables: Variables, opt_state: OptState):
        """ShapeDtypeStruct pytree of ``(variables, opt_state)`` carrying
        THIS mesh's shardings — the restore target handed to
        ``checkpoint_sharded.load_sharded`` / ``restore_from_snapshot``
        after a :meth:`resize` (the live arrays still carry the OLD mesh's
        shardings and cannot serve as the template)."""
        var_sh, opt_sh = self._state_shardings(variables, opt_state)

        def struct(x, s):
            dtype = getattr(x, "dtype", None)
            if dtype is None:
                dtype = jax.numpy.result_type(x)
            return jax.ShapeDtypeStruct(jax.numpy.shape(x), dtype, sharding=s)

        return jax.tree_util.tree_map(struct, (variables, opt_state), (var_sh, opt_sh))

    def place_state(self, variables: Variables, opt_state: OptState):
        """Re-place an existing state tree onto the CURRENT mesh (regrow
        path: the arrays live on the shrunken mesh and every target device
        is alive, so a direct resharding device_put suffices — no snapshot
        or disk round-trip)."""
        var_sh, opt_sh = self._state_shardings(variables, opt_state)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), (variables, opt_state), (var_sh, opt_sh)
        )

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

"""Device mesh construction and multi-host initialization.

Reference: ``platform/nccl_helper.h:81-126`` (NCCLContextMap: per-device
comms, ncclCommInitAll single-process / ncclCommInitRank multi-node with
nranks = num_trainers × local_devices) and the env-var cluster wiring
(``trainer.py:229-295`` PADDLE_TRAINER_ID etc.).

TPU-native: one ``jax.sharding.Mesh`` names the parallelism axes; XLA routes
collectives over ICI within a slice and DCN across slices based on the mesh's
device layout. ``jax.distributed.initialize`` (coordination service) replaces
the ncclUniqueId gRPC broadcast.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.core import logging as ptlog
from paddle_tpu.core.enforce import enforce

# Canonical axis names (used by layers' default sharding rules)
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"
TP_AXIS = "tp"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap (replaces gen_nccl_id_op + NCCLContextMap
    InitRank). Reads PADDLE_* env vars for drop-in parity with the reference
    cluster wiring, falling back to JAX's own env autodetection."""
    coordinator_address = coordinator_address or os.environ.get("PADDLE_COORDINATOR_ADDR")
    num_processes = num_processes or _env_int("PADDLE_TRAINERS")
    process_id = process_id if process_id is not None else _env_int("PADDLE_TRAINER_ID")
    # forward whatever the caller pinned down; silently dropping an explicit
    # topology (e.g. trainers=2 with no coordinator) would mis-initialize
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    ptlog.info(
        "distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None, **axis_sizes: int) -> Mesh:
    """Build a Mesh from axis name → size. Use -1 for one axis to absorb all
    remaining devices. Example: ``make_mesh(data=-1)`` or
    ``make_mesh(data=2, model=4)``.

    Device order follows jax.devices() (ICI-contiguous on TPU): the LAST mesh
    axis varies fastest, so put the most communication-heavy axis (model/seq)
    last to keep its collectives on the shortest ICI paths — the analogue of
    the reference's choice to put ring allreduce on the fastest interconnect.
    """
    sizes = dict(axes or {})
    sizes.update(axis_sizes)
    enforce(sizes, "make_mesh needs at least one axis")
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    unknown = [k for k, v in sizes.items() if v == -1]
    enforce(len(unknown) <= 1, "only one axis may be -1")
    known = int(np.prod([v for v in sizes.values() if v != -1]))
    if unknown:
        enforce(n % known == 0, f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
    total = int(np.prod(list(sizes.values())))
    enforce(
        total == n,
        f"mesh wants {total} devices ({sizes}) but {n} are available",
    )
    arr = np.array(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def default_mesh() -> Mesh:
    """All local devices on a single data axis (pure DP — the reference
    ParallelExecutor default)."""
    return make_mesh({DATA_AXIS: -1})


def tp_submesh(devices: Sequence) -> Mesh:
    """A single-axis ``tp`` Mesh over an explicit ordered device tuple — the
    program scope of one serving replica group. Device ORDER is the caller's
    contract (ICI-contiguous slices keep the tp collectives on-chip)."""
    devices = list(devices)
    enforce(devices, "tp_submesh needs at least one device")
    return make_mesh({TP_AXIS: len(devices)}, devices=devices)


def partition_devices(tp: int, devices: Optional[Sequence] = None):
    """Slice a device list into ICI-contiguous groups of ``tp`` (the serving
    analogue of NCCLContextMap's per-ring device slicing). Leftover devices
    that don't fill a group are dropped — returns a list of device tuples."""
    devices = list(devices if devices is not None else jax.devices())
    enforce(tp >= 1, f"partition_devices: tp must be >= 1, got {tp}")
    return [
        tuple(devices[i : i + tp])
        for i in range(0, len(devices) - tp + 1, tp)
    ]


def remesh(mesh: Mesh, devices: Sequence, resize_axis: str = DATA_AXIS) -> Mesh:
    """Rebuild ``mesh`` over a different device set (elastic shrink or
    regrow): every axis keeps its size except ``resize_axis``, which
    absorbs the new device count. Axis ORDER is preserved, so existing
    PartitionSpecs keep their meaning on the new mesh. The non-resized
    axes' product must divide the new device count (e.g. model=2 survives
    8 -> 6 devices but not 8 -> 7)."""
    sizes = {name: int(size) for name, size in zip(mesh.axis_names, mesh.devices.shape)}
    enforce(
        resize_axis in sizes,
        f"remesh: axis {resize_axis!r} not in mesh axes {tuple(sizes)}",
    )
    sizes[resize_axis] = -1
    return make_mesh(sizes, devices=devices)

"""Sharding rules: ParamInfo sharding specs → NamedShardings over a Mesh.

Reference mapping: the reference's per-parameter placement decisions lived in
MultiDevSSAGraphBuilder (replicate params everywhere + allreduce grads —
``multi_devices_graph_pass.cc:397-435`` — or Reduce-to-owner + broadcast,
``:437-446``). Here placement is a pure function from parameter metadata to
``jax.sharding.NamedSharding``; XLA materializes the matching collectives.
"""

from __future__ import annotations

import fnmatch
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import ParamInfo, Variables

# A rule table: ordered (glob-pattern, PartitionSpec) pairs, first match wins.
ShardingRules = Sequence[Tuple[str, P]]

# why a sharded dim was dropped to replicated (degraded_dims reasons)
MISSING_AXIS = "missing-axis"
NON_DIVISIBLE = "non-divisible"


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def spec_for(
    param_name: str,
    rules: ShardingRules,
    *,
    ndim: Optional[int] = None,
    fallback: P = P(),
) -> P:
    """Look up the PartitionSpec for ``param_name`` in an ordered rule table
    of ``(glob_pattern, PartitionSpec)`` pairs — first match wins, unknown
    params fall back to ``fallback`` (replicated by default) so a new layer
    never silently inherits a stale layout. When ``ndim`` is given, a matched
    spec naming more dims than the param has rank is an EnforceError: a rule
    written for ``[D, H*dh]`` applied to a 1-d bias is a layout bug, not
    something to truncate quietly."""
    for pattern, spec in rules:
        if fnmatch.fnmatchcase(param_name, pattern):
            if ndim is not None:
                enforce(
                    len(spec) <= ndim,
                    f"spec_for({param_name!r}): rule {pattern!r} names "
                    f"{len(spec)} dims but param has rank {ndim}",
                )
            return spec
    return fallback


def mesh_axis_sizes(mesh: Mesh) -> Dict[str, int]:
    """``{axis_name: size}`` for a mesh — the only mesh fact the degrade
    logic (and the static shard analyzer) needs, so both can run from a
    plain dict without touching devices."""
    return {
        name: int(size) for name, size in zip(mesh.axis_names, mesh.devices.shape)
    }


def degraded_dims(
    axis_sizes: Mapping[str, int], spec: P, shape: Tuple[int, ...]
) -> List[Tuple[int, str, str]]:
    """Which sharded dims :func:`degrade_spec` would drop to replicated,
    as ``(dim_index, axis_name, reason)`` — reason ``MISSING_AXIS`` (the
    documented any-mesh fallback) or ``NON_DIVISIBLE`` (the silent one:
    the axis exists but its size doesn't divide the dim). Pure function of
    the mesh's axis sizes so ``analysis.shard_analysis`` predicts exactly
    what the runtime does."""
    dims = tuple(spec) + (None,) * max(0, len(shape) - len(spec))
    out: List[Tuple[int, str, str]] = []
    for i, (dim_size, axis) in enumerate(zip(shape, dims)):
        if axis is None:
            continue
        n = axis_sizes.get(axis)
        if n is None:
            out.append((i, axis, MISSING_AXIS))
        elif dim_size % n != 0:
            out.append((i, axis, NON_DIVISIBLE))
    return out


def degrade_spec(
    mesh: Mesh,
    spec: P,
    shape: Tuple[int, ...],
    *,
    name: Optional[str] = None,
    quiet: bool = False,
) -> P:
    """Per-dim degradation to replicated: drop a sharded dim when its mesh
    axis is missing or its size doesn't divide the dim (same contract as
    ``param_shardings`` so one model definition runs on any mesh/tp shape).
    The spec is right-padded with None to the array rank.

    A NON-DIVISIBLE drop is the silent surprise — the layout author asked
    for a shard and got full replication — so it logs a ``warn_once`` per
    (param, axis) and counts ``sharding.degraded_total`` (labels: param,
    axis) unless ``quiet``; the static analyzer reports the same set as
    ``shard-silent-degrade``, so runtime counters and static reports
    agree. A missing axis stays silent: that is the documented fallback
    that lets one model definition run on any mesh shape."""
    axis_sizes = mesh_axis_sizes(mesh)
    dropped = degraded_dims(axis_sizes, spec, shape)
    if not quiet:
        from paddle_tpu.core import logging as ptlog
        from paddle_tpu.core import profiler as prof

        label = name or "<unnamed>"
        for dim, axis, reason in dropped:
            if reason != NON_DIVISIBLE:
                continue
            prof.inc_counter("sharding.degraded_total",
                             labels={"param": label, "axis": axis})
            ptlog.warn_once(
                ("sharding.degrade", label, axis, dim),
                "sharding: dim %d (size %d) of %s is not divisible by mesh "
                "axis %r (size %d) — degrading to replicated, losing the "
                "per-device memory split on that dim",
                dim, shape[dim], label, axis, axis_sizes[axis],
            )
    drop = {i for i, _, _ in dropped}
    dims = tuple(spec) + (None,) * max(0, len(shape) - len(spec))
    return P(*(None if i in drop else axis for i, axis in enumerate(dims[: len(shape)])))


def batch_sharding(mesh: Mesh, axis: str = "data", ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def param_shardings(
    mesh: Mesh,
    param_info: Dict[str, ParamInfo],
    params: Dict[str, jax.Array],
) -> Dict[str, NamedSharding]:
    """Per-parameter shardings: honor ParamAttr.sharding tuples (mesh-axis
    name or None per dim); default replicated. Axes not present in the mesh
    degrade to None so the same model runs on any mesh shape (tp spec on a
    dp-only mesh = replicated)."""
    out = {}
    mesh_axes = set(mesh.axis_names)
    for name, p in params.items():
        info = param_info.get(name)
        spec = None
        if info is not None and info.sharding is not None:
            dims = tuple(a if (a in mesh_axes) else None for a in info.sharding)
            # pad/truncate to param rank
            dims = tuple(dims[: p.ndim]) + (None,) * max(0, p.ndim - len(dims))
            spec = P(*dims)
        out[name] = NamedSharding(mesh, spec if spec is not None else P())
    return out


def shard_variables(
    mesh: Mesh,
    variables: Variables,
    param_info: Dict[str, ParamInfo],
) -> Variables:
    """Place a Variables pytree on the mesh according to the sharding rules
    (BCastParamsToDevices parity, reference parallel_executor.cc:249 — except
    'broadcast' is just device_put with a replicated sharding)."""
    p_shards = param_shardings(mesh, param_info, variables.params)
    params = {k: jax.device_put(v, p_shards[k]) for k, v in variables.params.items()}
    state = {k: jax.device_put(v, replicated(mesh)) for k, v in variables.state.items()}
    return Variables(params=params, state=state)

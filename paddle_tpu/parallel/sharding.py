"""Sharding rules: ParamInfo sharding specs → NamedShardings over a Mesh.

Reference mapping: the reference's per-parameter placement decisions lived in
MultiDevSSAGraphBuilder (replicate params everywhere + allreduce grads —
``multi_devices_graph_pass.cc:397-435`` — or Reduce-to-owner + broadcast,
``:437-446``). Here placement is a pure function from parameter metadata to
``jax.sharding.NamedSharding``; XLA materializes the matching collectives.
"""

from __future__ import annotations

import fnmatch
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import ParamInfo, Variables

# A rule table: ordered (glob-pattern, PartitionSpec) pairs, first match wins.
ShardingRules = Sequence[Tuple[str, P]]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def spec_for(
    param_name: str,
    rules: ShardingRules,
    *,
    ndim: Optional[int] = None,
    fallback: P = P(),
) -> P:
    """Look up the PartitionSpec for ``param_name`` in an ordered rule table
    of ``(glob_pattern, PartitionSpec)`` pairs — first match wins, unknown
    params fall back to ``fallback`` (replicated by default) so a new layer
    never silently inherits a stale layout. When ``ndim`` is given, a matched
    spec naming more dims than the param has rank is an EnforceError: a rule
    written for ``[D, H*dh]`` applied to a 1-d bias is a layout bug, not
    something to truncate quietly."""
    for pattern, spec in rules:
        if fnmatch.fnmatchcase(param_name, pattern):
            if ndim is not None:
                enforce(
                    len(spec) <= ndim,
                    f"spec_for({param_name!r}): rule {pattern!r} names "
                    f"{len(spec)} dims but param has rank {ndim}",
                )
            return spec
    return fallback


def degrade_spec(mesh: Mesh, spec: P, shape: Tuple[int, ...]) -> P:
    """Per-dim degradation to replicated: drop a sharded dim when its mesh
    axis is missing or its size doesn't divide the dim (same contract as
    ``param_shardings`` so one model definition runs on any mesh/tp shape).
    The spec is right-padded with None to the array rank."""
    axis_sizes = {
        name: int(size) for name, size in zip(mesh.axis_names, mesh.devices.shape)
    }
    dims = tuple(spec) + (None,) * max(0, len(shape) - len(spec))
    out = []
    for dim_size, axis in zip(shape, dims):
        n = axis_sizes.get(axis) if axis is not None else None
        out.append(axis if (n is not None and dim_size % n == 0) else None)
    return P(*out)


def batch_sharding(mesh: Mesh, axis: str = "data", ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def param_shardings(
    mesh: Mesh,
    param_info: Dict[str, ParamInfo],
    params: Dict[str, jax.Array],
) -> Dict[str, NamedSharding]:
    """Per-parameter shardings: honor ParamAttr.sharding tuples (mesh-axis
    name or None per dim); default replicated. Axes not present in the mesh
    degrade to None so the same model runs on any mesh shape (tp spec on a
    dp-only mesh = replicated)."""
    out = {}
    mesh_axes = set(mesh.axis_names)
    for name, p in params.items():
        info = param_info.get(name)
        spec = None
        if info is not None and info.sharding is not None:
            dims = tuple(a if (a in mesh_axes) else None for a in info.sharding)
            # pad/truncate to param rank
            dims = tuple(dims[: p.ndim]) + (None,) * max(0, p.ndim - len(dims))
            spec = P(*dims)
        out[name] = NamedSharding(mesh, spec if spec is not None else P())
    return out


def shard_variables(
    mesh: Mesh,
    variables: Variables,
    param_info: Dict[str, ParamInfo],
) -> Variables:
    """Place a Variables pytree on the mesh according to the sharding rules
    (BCastParamsToDevices parity, reference parallel_executor.cc:249 — except
    'broadcast' is just device_put with a replicated sharding)."""
    p_shards = param_shardings(mesh, param_info, variables.params)
    params = {k: jax.device_put(v, p_shards[k]) for k, v in variables.params.items()}
    state = {k: jax.device_put(v, replicated(mesh)) for k, v in variables.state.items()}
    return Variables(params=params, state=state)

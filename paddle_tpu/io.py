"""Model persistence: params and inference-model export.

Reference: ``python/paddle/fluid/io.py:89-506`` (save/load_vars/params/
persistables via save/load ops), ``io.py:544`` save_inference_model (prune to
feed/fetch targets + serialize ProgramDesc), ``io.py:670``
load_inference_model; C++ twins ``operators/save_op.cc``/``load_op.cc``.

TPU-native: parameters serialize as a flat name→array archive (.npz, with a
JSON manifest carrying dtype/shape/framework version — the analogue of the
LoDTensor version+header stream, ``lod_tensor.cc`` SerializeToStream). The
inference "program" artifact is a serialized StableHLO module from
``jax.export`` — loadable from Python or from the C++ PJRT serving runtime.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

import jax

try:
    import jax.export  # some versions don't re-export it from jax/__init__
except ImportError:  # pragma: no cover - very old jax; errors surface at use
    pass

from paddle_tpu.core import logging as ptlog
from paddle_tpu.core.enforce import enforce
from paddle_tpu.framework import Model, Variables
from paddle_tpu.version import __version__

_MANIFEST = "manifest.json"
_PARAMS_FILE = "params.npz"
_STATE_FILE = "state.npz"
_HLO_FILE = "program.stablehlo"


def _save_dict(d: Dict[str, jax.Array], path: str) -> None:
    np.savez(path, **{k: np.asarray(v) for k, v in d.items()})


def _load_dict(path: str) -> Dict[str, np.ndarray]:
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def save_params(dirname: str, variables: Variables, filename_prefix: str = "") -> None:
    """save_persistables parity: trainable params + mutable state."""
    os.makedirs(dirname, exist_ok=True)
    _save_dict(variables.params, os.path.join(dirname, filename_prefix + _PARAMS_FILE))
    _save_dict(variables.state, os.path.join(dirname, filename_prefix + _STATE_FILE))
    manifest = {
        "framework_version": __version__,
        "params": {k: {"shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)} for k, v in variables.params.items()},
        "state": {k: {"shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)} for k, v in variables.state.items()},
    }
    with open(os.path.join(dirname, filename_prefix + _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def load_params(dirname: str, filename_prefix: str = "") -> Variables:
    params = _load_dict(os.path.join(dirname, filename_prefix + _PARAMS_FILE))
    state_path = os.path.join(dirname, filename_prefix + _STATE_FILE)
    state = _load_dict(state_path) if os.path.exists(state_path) else {}
    return Variables(params={k: jax.numpy.asarray(v) for k, v in params.items()},
                     state={k: jax.numpy.asarray(v) for k, v in state.items()})


# full reference io surface (reference io.py:28 __all__): persistables =
# params + mutable state here, and save_vars/load_vars take an explicit
# name predicate instead of the reference's Variable-object filters
save_persistables = save_params
load_persistables = load_params


def save_vars(dirname: str, variables: Variables, predicate=None,
              filename_prefix: str = "") -> None:
    """Save the subset of variables whose NAME satisfies ``predicate``
    (reference ``io.save_vars``; default: everything)."""
    pred = predicate or (lambda name: True)
    sub = Variables(
        params={k: v for k, v in variables.params.items() if pred(k)},
        state={k: v for k, v in variables.state.items() if pred(k)},
    )
    save_params(dirname, sub, filename_prefix)


def load_vars(dirname: str, predicate=None, filename_prefix: str = "") -> Variables:
    """Load, keeping only names satisfying ``predicate``
    (reference ``io.load_vars``). Filters the host-side arrays BEFORE any
    device transfer, so selecting one layer out of a multi-GB checkpoint
    moves only that layer to the device."""
    pred = predicate or (lambda name: True)
    params = _load_dict(os.path.join(dirname, filename_prefix + _PARAMS_FILE))
    state_path = os.path.join(dirname, filename_prefix + _STATE_FILE)
    state = _load_dict(state_path) if os.path.exists(state_path) else {}
    return Variables(
        params={k: jax.numpy.asarray(v) for k, v in params.items() if pred(k)},
        state={k: jax.numpy.asarray(v) for k, v in state.items() if pred(k)},
    )


def save_inference_model(
    dirname: str,
    model: Model,
    variables: Variables,
    example_args: Sequence[Any],
    rng=None,
    native: bool = False,
) -> None:
    """Export an inference program (reference save_inference_model): the
    model is traced in eval mode with params baked as constants-free inputs,
    serialized as StableHLO bytes + the weights archive. With ``native=True``
    a C++-predictor artifact is ALSO written (program.txt + weights.bin,
    consumed by ``paddle_tpu.native.NativePredictor`` — the analogue of the
    reference's C++ ``inference/api`` consuming the saved ProgramDesc)."""
    os.makedirs(dirname, exist_ok=True)
    if native:
        from paddle_tpu.native.export import save_native_model

        save_native_model(model, variables, example_args, dirname)

    def infer_fn(params, state, *args):
        out, _ = model.apply(Variables(params, state), *args, rng=rng, is_train=False)
        return out

    exported = jax.export.export(jax.jit(infer_fn))(
        variables.params, variables.state, *example_args
    )
    with open(os.path.join(dirname, _HLO_FILE), "wb") as f:
        f.write(exported.serialize())
    save_params(dirname, variables)
    ptlog.info("inference model saved to %s", dirname)


def load_inference_model(dirname: str) -> Tuple[Callable, Variables]:
    """Returns (callable(params, state, *args), variables). The callable is
    the deserialized compiled program (reference load_inference_model)."""
    with open(os.path.join(dirname, _HLO_FILE), "rb") as f:
        exported = jax.export.deserialize(f.read())
    variables = load_params(dirname)

    def run(*args):
        return exported.call(variables.params, variables.state, *args)

    return run, variables

"""High-level Trainer with event callbacks, auto-checkpoint and auto-resume.

Reference: ``python/paddle/fluid/trainer.py:169`` (Trainer(train_func,
optimizer_func) driving train_loop with Begin/EndEpochEvent +
Begin/EndStepEvent callbacks), ``trainer.py:100`` (CheckpointConfig),
``trainer.py:594,663,763`` (auto-resume on init, save_checkpoint per
epoch/step interval, trainer metadata), ``trainer.py:324`` (cluster-role
wiring from env vars), ``trainer.py:541`` (ParallelExecutor path).

TPU-native: the "program pair" (startup + main) collapses into
``Model.init`` + a compiled train step; the ParallelExecutor path becomes
:class:`paddle_tpu.parallel.DataParallel` over a mesh; PS-mode transpilation
is replaced by multi-host mesh initialization (see
``paddle_tpu.transpiler.distributed``).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import jax
import numpy as np

from paddle_tpu import checkpoint as ckpt_mod
from paddle_tpu import observability as obs
from paddle_tpu import tracing
from paddle_tpu.checkpoint import CheckpointConfig
from paddle_tpu.core import logging as ptlog
from paddle_tpu.core import profiler as prof
from paddle_tpu.core.enforce import EnforceError, enforce
from paddle_tpu.executor import Executor
from paddle_tpu.framework import Model, Variables
from paddle_tpu.observability import mfu as obs_mfu
from paddle_tpu.observability import runlog
from paddle_tpu.optimizer import Optimizer, OptState, StepOutput
from paddle_tpu.resilience import ResilienceConfig, faults
from paddle_tpu.resilience import elastic as elastic_mod
from paddle_tpu.resilience.watchdog import StepWatchdog

__all__ = [
    "Trainer",
    "BeginEpochEvent",
    "EndEpochEvent",
    "BeginStepEvent",
    "EndStepEvent",
    "CheckpointConfig",
    "ResilienceConfig",
]


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id
        # mirrors reference BeginStepEvent.fetch_metrics (trainer.py:158)
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class Trainer:
    """Drive training of a built Model with events + checkpointing.

    ``train_func`` builds and returns the model (a :class:`Model` or a plain
    layer-calling function, which is wrapped); its forward must return the
    loss first. ``optimizer_func`` returns an :class:`Optimizer`.
    """

    def __init__(
        self,
        train_func: Callable[[], Any],
        optimizer_func: Callable[[], Optimizer],
        place=None,
        parallel: bool = False,
        checkpoint_config: Optional[CheckpointConfig] = None,
        rng: int | jax.Array | None = 0,
        parallel_kwargs: Optional[dict] = None,
        prefetch: bool = False,
        resilience: Optional[ResilienceConfig] = None,
        observability: Optional["obs.ObservabilityConfig"] = None,
        watch: Optional[Any] = None,
    ):
        from paddle_tpu.framework import build

        # flags-driven (or explicit) telemetry: exporter + runlog, idempotent
        obs.setup(observability)

        model = train_func()
        self.model = model if isinstance(model, Model) else build(model)
        self.optimizer = optimizer_func()
        self.parallel = parallel
        # extra DataParallel options (mesh=..., zero_shard_optimizer=True, ...)
        self.parallel_kwargs = dict(parallel_kwargs or {})
        # async host->device double buffering of reader batches (the
        # reference's double_buffer reader, operators/reader/buffered_reader.cc)
        self.prefetch = prefetch
        self.checkpoint_cfg = checkpoint_config
        self.rng = rng
        self.place = place
        self.exe = Executor(place)
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._dp = None
        self._step_fn = None
        self.variables: Optional[Variables] = None
        self.opt_state: Optional[OptState] = None
        self.epoch = 0
        self.global_step = 0
        self._last_saved_step = -1
        # preemption-aware save (SURVEY §5.3): SIGTERM during train() is
        # caught at the next step boundary → checkpoint + clean return.
        # True after train() returned early because of a signal.
        self.preempted = False
        self._preempt_requested = False
        # self-healing policy (default from flags: PADDLE_TPU_CHECK_NAN_INF_POLICY
        # etc.; the flags default is "raise", the pre-resilience behavior)
        self.resilience = resilience if resilience is not None else ResilienceConfig.from_flags()
        self.bad_steps = 0  # non-finite steps whose update was dropped
        self.rollbacks = 0  # checkpoint restores triggered by the nan policy
        self._consec_bad = 0
        self._rollbacks_since_good = 0
        self._watchdog: Optional[StepWatchdog] = None
        # elastic supervisor (ResilienceConfig(elastic=True)): created in
        # _ensure_initialized once the mesh exists
        self._elastic: Optional[elastic_mod.ElasticSupervisor] = None
        # -- telemetry (paddle_tpu.observability / paddle_tpu.tracing) -----
        self.goodput = obs_mfu.GoodputTracker()
        self._ema_eps: Optional[float] = None  # EMA examples/sec
        self._step_flops: Optional[float] = None  # XLA cost-model FLOPs/step
        # temporal skew watch over step durations: a step that blows past
        # this trainer's own recent median gets flagged (per-device spatial
        # attribution needs one timing per device, which a single-host
        # pjit step does not expose — the detector accepts external
        # per-device keys when a multi-host launcher has them)
        self._straggler = tracing.StragglerDetector("trainer.step")
        # watch layer: anomaly detectors / SLOs over this trainer's metric
        # streams (step time, MFU, goodput), attached via config
        # (a paddle_tpu.watch.WatchConfig; None = no watching)
        self._watcher = None
        if watch is not None:
            from paddle_tpu import watch as watch_mod

            self._watcher = watch_mod.build(watch)

    # -- init / resume ------------------------------------------------------
    def _ensure_initialized(self, first_batch: Sequence[Any]):
        if self.variables is not None:
            return
        if self.parallel:
            from paddle_tpu.parallel import DataParallel
            from paddle_tpu.parallel.mesh import default_mesh

            kw = dict(self.parallel_kwargs)
            kw.setdefault("mesh", default_mesh())
            self._dp = DataParallel(self.model, self.optimizer, **kw)
            self.variables, self.opt_state = self._dp.init(self.rng, *first_batch)
        else:
            self.variables = self.model.init(self.rng, *first_batch)
            self.opt_state = self.optimizer.create_state(self.variables.params)

        if self.resilience is not None and getattr(self.resilience, "elastic", False):
            enforce(self.parallel, "elastic training requires parallel=True (a mesh to shrink)")
            enforce(
                self.checkpoint_cfg is not None and self.checkpoint_cfg.use_sharded(),
                "elastic training needs CheckpointConfig(sharded=True) — "
                "snapshots/serials are the recovery source",
            )
            from paddle_tpu import checkpoint_sharded as cks

            self._elastic = elastic_mod.ElasticSupervisor(
                self.resilience, devices=list(np.ravel(self._dp.mesh.devices))
            )
            # feed every save's device->host snapshot to the supervisor so
            # recovery has the freshest state without touching disk
            cks.set_snapshot_listener(self._elastic.note_snapshot)

        # auto-resume (reference Trainer.__init__ -> _load_checkpoint,
        # trainer.py:594-629)
        if self.checkpoint_cfg is not None:
            root = self.checkpoint_cfg.checkpoint_dir
            if self.checkpoint_cfg.use_sharded():
                from paddle_tpu import checkpoint_sharded as cks

                if cks.latest_sharded_checkpoint(root):
                    tree = (self.variables, self.opt_state)
                    tree, meta = cks.load_sharded(root, tree)
                    self.variables, self.opt_state = tree
                    self.epoch = int(meta.get("next_epoch", meta.get("epoch", 0)))
                    self.global_step = int(meta.get("step", 0))
                    self._last_saved_step = self.global_step
                    ptlog.vlog(
                        0, "resumed from sharded checkpoint: epoch %d step %d",
                        self.epoch, self.global_step,
                    )
                return
            if ckpt_mod.latest_checkpoint(root):
                tree = (self.variables, self.opt_state)
                tree, meta = ckpt_mod.load_checkpoint(root, tree, self.trainer_id)
                self.variables, self.opt_state = tree
                # next_epoch: epoch+1 for end-of-epoch saves, same epoch for
                # mid-epoch saves (reference restarts the interrupted epoch)
                self.epoch = int(meta.get("next_epoch", meta.get("epoch", 0)))
                self.global_step = int(meta.get("step", 0))
                self._last_saved_step = self.global_step
                ptlog.vlog(
                    0, "resumed from checkpoint: continuing at epoch %d step %d",
                    self.epoch, self.global_step,
                )

    def _compiled_step(self):
        if self._step_fn is None:
            raw = self.optimizer.minimize(self.model)
            self._step_fn = self.exe.prepare(raw, key=("trainer_step", id(self)))
        return self._step_fn

    # -- train loop ---------------------------------------------------------
    def train(
        self,
        num_epochs: int,
        event_handler: Optional[Callable[[Any], None]] = None,
        reader: Optional[Callable[[], Iterable[Tuple]]] = None,
        feed_order=None,  # accepted for API parity; batches are positional
        allow_ragged: bool = False,
    ):
        """Run the training loop (reference ``Trainer.train`` →
        ``_train_by_executor``/``_train_by_parallel_executor``,
        trainer.py:404,541).

        ``allow_ragged``: in parallel mode, a batch whose leading dim does
        not divide the mesh trains through ``DataParallel.step_ragged``
        (replicated batch, sharded params — numerically a single-device
        step) instead of raising, so ``drop_last=False`` readers train on
        EVERY sample, the reference's data_balance guarantee
        (``details/data_balance_op_handle.cc:154``)."""
        enforce(reader is not None, "Trainer.train needs a batched reader")
        self._allow_ragged = allow_ragged
        handler = event_handler or (lambda event: None)
        # a Trainer may be re-entered after a preempted run (in-process
        # resume): stale flags must not end the new loop after one step
        self.preempted = False
        self._preempt_requested = False
        # initialize (and auto-resume) BEFORE choosing the start epoch, so a
        # fresh Trainer with a checkpoint on disk skips completed epochs
        if self.variables is None:
            first = next(iter(reader()), None)
            enforce(first is not None, "reader yielded no batches")
            self._ensure_initialized(first)
        if self._elastic is not None and self._elastic.lost:
            # re-entered after an elastic shrink: the global batch may not
            # divide the shrunken mesh — keep the ragged path open
            self._allow_ragged = True
        prev_handlers = self._install_preemption_handlers()
        res = self.resilience
        if res is not None and res.stall_timeout_s is not None and self._watchdog is None:
            self._watchdog = StepWatchdog(
                res.stall_timeout_s, on_stall=self._on_stall)
        try:
            # while (not for-range): elastic recovery rewinds self.epoch to
            # the restored checkpoint's epoch and restarts it — the same
            # restart-the-interrupted-epoch semantics a cold resume has
            epoch_id = self.epoch
            while epoch_id < num_epochs:
                self.epoch = epoch_id
                handler(BeginEpochEvent(epoch_id))
                # manual next() instead of a for-loop: the wait for the
                # reader is measured and belongs INSIDE the step's trace
                batches = iter(self._batches(reader))
                step_id = -1
                recovered = False
                while True:
                    # stall escalation: between steps (state consistent) ask
                    # the supervisor to probe device liveness; a dead device
                    # recovers through the same shrink path as a raised loss
                    if self._elastic is not None and self._elastic.escalation_due():
                        probe_err = self._elastic.escalate()
                        if probe_err is not None:
                            self._elastic.recover(self, probe_err)
                            recovered = True
                            break
                    t_wait0 = time.perf_counter()
                    batch = next(batches, None)
                    t_wait1 = time.perf_counter()
                    if batch is None:
                        break
                    step_id += 1
                    try:
                        with tracing.start_trace(
                            "trainer.step", epoch=epoch_id,
                        ) as step_span:
                            # the step trace begins where the data wait began
                            step_span.t0_us = t_wait0 * 1e6
                            step_span.set(step=self.global_step)
                            tracing.record_span("trainer.data_wait", t_wait0, t_wait1)
                            begin_ev = BeginStepEvent(epoch_id, step_id)
                            handler(begin_ev)
                            # elastic fault points: a scheduler's advance
                            # preemption notice ("preempt" -> SIGTERM, handled
                            # at the boundary below) and a device vanishing
                            # ("error" -> DeviceLostError, recovered below)
                            faults.inject(
                                faults.PREEMPT_NOTICE, epoch=epoch_id, step=step_id
                            )
                            faults.inject(
                                faults.DEVICE_LOST, epoch=epoch_id, step=step_id
                            )
                            # fault point: "error" raises here (a crashing step),
                            # "nan" forces this step to count as non-finite,
                            # "preempt" delivers SIGTERM (handled at the boundary below)
                            spec = faults.inject(
                                faults.TRAINER_STEP, epoch=epoch_id, step=step_id
                            )
                            t_step = time.perf_counter()
                            if self._watchdog is not None:
                                with self._watchdog.watch(f"epoch {epoch_id} step {step_id}"):
                                    out = self._run_step(batch)
                            else:
                                out = self._run_step(batch)
                            bad = (out.finite is not None and not bool(out.finite)) or (
                                spec is not None and spec.kind == "nan"
                            )
                            if bad:
                                step_span.set(status="bad_step")
                                # charge the wasted step to badput even if the policy
                                # raises below — the accounting outlives the run
                                self.goodput.record_bad(
                                    time.perf_counter() - t_step, "nan_skip")
                                # may raise (policy "raise", or rollback gave up)
                                self._handle_bad_step(epoch_id, step_id)
                                metrics = float("nan") if begin_ev.fetch_metrics else None
                            else:
                                self._consec_bad = 0
                                self._rollbacks_since_good = 0
                                self.variables, self.opt_state = out.variables, out.opt_state
                                self.global_step += 1
                                # honoring fetch_metrics avoids a host sync per step
                                # (reference BeginStepEvent.fetch_metrics, trainer.py:158)
                                metrics = float(out.loss) if begin_ev.fetch_metrics else None
                                self._record_step(
                                    epoch_id, batch, time.perf_counter() - t_step,
                                    metrics)
                            handler(EndStepEvent(epoch_id, step_id, metrics))
                            if self._preempt_requested:
                                with tracing.start_span("trainer.checkpoint",
                                                        reason="preempt"):
                                    self._preemption_save(next_epoch=epoch_id)
                                return
                            with tracing.start_span("trainer.checkpoint"):
                                self._maybe_checkpoint(epoch_id, step=True)
                            if self._elastic is not None:
                                # regrow only at a checkpoint boundary (the
                                # supervisor checks; state is durable there)
                                self._elastic.maybe_regrow(self)
                    except Exception as e:
                        if self._elastic is None or not elastic_mod.is_device_loss(e):
                            raise
                        # device loss: shrink the mesh to the survivors,
                        # restore the freshest snapshot/serial, restart the
                        # interrupted epoch from the restored step
                        self._elastic.recover(self, e)
                        recovered = True
                        break
                if recovered:
                    epoch_id = self.epoch  # the restored manifest's epoch
                    continue
                handler(EndEpochEvent(epoch_id))
                with tracing.start_span("trainer.checkpoint", boundary="epoch"):
                    self._maybe_checkpoint(epoch_id, step=False)
                if self._preempt_requested:
                    # the epoch just COMPLETED — resume must not re-train it
                    self._preemption_save(next_epoch=epoch_id + 1)
                    return
                epoch_id += 1
        finally:
            self._restore_signal_handlers(prev_handlers)
            if self._watchdog is not None:
                self._watchdog.close()
                self._watchdog = None
            if self.checkpoint_cfg is not None and getattr(self.checkpoint_cfg, "async_save", False):
                from paddle_tpu import checkpoint_sharded as cks

                import sys as _sys

                unwinding = _sys.exc_info()[1] is not None
                try:
                    cks.wait_pending_save()  # train() returning => saves durable
                except Exception as e:
                    if not unwinding:  # clean exit: surface it — "train()
                        raise  # returned" must imply a durable save
                    # the loop is already unwinding with its own exception —
                    # log the writer failure instead of masking the cause
                    ptlog.error("async checkpoint writer failed during train() exit: %s", e)

    # -- telemetry (paddle_tpu.observability) -------------------------------
    def _record_step(self, epoch_id: int, batch, dt: float,
                     loss: Optional[float]) -> None:
        """Registry + runlog record for one GOOD step: step-time histogram,
        throughput gauges (instant + EMA), goodput, and MFU from the step
        function's XLA cost-model FLOPs."""
        rows = int(np.shape(batch[0])[0]) if len(batch) else 0
        eps = rows / dt if dt > 0 else 0.0
        self._ema_eps = (
            eps if self._ema_eps is None else 0.9 * self._ema_eps + 0.1 * eps
        )
        prof.inc_counter("trainer.steps_total")
        prof.inc_counter("trainer.examples_total", rows)
        prof.observe("trainer.step_seconds", dt)
        prof.set_gauge("trainer.examples_per_sec", eps)
        prof.set_gauge("trainer.examples_per_sec_ema", self._ema_eps)
        if loss is not None:
            prof.set_gauge("trainer.loss", loss)
        self.goodput.record_good(dt)
        prof.set_gauge("trainer.goodput_frac", self.goodput.goodput_frac())
        if self._step_flops is None:
            self._step_flops = self._compute_step_flops(batch)
        mfu_val = None
        if self._step_flops:
            mfu_val = obs_mfu.mfu(self._step_flops, dt,
                                  device_count=self._device_count())
            if mfu_val is not None:
                prof.set_gauge("trainer.mfu", mfu_val)
        extra = {"mfu": round(mfu_val, 6)} if mfu_val is not None else {}
        runlog.emit(
            "step", step=self.global_step, epoch=epoch_id, loss=loss,
            step_time_s=round(dt, 6), examples_per_sec=round(eps, 3),
            ema_examples_per_sec=round(self._ema_eps, 3), **extra)
        # per-device HBM gauges (device.hbm.*) + temporal straggler watch:
        # a step far above this trainer's own recent median gets flagged
        tracing.sample_device_memory(self._devices_in_use())
        self._straggler.record("step", dt)

    def _devices_in_use(self):
        if self.parallel and self._dp is not None:
            mesh = getattr(self._dp, "mesh", None)
            if mesh is not None:
                return list(np.ravel(mesh.devices))
            return jax.local_devices()
        return [self.exe.device]

    def _compute_step_flops(self, batch) -> float:
        """Model FLOPs of one step from XLA's cost analysis — ``lower()``
        traces without compiling, so this is cheap and exact for the step
        actually being run. 0.0 (MFU suppressed) when the path doesn't
        lower (e.g. step_ragged) or the backend has no cost model."""
        target = self._dp.step if self.parallel else self._step_fn
        if target is None or not hasattr(target, "lower"):
            return 0.0
        try:
            args = [jax.numpy.asarray(b) for b in batch]
            return obs_mfu.lowered_flops(
                target, self.variables, self.opt_state, *args)
        except Exception:
            return 0.0

    def _device_count(self) -> int:
        if self.parallel and self._dp is not None:
            mesh = getattr(self._dp, "mesh", None)
            if mesh is not None:
                return int(mesh.size)
            return jax.local_device_count()
        return 1

    def _on_stall(self, tag: str, elapsed: float) -> None:
        # the watchdog already logged stacks + runlog'd the stall; charge
        # the stalled wall time against goodput here (trainer-side policy)
        self.goodput.record_bad(elapsed, "stall")
        prof.set_gauge("trainer.goodput_frac", self.goodput.goodput_frac())
        if self._elastic is not None:
            # repeated stalls without recovery escalate to a device-liveness
            # probe at the next step boundary (supervisor counts them)
            self._elastic.note_stall()

    # -- self-healing (resilience.ResilienceConfig) -------------------------
    def _handle_bad_step(self, epoch_id: int, step_id: int) -> None:
        """A non-finite step (in-step check_nan_inf, or an injected "nan"
        fault). Policy "raise" keeps the pre-resilience fatal behavior;
        "skip_step" drops the update and continues; "rollback" additionally
        restores the last good checkpoint after ``rollback_after``
        CONSECUTIVE bad steps — and gives up (raises) after
        ``max_rollbacks`` restores with no good step in between."""
        res = self.resilience
        msg = (
            f"NaN/Inf in loss or gradients at epoch {epoch_id} "
            f"step {step_id} (check_nan_inf)"
        )
        if res is None or res.nan_policy == "raise":
            raise EnforceError(msg)
        self.bad_steps += 1
        self._consec_bad += 1
        prof.inc_counter("resilience.bad_steps")
        runlog.emit("nan_skip", step=self.global_step, epoch=epoch_id,
                    consecutive=self._consec_bad)
        ptlog.warning(
            "%s — policy %r: update dropped (%d consecutive bad)",
            msg, res.nan_policy, self._consec_bad,
        )
        if res.nan_policy == "skip_step" or self._consec_bad < res.rollback_after:
            return
        # rollback due
        enforce(
            self.checkpoint_cfg is not None,
            f"nan_policy='rollback' needs a checkpoint_config to restore "
            f"from ({msg})",
        )
        enforce(
            self._rollbacks_since_good < res.max_rollbacks,
            f"giving up after {self._rollbacks_since_good} rollbacks without "
            f"a good step in between ({msg})",
        )
        self._rollback()

    def _rollback(self) -> None:
        """Restore params + optimizer state from the last good checkpoint
        (corrupt serials already fall back inside load_*)."""
        cfg = self.checkpoint_cfg
        root = cfg.checkpoint_dir
        tree = (self.variables, self.opt_state)
        t0 = time.perf_counter()
        rolled_back_from = self.global_step
        if cfg.use_sharded():
            from paddle_tpu import checkpoint_sharded as cks

            cks.wait_pending_save()
            enforce(
                cks.latest_sharded_checkpoint(root) is not None,
                f"rollback: no checkpoint under {root} to restore",
            )
            tree, meta = cks.load_sharded(root, tree)
        else:
            enforce(
                ckpt_mod.latest_checkpoint(root) is not None,
                f"rollback: no checkpoint under {root} to restore",
            )
            tree, meta = ckpt_mod.load_checkpoint(root, tree, self.trainer_id)
        self.variables, self.opt_state = tree
        self.global_step = int(meta.get("step", self.global_step))
        self._last_saved_step = self.global_step
        self.rollbacks += 1
        self._rollbacks_since_good += 1
        self._consec_bad = 0
        prof.inc_counter("resilience.rollbacks")
        restore_s = time.perf_counter() - t0
        self.goodput.record_bad(restore_s, "rollback")
        runlog.emit("rollback", step=self.global_step,
                    rolled_back_from=rolled_back_from,
                    restore_seconds=round(restore_s, 6))
        ptlog.error(
            "rolled back to checkpoint step %d (rollback %d this run)",
            self.global_step, self.rollbacks,
        )

    # -- preemption (SURVEY §5.3 failure detection / recovery) --------------
    def _install_preemption_handlers(self):
        """Catch SIGTERM (the cluster-preemption signal) during the loop;
        the actual save happens at the next step boundary, where params are
        a consistent, fully-materialized tree. Main thread only — signal
        handlers cannot be installed elsewhere."""
        if threading.current_thread() is not threading.main_thread():
            return None

        def on_signal(signum, frame):
            self._preempt_requested = True
            ptlog.vlog(0, "signal %d: checkpoint at next step boundary", signum)

        prev = {}
        for sig in (signal.SIGTERM,):
            try:
                prev[sig] = signal.signal(sig, on_signal)
            except (ValueError, OSError):  # non-main interpreter contexts
                pass
        return prev

    def _restore_signal_handlers(self, prev):
        if not prev:
            return
        for sig, old in prev.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass

    def _preemption_save(self, next_epoch: int):
        """Emergency save on preemption. ``next_epoch`` is the epoch resume
        should start at: the interrupted epoch for a mid-epoch save (it
        restarts, matching the reference's mid-epoch checkpoint semantics),
        epoch+1 when the signal landed on a completed epoch boundary."""
        self.preempted = True
        if self.checkpoint_cfg is not None and getattr(self.checkpoint_cfg, "async_save", False):
            # an async save may be in flight or may have FAILED — "already
            # saved" is only true once the publish is confirmed durable
            from paddle_tpu import checkpoint_sharded as cks

            try:
                cks.wait_pending_save()
            except Exception as e:
                ptlog.warning("pending async checkpoint failed (%s); re-saving", e)
                self._last_saved_step = -1
        if self.checkpoint_cfg is not None and self.global_step != self._last_saved_step:
            self._save_checkpoint({"next_epoch": next_epoch, "preempted": True})
            ptlog.vlog(0, "preempted: saved at epoch %d step %d", self.epoch, self.global_step)
        else:
            ptlog.vlog(
                0, "preempted at epoch %d step %d (no new checkpoint: %s)",
                self.epoch, self.global_step,
                "none configured" if self.checkpoint_cfg is None else "state already saved",
            )

    def _batches(self, reader):
        """One epoch's batch stream, optionally device-prefetched: transfers
        run on a producer thread ``prefetch_depth`` batches ahead, already
        placed with the step's input shardings, so the step never waits on
        host->device copies."""
        for batch in self._raw_batches(reader):
            # fault point: reader-side IO errors / stalls surface here, on
            # the consuming thread (a prefetcher producer re-raises anyway)
            faults.inject(faults.READER_NEXT, epoch=self.epoch, step=self.global_step)
            yield batch

    def _raw_batches(self, reader):
        it = iter(reader())
        if not self.prefetch:
            yield from it
            return
        from paddle_tpu.reader import DevicePrefetcher

        first = next(it, None)
        if first is None:
            return
        if self.parallel:
            shardings = tuple(self._dp._batch_shardings(first))
            if getattr(self, "_allow_ragged", False):
                # a ragged tail batch cannot take the sharded placement —
                # send it to the default device; step_ragged replicates it
                placement = lambda item: (
                    shardings if self._dp.batch_divisible(*item) else None
                )
            else:
                placement = shardings
        else:
            placement = self.exe._device
        yield first
        yield from DevicePrefetcher(it, device=placement)

    def _run_step(self, batch) -> StepOutput:
        if self.parallel:
            if getattr(self, "_allow_ragged", False) and \
                    not self._dp.batch_divisible(*batch):
                with tracing.start_span("trainer.h2d"):
                    args = [jax.numpy.asarray(b) for b in batch]
                with tracing.start_span("trainer.step_compute", ragged=True):
                    return self._dp.step_ragged(
                        self.variables, self.opt_state, *args,
                    )
            with tracing.start_span("trainer.h2d"):
                dev_batch = self._dp.put_batch(*batch)
            with tracing.start_span("trainer.step_compute"):
                return self._dp.step(self.variables, self.opt_state, *dev_batch)
        step_fn = self._compiled_step()
        with tracing.start_span("trainer.h2d"):
            args = [jax.numpy.asarray(b) for b in batch]
        with tracing.start_span("trainer.step_compute"):
            return step_fn(self.variables, self.opt_state, *args)

    def _maybe_checkpoint(self, epoch_id: int, step: bool):
        cfg = self.checkpoint_cfg
        if cfg is None or self.variables is None:
            return
        due = (
            self.global_step % cfg.step_interval == 0
            if step
            else (epoch_id + 1) % cfg.epoch_interval == 0
        )
        if not due:
            return
        # if a step save already captured this state, don't save a duplicate
        # serial — but an epoch boundary must still bump next_epoch in the
        # metadata so resume skips the completed epoch
        if self.global_step == self._last_saved_step:
            if not step:
                if cfg.use_sharded():
                    from paddle_tpu import checkpoint_sharded as cks

                    cks.update_manifest(cfg.checkpoint_dir, {"next_epoch": self.epoch + 1})
                else:
                    ckpt_mod.update_meta(
                        cfg.checkpoint_dir, {"next_epoch": self.epoch + 1}
                    )
            return
        self._save_checkpoint({"next_epoch": self.epoch + (0 if step else 1)})

    def _save_checkpoint(self, extra_meta: dict):
        """Shared sharded/unsharded checkpoint dispatch."""
        cfg = self.checkpoint_cfg
        if cfg.use_sharded():
            from paddle_tpu import checkpoint_sharded as cks

            save = cks.save_sharded_async if getattr(cfg, "async_save", False) else cks.save_sharded
            save(
                cfg.checkpoint_dir,
                (self.variables, self.opt_state),
                step=self.global_step,
                epoch=self.epoch,
                max_num_checkpoints=cfg.max_num_checkpoints,
                extra_meta=extra_meta,
            )
        else:
            ckpt_mod.save_checkpoint(
                cfg.checkpoint_dir,
                (self.variables, self.opt_state),
                step=self.global_step,
                epoch=self.epoch,
                max_num_checkpoints=cfg.max_num_checkpoints,
                trainer_id=self.trainer_id,
                extra_meta=extra_meta,
            )
        self._last_saved_step = self.global_step

    # -- eval / predict -----------------------------------------------------
    def test(self, reader: Callable[[], Iterable[Tuple]], loss_index: int = 0):
        """Average loss over a reader (reference Trainer.test,
        trainer.py:438)."""
        enforce(self.variables is not None, "train (or init) before test")
        losses, count = [], 0
        for batch in reader():
            out, _ = self.model.apply(
                self.variables, *[jax.numpy.asarray(b) for b in batch], is_train=False
            )
            loss = out[loss_index] if isinstance(out, (tuple, list)) else out
            losses.append(float(jax.numpy.mean(loss)))
            count += 1
        return float(np.mean(losses)) if losses else float("nan")

    def evaluate(self, reader: Callable[[], Iterable[Tuple]], metric_fn,
                 pad_to_first: bool = True):
        """Exact test-set metric: every sample counts exactly once, INCLUDING
        a ragged final batch (N % (devices x bs) != 0) — the reference
        guarantees the same via data_balance
        (``details/data_balance_op_handle.cc:154``); here the ragged batch is
        padded to the shard multiple (``DataParallel.pad_batch``) and the
        validity mask zeroes the padding out of the metric.

        ``metric_fn(outputs, *batch) -> [B]`` per-sample values (e.g. a
        correct-prediction indicator); returns their mask-weighted mean.
        ``pad_to_first`` pads every ragged batch to the first batch's size so
        eval compiles exactly once."""
        enforce(self.variables is not None, "train (or init) before evaluate")
        total, count = 0.0, 0
        target = None
        for batch in reader():
            n = int(np.shape(batch[0])[0])
            if self.parallel:
                # a batch LARGER than the latched first-batch size (ragged
                # batch first in the stream) pads to its own multiple
                # instead of tripping pad_batch's target >= n enforce
                to = target if (target is not None and n <= target) else None
                padded, mask = self._dp.pad_batch(*batch, to=to)
                if target is None and pad_to_first:
                    # latch from what pad_batch actually produced — the
                    # multiple-selection rule lives in pad_batch alone
                    target = mask.shape[0]
                out = self._dp.eval_step(self.variables, *padded)
            else:
                padded, mask = batch, np.ones((n,), np.float32)
                out, _ = self.model.apply(
                    self.variables, *[jax.numpy.asarray(b) for b in padded],
                    is_train=False,
                )
            per_sample = np.asarray(metric_fn(out, *padded), np.float64)
            # exact shape: a [B, 1] column would broadcast against the [B]
            # mask into [B, B] and silently inflate the metric
            enforce(
                per_sample.shape == mask.shape,
                f"metric_fn must return one value per row (shape "
                f"{mask.shape}), got shape {per_sample.shape}",
            )
            total += float((per_sample * mask).sum())
            count += int(mask.sum())
        return total / count if count else float("nan")

    def save_params(self, dirname: str):
        """Persist current parameters (reference save_params, io.py:89)."""
        from paddle_tpu import io as io_mod

        enforce(self.variables is not None, "nothing to save: model not initialized")
        io_mod.save_params(dirname, self.variables)

    def stop(self):
        from paddle_tpu import checkpoint_sharded as cks

        # detach OUR snapshot listener (== not `is`: bound methods are
        # recreated per access) so a later trainer's saves don't feed a
        # dead supervisor
        if self._elastic is not None and cks._snapshot_listener == self._elastic.note_snapshot:
            cks.set_snapshot_listener(None)
        try:
            cks.wait_pending_save()  # last async checkpoint must be durable
        finally:
            self.exe.close()  # a failed writer must not leak the executor
